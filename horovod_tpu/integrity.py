"""Silent-data-corruption defense plane: cross-rank integrity voting,
non-finite tripwires, and storage-free rewind-on-spike.

Every robustness layer so far (liveness, coordinated abort, the recovery
ladder, peer replicas, self-healing, driver failover) survives *process*
failures. Nothing guards the *data* plane: a host computing wrong answers
(SDC), a bit flip on the wire, or a non-finite gradient burst propagates
silently through allreduce into every rank's parameters — and then into
the very peer/durable checkpoints the ladder would recover from. This
module is that guard, built on the one invariant the synchronous
data-parallel contract gives us for free: **post-sync replica state is
bitwise identical across ranks**, so any divergence is evidence.

Three mechanisms, all inert until their knob is set:

1. **Cross-rank integrity voting** (``HOROVOD_INTEGRITY_INTERVAL=N``):
   every N-th elastic commit, each rank fingerprints its committed state
   — sha256 of a deterministic byte view of the *replicated* portion
   (params + opt state under ``allreduce``; params under ``sharded``,
   whose opt rows differ per rank by design), plus per-bucket
   finite-count/L2 summaries, plus a per-shard digest of the rank-local
   rows. Shards have no replicated copy to vote against, so their
   coverage is narrower: non-finite summaries, the stuck-shard check
   (shard digest frozen across an interval while every peer's moved),
   and the replica wire's ``checkpoint.payload_digest`` transport
   checksum — finite-garbage SDC confined to a shard is not
   cross-verifiable without redundant computation. The
   record rides the heartbeat the worker already sends; the rendezvous
   server serves the collected set at ``GET /integrity``; the DRIVER
   majority-votes each complete (generation, step) group: with n >= 3
   voters the minority digest names the outlier outright; with exactly 2
   voters a digest majority is impossible, so the tie is broken by
   asymmetric evidence — a record whose summaries carry non-finite
   values, or whose per-bucket L2 drifted from its own previous record
   by ``HOROVOD_INTEGRITY_TIEBREAK`` x more than the peer's did (a bit
   flip moves a fingerprint by e+38; one optimizer step does not). An
   unbreakable tie journals ``ambiguous`` and quarantines nobody. The
   named host is journaled (``integrity_divergence`` + a flight record),
   counted (``hvd_integrity_divergence_total{host}``), its peer-replica
   PUTs are fenced on the KV server (a corrupt shard must never displace
   a good replica), its strike feeds ``elastic/policy.py`` as a fourth
   evidence channel, and — under ``HOROVOD_INTEGRITY_ACTION=drain`` (the
   default) — the driver drains the host through the existing actuators
   and a warm spare joins at the next generation fence.

2. **Non-finite tripwires** (``HOROVOD_NONFINITE_ACTION=warn|skip|abort``):
   a cheap ``isfinite`` reduction fused into the gradient flush
   (``ops/fusion.py`` / ``optimizer.py``). The check runs on the
   *reduced* gradients — rank-identical under allreduce by construction,
   made rank-identical by one scalar ``psum`` under the sharded/fsdp
   halves — so ``skip`` drops the step's update (and keeps the optimizer
   state un-advanced) identically on every rank with no extra
   coordination. Detections are counted (``hvd_nonfinite_steps_total``)
   and journaled (``nonfinite_step``) from a host callback;
   ``abort`` additionally arms the coordinated abort so the elastic
   ladder restores the last commit everywhere.

3. **Rewind-on-spike** (``HOROVOD_LOSS_SPIKE_SIGMA=S``): an EWMA
   mean/variance detector over the training loss
   (:func:`observe_loss`). A loss more than S sigma above trend (or
   non-finite) posts the coordinated abort and raises
   :class:`~horovod_tpu.exceptions.LossSpikeError` into the elastic
   loop, which rewinds to the last commit **storage-free** — the local
   snapshot, completed through the peer rung when the state is
   shard-local (``PeerShardedState``). A skip-ahead counter
   (:func:`consume_skip_ahead`) lets the training loop advance past the
   poison batch instead of replaying it, and
   ``HOROVOD_REWIND_MAX`` consecutive spike-rewinds without a landed
   commit breaks the storm (the spike then rides the normal ladder).
   Feed :func:`observe_loss` a rank-identical loss (the allreduced mean
   every logging path already computes) so every rank rewinds together.

Stdlib-only at import (numpy is imported lazily inside the fingerprint
math) and jax-free throughout, so the rendezvous KV server — which
serves ``GET /integrity`` and votes before any framework init — imports
this module directly.
"""

from __future__ import annotations

import hashlib
import math
import os
import threading
import time
from typing import Any, Mapping

from . import faults
from . import metrics as _metrics
from .utils.env import get_float, get_int
from .utils.logging import get_logger

#: Wire/record format version (records carry it for forward evolution).
RECORD_VERSION = 1

#: Summary buckets per fingerprint: contiguous leaf runs, so a corrupt
#: leaf localizes to a bucket without per-leaf record bloat.
SUMMARY_BUCKETS = 8


# ---------------------------------------------------------------------------
# Knobs
# ---------------------------------------------------------------------------


def check_interval() -> int:
    """``HOROVOD_INTEGRITY_INTERVAL``: fingerprint every N-th commit;
    0/unset disables the whole voting plane (bit-for-bit inert)."""
    return get_int("HOROVOD_INTEGRITY_INTERVAL", 0)


def enabled() -> bool:
    return check_interval() > 0


def integrity_action() -> str:
    """``HOROVOD_INTEGRITY_ACTION``: what the driver does with a named
    divergent host — ``drain`` (default: quarantine + drain through the
    existing actuators) or ``warn`` (journal/count/fence only; the
    policy strike channel can still drain it)."""
    action = os.environ.get("HOROVOD_INTEGRITY_ACTION", "drain").strip()
    return action if action in ("warn", "drain") else "drain"


def confirmations() -> int:
    """Consecutive divergent votes naming the same host before the
    driver acts (default 1 — one bad fingerprint is already a bitwise
    proof, not a noisy analog signal)."""
    return max(1, get_int("HOROVOD_INTEGRITY_CONFIRMATIONS", 1))


def tiebreak_ratio() -> float:
    """Two-voter tie-break: the outlier's summary drift must exceed the
    peer's by this factor, or the vote stays ambiguous."""
    return get_float("HOROVOD_INTEGRITY_TIEBREAK", 4.0)


def loss_spike_sigma() -> float | None:
    """``HOROVOD_LOSS_SPIKE_SIGMA``: sigmas above the EWMA loss trend at
    which :func:`observe_loss` trips a rewind; unset/invalid disables."""
    raw = os.environ.get("HOROVOD_LOSS_SPIKE_SIGMA", "").strip()
    if not raw:
        return None
    try:
        v = float(raw)
    except ValueError:
        return None
    return v if v > 0 else None


def rewind_max() -> int:
    """Consecutive spike-rewinds without a landed commit before the
    storm breaker stops special-casing spikes (0 disables the cap)."""
    return get_int("HOROVOD_REWIND_MAX", 3)


# Integrity records group-match by (generation, step) against replica
# records and the KV fences: both planes MUST derive the generation the
# same way, so the derivation lives in one place (peercheck's, which the
# replica wire already stamps with).
from .peercheck import _env_generation  # noqa: E402


# ---------------------------------------------------------------------------
# Fingerprints (worker side; lazy numpy)
# ---------------------------------------------------------------------------


def _iter_leaves(tree):
    """Deterministic, jax-free leaf walk: dicts by sorted key, lists and
    tuples (optax NamedTuples included) in order. Yields (path, leaf)."""
    if isinstance(tree, Mapping):
        for k in sorted(tree, key=str):
            yield from _iter_leaves(tree[k])
    elif isinstance(tree, (list, tuple)):
        for item in tree:
            yield from _iter_leaves(item)
    else:
        yield tree


def _is_float_dtype(dtype) -> bool:
    """Floating to the defense plane: numpy floats PLUS the ml_dtypes
    customs (bfloat16, float8_*) jax states actually use on TPU — those
    register as custom dtypes that fail ``np.issubdtype(.., floating)``,
    which silently blinded the summaries and the grad.corrupt injector
    on the most common accelerator dtype. The float64 cast downstream
    handles them all. Name-based so the check stays import-free when
    ml_dtypes is absent."""
    import numpy as np

    if np.issubdtype(dtype, np.floating):
        return True
    return getattr(dtype, "name", "").startswith(("bfloat16", "float8"))


def _is_numeric_dtype(dtype) -> bool:
    import numpy as np

    if _is_float_dtype(dtype) or np.issubdtype(dtype, np.integer):
        return True
    return getattr(dtype, "name", "") in ("int4", "uint4")


def _leaf_arrays(tree):
    """The tree's numeric leaves as numpy arrays (order-stable)."""
    import numpy as np

    out = []
    for leaf in _iter_leaves(tree):
        if leaf is None:
            continue
        try:
            arr = np.asarray(leaf)
            opaque = bool(arr.dtype.hasobject)
        except Exception:  # noqa: BLE001 — unconvertible leaf
            opaque = True
        if opaque:
            # Opaque leaf (callable, custom object — np.asarray yields
            # an object array whose tobytes() would be the in-process
            # POINTER, different on every rank; reprs embed addresses
            # too). Digest the type identity only: contents are not
            # byte-comparable, but the digest stays rank-deterministic
            # so identical states keep identical digests.
            tag = f"{type(leaf).__module__}.{type(leaf).__qualname__}"
            out.append(np.frombuffer(tag.encode(), dtype=np.uint8))
            continue
        out.append(arr)
    return out


def digest_tree(tree, leaves=None) -> str:
    """Hex sha256 of the tree's deterministic byte view (shape + dtype
    headers guard against reshuffle collisions). Identical trees —
    which the synchronous sync contract guarantees for replicated state
    across ranks — produce identical digests on every rank. ``leaves``
    (a precomputed ``_leaf_arrays`` result) lets ``make_record`` share
    one tree walk between the digest and the summaries."""
    import numpy as np

    h = hashlib.sha256()
    for arr in (leaves if leaves is not None else _leaf_arrays(tree)):
        h.update(f"{arr.dtype!s}:{arr.shape!r};".encode())
        h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()


def summarize_tree(tree, buckets: int = SUMMARY_BUCKETS,
                   leaves=None) -> list[dict]:
    """Per-bucket summaries: contiguous leaf runs with element count,
    finite count, and L2 norm — the cheap numeric shadow of the digest.
    Finite counts catch NaN/Inf bursts outright; the L2 is the two-voter
    tie-break's drift signal (a flipped exponent bit moves it by orders
    of magnitude; one optimizer step does not)."""
    import numpy as np

    arrays = [a for a in (leaves if leaves is not None
                          else _leaf_arrays(tree))
              if _is_numeric_dtype(a.dtype)]
    if not arrays:
        return []
    k = max(1, min(int(buckets), len(arrays)))
    out = []
    per = -(-len(arrays) // k)
    for i in range(0, len(arrays), per):
        run = arrays[i:i + per]
        n = int(sum(a.size for a in run))
        finite = 0
        sq = 0.0
        # Chunked accumulation: a whole-leaf float64 cast plus a masked
        # fancy-index would transiently triple a multi-GB state's RAM
        # on every fingerprint; 1M-element chunks bound the transients
        # to a few MB regardless of state size.
        chunk = 1 << 20
        for a in run:
            flat = a.reshape(-1)
            for lo in range(0, flat.size, chunk):
                # Corrupted payloads legitimately carry signaling-NaN
                # bit patterns; the cast must summarize them, not warn.
                with np.errstate(invalid="ignore", over="ignore"):
                    cf = flat[lo:lo + chunk].astype(np.float64,
                                                    copy=False)
                    m = np.isfinite(cf)
                    nfin = int(m.sum())
                    finite += nfin
                    if nfin != cf.size:
                        cf = np.where(m, cf, 0.0)
                    sq += float(np.dot(cf, cf))
        out.append({"n": n, "finite": finite,
                    "l2": float(math.sqrt(sq))})
    return out


class _IntegrityState:
    """Per-process integrity bookkeeping (thread-safe)."""

    def __init__(self):
        self.lock = threading.Lock()
        self.commit_count = 0
        self.fingerprints = 0
        self.latest: dict | None = None
        self.prev_summary: dict | None = None
        self.nonfinite_detections = 0
        self.nonfinite_burst: set[int] = set()
        self.rewinds = 0
        self.skip_ahead = 0


_state = _IntegrityState()


def make_record(params, opt_state, step: int, sync_mode: str = "allreduce",
                shard=None, rank: int | None = None,
                host: str | None = None,
                generation: int | None = None) -> dict:
    """One rank's integrity fingerprint of a committed state.

    ``sync_mode`` decides what the cross-rank-comparable ``digest``
    covers: everything under ``allreduce`` (fully replicated), the
    params only under ``sharded`` (the ZeRO-1 opt rows differ per rank
    by design), and nothing under ``fsdp`` (params live sharded — the
    per-shard digest is the verification there, exactly the
    ``checkpoint.payload_digest`` contract the replica wire already
    enforces). ``shard`` is the rank-local portion (opt row / fsdp param
    row) covered by ``shard_digest``."""
    if sync_mode == "allreduce":
        voted = summarized = (params, opt_state)
    elif sync_mode == "sharded":
        voted = params
        summarized = params
    else:  # fsdp: nothing replicated to vote on
        voted = None
        summarized = (params, shard)
    # One tree walk for both digest and summaries when they cover the
    # same tree (allreduce and sharded modes) — the walk materializes
    # every leaf, a real cost on multi-GB states.
    voted_leaves = _leaf_arrays(voted) if voted is not None else None
    summary_leaves = (voted_leaves if summarized is voted
                      else _leaf_arrays(summarized))
    record = {
        "v": RECORD_VERSION,
        "rank": int(rank if rank is not None
                    else int(os.environ.get("HOROVOD_RANK", "0") or 0)),
        "host": str(host if host is not None
                    else os.environ.get("HOROVOD_HOSTNAME", "localhost")),
        "generation": int(generation if generation is not None
                          else _env_generation()),
        "step": int(step),
        "sync_mode": str(sync_mode),
        "digest": (digest_tree(voted, leaves=voted_leaves)
                   if voted is not None else None),
        "shard_digest": (digest_tree(shard) if shard is not None else None),
        "summaries": summarize_tree(summarized, leaves=summary_leaves),
        "t": time.time(),
    }
    return record


def maybe_fingerprint(params, opt_state, step: int,
                      sync_mode: str = "allreduce",
                      shard=None) -> dict | None:
    """The commit hook: every ``HOROVOD_INTEGRITY_INTERVAL``-th call,
    fingerprint the committed state and stage the record for the next
    heartbeat. Unarmed (interval 0) this is one int compare — the
    bit-for-bit-inert contract. Never raises: the defense plane must not
    take down the training it defends."""
    interval = check_interval()
    if interval <= 0:
        return None
    try:
        with _state.lock:
            _state.commit_count += 1
            prev = _state.prev_summary
        # Gate on the CALLER's commit counter, not the process-local
        # call count: vote_latest needs one record per rank at the SAME
        # (generation, step), and a replacement rank's fresh process
        # counter would phase-shift its fingerprints off the survivors'
        # forever — silently disarming the voting plane after the first
        # membership change. The state layer keeps `step` world-aligned
        # across re-forms (PeerShardedState's replica baseline,
        # TpuState's sync broadcast), so gating on it keeps every rank
        # fingerprinting the same commits.
        if int(step) % interval != 0:
            return None
        record = make_record(params, opt_state, step, sync_mode=sync_mode,
                             shard=shard)
        # The previous interval's digest/L2 ride along: the two-voter
        # tie-break compares each rank's drift against its OWN trend,
        # and shipping it inline spares the server a history store.
        record["prev"] = prev
        with _state.lock:
            _state.latest = record
            _state.prev_summary = {
                "digest": record["digest"],
                "step": record["step"],
                # The generation rides along so a vote that back-dates
                # the quarantine from this prev (corruption predating
                # the group) can condemn the right generation's replica
                # records even across a world re-form.
                "generation": record["generation"],
                # The shard digest feeds the fsdp stuck-shard check: a
                # rank whose shard never moved across an interval while
                # every peer's did is wedged on (possibly corrupt)
                # state.
                "shard_digest": record["shard_digest"],
                "l2": [b["l2"] for b in record["summaries"]],
                "finite": [b["finite"] for b in record["summaries"]],
            }
            _state.fingerprints += 1
        _metrics.INTEGRITY_CHECKS.inc()
        return record
    except Exception as e:  # noqa: BLE001 — defense must not break training
        get_logger().warning("integrity: fingerprint failed: %s", e)
        return None


def heartbeat_payload() -> dict | None:
    """The latest staged record, for the worker heartbeat piggyback
    (None when the plane is unarmed or nothing is staged yet)."""
    if not enabled():
        return None
    with _state.lock:
        return _state.latest


def maybe_corrupt_snapshot(saved: dict) -> dict:
    """The ``grad.corrupt`` SDC injector's call site: with the fault
    armed, flip seeded bits in the committed snapshot's first float leaf
    of each state entry (params / param rows / opt rows) — host memory
    corrupting a replica copy, exactly the failure only cross-rank
    voting can see (the digests stay self-consistent). One fault hit per
    commit; unarmed this is a single dict lookup. Mutates and returns
    ``saved``."""
    if not faults.armed(faults.GRAD_CORRUPT):
        return saved
    import numpy as np

    targets = []
    for key in ("params", "param_row", "row", "opt_state"):
        tree = saved.get(key)
        if tree is None:
            continue
        for arr in _leaf_arrays(tree):
            if _is_float_dtype(arr.dtype) and arr.size:
                targets.append((key, arr))
                break
    if not targets:
        faults.fire(faults.GRAD_CORRUPT)  # count the hit anyway
        return saved
    blob = b"".join(np.ascontiguousarray(a).tobytes() for _, a in targets)
    mutated = faults.corrupt_payload(faults.GRAD_CORRUPT, blob)
    if mutated == blob:
        return saved
    offset = 0
    for key, arr in targets:
        nbytes = arr.nbytes
        new = np.frombuffer(mutated[offset:offset + nbytes],
                            dtype=arr.dtype).reshape(arr.shape).copy()
        offset += nbytes
        _replace_first_float_leaf(saved, key, new)
    get_logger().error(
        "integrity: grad.corrupt injected — committed snapshot mutated "
        "(%d bytes across %d entries)", len(blob), len(targets))
    return saved


def _replace_first_float_leaf(saved: dict, key: str, new) -> None:
    """Install ``new`` over the first float leaf of ``saved[key]``,
    rebuilding the (host-numpy) containers along the path."""
    import numpy as np

    def rebuild(tree):
        done = False

        def walk(node):
            nonlocal done
            if done:
                return node
            if isinstance(node, Mapping):
                out = {}
                for k in sorted(node, key=str):
                    out[k] = walk(node[k])
                # preserve original (possibly unsorted) key order
                return {k: out[k] for k in node}
            if isinstance(node, (list, tuple)):
                items = [walk(x) for x in node]
                if isinstance(node, tuple):
                    try:
                        return type(node)(*items)  # NamedTuple
                    except TypeError:
                        return tuple(items)
                return items
            if node is None:
                return node
            try:
                arr = np.asarray(node)
            except Exception:  # noqa: BLE001
                return node
            if _is_float_dtype(arr.dtype) and arr.size:
                done = True
                return new
            return node

        return walk(tree)

    saved[key] = rebuild(saved[key])


# ---------------------------------------------------------------------------
# Voting (driver / KV-server side; pure stdlib)
# ---------------------------------------------------------------------------


def vote(records: Mapping[Any, Mapping]) -> dict:
    """Majority-vote one complete (generation, step) group of records.

    Returns ``{"divergent", "outlier_rank", "outlier_host", "ambiguous",
    "method", "digests", "voters"}``. With n >= 3 comparable digests the
    minority is named outright; with exactly 2, asymmetric evidence
    breaks the tie — non-finite summary values first, then per-bucket L2
    drift vs each rank's own previous record
    (:func:`tiebreak_ratio`). With no comparable digests (fsdp) the
    only shard signals are non-finite summaries and the stuck-shard
    check (``shard_digest`` unchanged vs the rank's own prev while
    every peer's moved). No majority or an unbreakable tie →
    ``divergent`` with ``ambiguous=True`` (or a clean non-divergent
    verdict when everything agrees)."""
    comparable = {r: rec for r, rec in records.items()
                  if rec.get("digest")}
    out = {
        "divergent": False,
        "ambiguous": False,
        "outlier_rank": None,
        "outlier_host": None,
        "method": None,
        "voters": len(records),
        "digests": {str(r): rec.get("digest")
                    for r, rec in records.items()},
    }
    # Non-finite summaries are damning on their own, digest or not: a
    # record whose committed state carries NaN/Inf while every peer's is
    # clean names its host outright (the fsdp path's voting signal).
    bad_finite = [
        (r, rec) for r, rec in records.items()
        if any(b.get("finite", b.get("n", 0)) < b.get("n", 0)
               for b in rec.get("summaries") or ())
    ]
    if bad_finite and len(bad_finite) < len(records):
        r, rec = bad_finite[0]
        out.update(divergent=True, method="nonfinite",
                   outlier_rank=rec.get("rank", r),
                   outlier_host=rec.get("host"))
        if len(bad_finite) > 1:
            out.update(ambiguous=True, outlier_rank=None,
                       outlier_host=None)
        return out
    if len(comparable) < 2:
        # No replicated digest to compare (fsdp world, or lone rank).
        # shard_digest still carries one sound cross-rank signal: a
        # training step always changes a rank's shard, so a rank whose
        # shard digest is IDENTICAL to its own previous record's while
        # every peer's moved is stuck on (possibly corrupt) state.
        stuck, moved = [], 0
        for r, rec in records.items():
            sd = rec.get("shard_digest")
            prev = rec.get("prev")
            psd = (prev.get("shard_digest")
                   if isinstance(prev, Mapping) else None)
            if not sd or not psd:
                return out  # incomplete evidence: no verdict
            if sd == psd:
                stuck.append((r, rec))
            else:
                moved += 1
        if len(stuck) == 1 and moved >= 1:
            r, rec = stuck[0]
            out.update(divergent=True, method="stuck_shard",
                       outlier_rank=rec.get("rank", r),
                       outlier_host=rec.get("host"))
        return out
    digests: dict[str, list] = {}
    for r, rec in comparable.items():
        digests.setdefault(rec["digest"], []).append((r, rec))
    if len(digests) == 1:
        return out  # bitwise agreement — the expected steady state
    out["divergent"] = True
    counts = sorted(((len(v), d) for d, v in digests.items()),
                    reverse=True)
    if len(comparable) >= 3 and counts[0][0] > counts[1][0]:
        minority = [rv for d, group in digests.items()
                    if d != counts[0][1] for rv in group]
        if len(minority) == 1:
            r, rec = minority[0]
            out.update(method="majority",
                       outlier_rank=rec.get("rank", r),
                       outlier_host=rec.get("host"))
            return out
    if len(comparable) == 2:
        # Two voters: no majority exists. Break the tie by drift vs each
        # rank's OWN previous record — a corrupted fingerprint moves its
        # L2 by orders of magnitude; a healthy optimizer step moves it a
        # little. Valid ONLY when both ranks' previous records agreed
        # bitwise: disagreeing prev digests prove the corruption
        # predates this group (a stuck-at-corrupt state drifts ~zero vs
        # its own already-corrupt prev, which would name the HEALTHY
        # rank), so the verdict must stay ambiguous — no host named on
        # evidence that cannot tell who diverged.
        prev_digests = {
            (rec.get("prev") or {}).get("digest")
            if isinstance(rec.get("prev"), Mapping) else None
            for _r, rec in comparable.items()}
        if len(prev_digests) != 1 or None in prev_digests:
            out["ambiguous"] = True
            return out
        drifts = []
        for r, rec in comparable.items():
            d = _summary_drift(rec)
            if d is None:
                drifts = []
                break
            drifts.append((d, r, rec))
        if drifts:
            drifts.sort(reverse=True)
            worst, best = drifts[0][0], drifts[-1][0]
            if worst > max(best, 1e-12) * tiebreak_ratio():
                _, r, rec = drifts[0]
                out.update(method="drift",
                           outlier_rank=rec.get("rank", r),
                           outlier_host=rec.get("host"))
                return out
    out["ambiguous"] = True
    return out


def _summary_drift(record: Mapping) -> float | None:
    """Relative per-bucket L2 drift of a record vs its own inlined
    previous summary; None when no previous record rides along."""
    prev = record.get("prev")
    if not isinstance(prev, Mapping):
        return None
    prev_l2 = prev.get("l2")
    cur = [b.get("l2", 0.0) for b in record.get("summaries") or ()]
    if not isinstance(prev_l2, (list, tuple)) or len(prev_l2) != len(cur):
        return None
    drift = 0.0
    for now, was in zip(cur, prev_l2):
        try:
            drift += abs(float(now) - float(was)) / (abs(float(was)) + 1e-9)
        except (TypeError, ValueError):
            return None
    return drift


def vote_latest(records_by_rank: Mapping[Any, Mapping],
                world_size: int) -> tuple[tuple[int, int], dict] | None:
    """Vote the newest COMPLETE (generation, step) group: one record per
    rank 0..world_size-1 at the same group key. Incomplete groups are
    skipped — a vote over a partial world could name a rank whose record
    merely had not arrived yet. Returns ((generation, step), vote) or
    None."""
    groups: dict[tuple[int, int], dict] = {}
    for key, rec in records_by_rank.items():
        if not isinstance(rec, Mapping):
            continue
        try:
            group = (int(rec.get("generation", 0)), int(rec["step"]))
            rank = int(rec.get("rank", key))
        except (KeyError, TypeError, ValueError):
            continue
        groups.setdefault(group, {})[rank] = rec
    for group in sorted(groups, reverse=True):
        members = groups[group]
        if len(members) >= int(world_size) and set(
                range(int(world_size))) <= set(members):
            return group, vote({r: members[r]
                                for r in range(int(world_size))})
    return None


# ---------------------------------------------------------------------------
# Non-finite tripwire (host side of the traced guard)
# ---------------------------------------------------------------------------


def note_nonfinite(action: str, ok, idx) -> None:
    """Host target of the traced tripwire's debug callback.

    Called once per LOCAL shard per step (once per process in
    multi-process worlds, once per device in single-controller
    multi-device ones), with the shard's axis index as a value. A step
    is counted once by burst detection: a repeated index means a new
    step's callbacks began (each step delivers every local shard's
    distinct index exactly once), so only the first call of a burst
    counts — best-effort under cross-device callback interleaving, which
    is fine for a counter. ``abort`` additionally arms the coordinated
    abort so every blocking site raises into the elastic ladder. Never
    raises."""
    try:
        idx = int(idx)
        with _state.lock:
            if idx in _state.nonfinite_burst:
                _state.nonfinite_burst = {idx}     # new step's burst
            else:
                _state.nonfinite_burst.add(idx)
            first_of_burst = len(_state.nonfinite_burst) == 1
            if first_of_burst and not bool(ok):
                _state.nonfinite_detections += 1
                n = _state.nonfinite_detections
        if not first_of_burst or bool(ok):
            return
        _metrics.NONFINITE_STEPS.inc(action=action)
        _metrics.event("nonfinite_step", action=action, detections=n)
        get_logger().warning(
            "integrity: non-finite reduced gradients detected "
            "(action=%s, detection #%d)", action, n)
        if action == "abort":
            from . import abort

            # post, not trigger_local: the callback delivery is
            # best-effort per rank (fusion swallows emission failures),
            # so a rank whose callback was dropped needs the KV
            # abort/<generation> record to unblock within one
            # abort-poll interval — exactly the observe_loss contract.
            # Without a rendezvous endpoint post still arms locally.
            abort.post(
                "non-finite gradients (HOROVOD_NONFINITE_ACTION=abort)")
    except Exception:  # noqa: BLE001 — the tripwire must not take down
        pass           # the step it is guarding


# ---------------------------------------------------------------------------
# Rewind-on-spike
# ---------------------------------------------------------------------------


class LossSpikeDetector:
    """EWMA mean/variance spike detector over the training loss.

    ``observe`` folds one loss sample; it returns True (and stages one
    skip-ahead batch) when the sample sits more than ``sigma`` standard
    deviations above the EWMA trend after ``warmup`` samples — or is
    non-finite, which trips immediately once armed. The spike sample is
    NOT folded into the trend (the rewind discards it; folding it would
    desensitize the detector to the replay). Pure python so the unit
    tests drive it without a framework."""

    def __init__(self, sigma: float, alpha: float | None = None,
                 warmup: int | None = None):
        self.sigma = float(sigma)
        self.alpha = (get_float("HOROVOD_LOSS_SPIKE_ALPHA", 0.1)
                      if alpha is None else float(alpha))
        self.warmup = (get_int("HOROVOD_LOSS_SPIKE_WARMUP", 8)
                       if warmup is None else int(warmup))
        self.mean = 0.0
        self.var = 0.0
        self.samples = 0

    def observe(self, loss: float) -> bool:
        loss = float(loss)
        if not math.isfinite(loss):
            # Non-finite loss: instant spike once ANYTHING was observed.
            # It still counts as observed (not folded into the trend):
            # a stream that is non-finite from the very first sample
            # must trip on the second, not stay disarmed forever.
            tripped = self.samples >= 1
            self.samples += 1
            return tripped
        if self.samples >= self.warmup:
            dev = loss - self.mean
            if dev > self.sigma * math.sqrt(max(self.var, 0.0)) + 1e-12:
                return True
        delta = loss - self.mean
        self.mean += self.alpha * delta
        self.var = (1.0 - self.alpha) * (
            self.var + self.alpha * delta * delta)
        self.samples += 1
        return False


_detector: LossSpikeDetector | None = None
_detector_lock = threading.Lock()


def _get_detector() -> LossSpikeDetector | None:
    global _detector
    sigma = loss_spike_sigma()
    if sigma is None:
        return None
    with _detector_lock:
        if _detector is None or _detector.sigma != sigma:
            _detector = LossSpikeDetector(sigma)
        return _detector


def observe_loss(loss) -> None:
    """Feed one (rank-identical) loss sample to the spike detector.

    Unarmed (``HOROVOD_LOSS_SPIKE_SIGMA`` unset) this is one env read.
    On a spike: stages one skip-ahead batch, posts the coordinated abort
    (so every rank — including ones fed a per-rank loss — leaves its
    collectives and rewinds together), and raises
    :class:`~horovod_tpu.exceptions.LossSpikeError`, which the elastic
    loop converts into a storage-free rewind to the last commit."""
    det = _get_detector()
    if det is None:
        return
    if not det.observe(loss):
        return
    from . import abort
    from .exceptions import LossSpikeError

    with _state.lock:
        _state.skip_ahead += 1
    msg = (f"loss spike: {float(loss):.6g} is more than "
           f"{det.sigma:g} sigma above the EWMA trend "
           f"(mean {det.mean:.6g}, std "
           f"{math.sqrt(max(det.var, 0.0)):.6g})")
    get_logger().error("integrity: %s — rewinding to the last commit",
                       msg)
    try:
        abort.post(f"loss-spike rewind: {msg}")
    except Exception:  # noqa: BLE001 — local rewind still happens
        pass
    raise LossSpikeError(msg)


def consume_skip_ahead() -> int:
    """Batches the training loop should skip after a rewind (the poison
    batch must not replay). Returns the staged count and zeroes it."""
    with _state.lock:
        n = _state.skip_ahead
        _state.skip_ahead = 0
    return n


def record_rewind(reason: str, generation: int | None = None,
                  consecutive: int = 1, detail: str = "") -> None:
    """Count + journal one storage-free rewind (called by the elastic
    runner when it converts a :class:`LossSpikeError` into a rewind)."""
    with _state.lock:
        _state.rewinds += 1
    _metrics.REWINDS.inc(reason=reason)
    _metrics.event("rewind", generation=generation, reason=reason,
                   consecutive=consecutive, detail=detail[:300])


# ---------------------------------------------------------------------------
# Observability surfaces
# ---------------------------------------------------------------------------


def flight_summary() -> dict | None:
    """Integrity-plane state for flight-record dumps: the latest staged
    fingerprint (digest + group, not the full summaries) plus the
    tripwire/rewind counters. None when the plane never engaged."""
    try:
        with _state.lock:
            latest = _state.latest
            nonfinite = _state.nonfinite_detections
            rewinds = _state.rewinds
        if latest is None and not nonfinite and not rewinds:
            return None
        out: dict = {"nonfinite_detections": nonfinite,
                     "rewinds": rewinds}
        if latest is not None:
            out["latest"] = {
                "generation": latest.get("generation"),
                "step": latest.get("step"),
                "digest": latest.get("digest"),
                "shard_digest": latest.get("shard_digest"),
                "sync_mode": latest.get("sync_mode"),
            }
        return out
    except Exception:  # noqa: BLE001 — postmortems are best-effort
        return None


def summary() -> dict:
    """Process-local integrity ledger for ``profiler.summary()``."""
    with _state.lock:
        return {
            "armed": enabled(),
            "interval": check_interval(),
            # checks = fingerprints actually computed (the
            # hvd_integrity_checks_total definition); commits = every
            # commit seen, most of which the interval gate passes over.
            "checks": _state.fingerprints,
            "commits": _state.commit_count,
            "latest_digest": (_state.latest or {}).get("digest"),
            "nonfinite_detections": _state.nonfinite_detections,
            "rewinds": _state.rewinds,
            "skip_ahead_pending": _state.skip_ahead,
        }


def reset_for_testing() -> None:
    global _detector
    with _state.lock:
        _state.commit_count = 0
        _state.fingerprints = 0
        _state.latest = None
        _state.prev_summary = None
        _state.nonfinite_detections = 0
        _state.nonfinite_burst = set()
        _state.rewinds = 0
        _state.skip_ahead = 0
    with _detector_lock:
        _detector = None
