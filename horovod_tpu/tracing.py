"""Cross-rank step tracing: clock-aligned spans, skew attribution, and the
flight recorder.

The per-process Chrome timeline (:mod:`horovod_tpu.timeline`) answers
"what did THIS process just do"; the metrics plane (PR 5) answers "what
are the cluster's aggregate numbers". Neither answers the straggler
question ROADMAP item 3 needs: *which rank made the collective slow, and
what was it doing instead*. This module is that sensor layer:

1. **Span API**: :func:`span` records host-observable phases — ``step``,
   ``forward``/``backward`` (where separable), per-collective dispatch,
   ``optimizer_update``, ``param_allgather`` — into a per-rank
   :class:`StepTracer` (ring buffer of the last K steps) AND dual-emits
   onto the per-process Chrome timeline. Factory train steps open a step
   scope per call (``parallel/data_parallel.py``); eager collective
   dispatch (``ops/collective_ops.py``) records per-op spans.
2. **Clock alignment**: :class:`ClockSync` piggybacks NTP-style offset
   estimation on the heartbeat PUTs the elastic worker already sends —
   the server stamps its wall clock into the 200 reply, and the worker's
   send/receive timestamps bound the offset to ±RTT/2. Every rank thus
   carries a server-relative offset ± error bound, shipped with its
   spans so the merge can put all ranks on one timebase.
3. **Trace shipping**: every ``HOROVOD_TRACE_SAMPLE``-th step's spans are
   posted (bounded payload, dedicated background thread, 1-attempt/2s
   client) to ``PUT /trace/<host>`` on the rendezvous KV server, whose
   ``GET /timeline`` serves the merged, offset-corrected Chrome/Perfetto
   JSON with one track per rank and whose ``/metrics`` gains
   ``hvd_collective_skew_seconds{rank}`` / ``hvd_straggler_score{host}``
   from :func:`compute_skew` (see ``runner/http/kv_server.py``).
4. **Flight recorder**: the ring buffer of the last K steps' spans is
   dumped through the lifecycle journal (``flight_record`` event) on
   abort-consume, stall shutdown, deadman exit, and SIGTERM drain — so
   every rung of the recovery ladder leaves a postmortem of what each
   rank was doing when the world wedged.

Knobs (see docs/timeline.md):

- ``HOROVOD_TRACE_SAMPLE`` — ship every Nth step's spans (0 = default =
  record locally only, never ship; shipping syncs the sampled step).
- ``HOROVOD_TRACE_RING_STEPS`` — flight-recorder depth K (default 8).
- ``HOROVOD_TRACE_MAX_SPANS`` — per-step span cap (default 64; overflow
  is counted, never silently unbounded).

Stdlib-only and jax-free by design: the KV server (driver side, before
any framework init) imports :func:`compute_skew` from here.
"""

from __future__ import annotations

import collections
import json
import os
import socket
import threading
import time
from typing import Any, Callable, Mapping

from .utils.env import get_float, get_int

#: KV scope trace payloads ship to (``PUT /trace/<host>``).
TRACE_SCOPE = "trace"


def sample_every() -> int:
    """Ship every Nth step's spans to the rendezvous KV (0 disables
    shipping; local ring recording is always on)."""
    return get_int("HOROVOD_TRACE_SAMPLE", 0)


def ring_steps() -> int:
    """Flight-recorder depth: how many recent steps the ring keeps."""
    return max(1, get_int("HOROVOD_TRACE_RING_STEPS", 8))


def max_spans_per_step() -> int:
    return max(1, get_int("HOROVOD_TRACE_MAX_SPANS", 64))


def _rank() -> str:
    return os.environ.get("HOROVOD_RANK", "0") or "0"


def _host() -> str:
    return os.environ.get("HOROVOD_HOSTNAME", "") or socket.gethostname()


# ---------------------------------------------------------------------------
# Clock alignment
# ---------------------------------------------------------------------------


class ClockSync:
    """NTP-style offset of this process's wall clock vs the rendezvous
    server's, estimated from heartbeat round trips.

    For each exchange the worker records ``t_send``/``t_recv`` on its own
    wall clock and the server stamps ``t_server`` into the reply; the
    classic bound is::

        offset = t_server - (t_send + t_recv) / 2    (server - local)
        error  = (t_recv - t_send) / 2               (half the RTT)

    The estimate is the minimum-error sample over a sliding window (the
    standard NTP minimum-RTT filter: queueing delay only ever inflates
    the RTT, so the tightest round trip is the most truthful). ``clock``
    is injectable so tests can simulate a skewed rank.
    """

    WINDOW = 16

    def __init__(self, clock: Callable[[], float] = time.time):
        self._clock = clock
        self._lock = threading.Lock()
        self._samples: collections.deque = collections.deque(
            maxlen=self.WINDOW)

    def now(self) -> float:
        """This process's wall clock (the one spans are stamped with)."""
        return self._clock()

    def observe(self, t_send: float, t_recv: float,
                t_server: float) -> None:
        rtt = max(float(t_recv) - float(t_send), 0.0)
        sample = (rtt / 2.0,
                  float(t_server) - (float(t_send) + float(t_recv)) / 2.0)
        with self._lock:
            self._samples.append(sample)
        try:
            from . import metrics

            metrics.CLOCK_OFFSET.set(self.offset())
            err = self.error()
            if err is not None:
                metrics.CLOCK_ERROR.set(err)
        except Exception:  # noqa: BLE001 — observability is best-effort
            pass

    def _best(self):
        with self._lock:
            if not self._samples:
                return None
            return min(self._samples, key=lambda s: s[0])

    def offset(self) -> float:
        """Best estimate of (server wall clock − local wall clock), or
        0.0 before any exchange (merge degrades to raw local clocks)."""
        best = self._best()
        return best[1] if best is not None else 0.0

    def error(self) -> float | None:
        """± bound on :meth:`offset` (half the best sample's RTT), or
        None before any exchange."""
        best = self._best()
        return best[0] if best is not None else None

    def synced(self) -> bool:
        return self._best() is not None


# ---------------------------------------------------------------------------
# Step tracer + flight-recorder ring
# ---------------------------------------------------------------------------


class StepRecord:
    """One step's spans. ``synced=True`` means the step was blocked on
    (``block_until_ready``) so its duration is the real step time, not
    just async dispatch; ``ship`` marks it for posting to the KV."""

    __slots__ = ("step", "kind", "t_start", "spans", "dropped",
                 "synced", "ship", "dur")

    def __init__(self, step: int, kind: str, t_start: float):
        self.step = step
        self.kind = kind
        self.t_start = t_start
        self.spans: list[dict] = []
        self.dropped = 0
        self.synced = False
        self.ship = False
        self.dur: float | None = None

    def as_dict(self) -> dict:
        out = {
            "step": self.step,
            "kind": self.kind,
            "t": self.t_start,
            "synced": self.synced,
            "spans": list(self.spans),
        }
        if self.dur is not None:
            out["dur"] = self.dur
        if self.dropped:
            out["dropped_spans"] = self.dropped
        return out


class StepTracer:
    """Per-process span recorder: a ring of the last K steps (the flight
    recorder) plus the currently open step and spans. Recording is cheap
    (one dict append under a lock) and always on; only shipping and the
    sampled-step sync are gated by ``HOROVOD_TRACE_SAMPLE``."""

    def __init__(self, clock_sync: ClockSync | None = None):
        self.clock = clock_sync or ClockSync()
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(
            maxlen=ring_steps())
        self._current: StepRecord | None = None
        self._ambient: StepRecord | None = None
        self._open: dict[int, tuple[str, str, float]] = {}
        self._next_open = 0
        self._step_count = 0
        self._dispatch_seq: dict[str, int] = {}

    # -- span recording -----------------------------------------------------

    def begin_span(self, name: str, cat: str) -> int:
        """Register an in-flight span (so a wedge shows up in the flight
        record as an OPEN span with its age). Returns a token for
        :meth:`end_span`."""
        t0 = self.clock.now()
        with self._lock:
            token = self._next_open
            self._next_open += 1
            self._open[token] = (name, cat, t0)
        return token

    def end_span(self, token: int,
                 args: Mapping[str, Any] | None = None) -> None:
        now = self.clock.now()
        with self._lock:
            opened = self._open.pop(token, None)
            if opened is None:
                return
            name, cat, t0 = opened
            self._record_locked(name, cat, t0, now - t0, args)

    def record(self, name: str, cat: str, t_start: float, dur: float,
               args: Mapping[str, Any] | None = None) -> None:
        """Record a completed span directly (bench's derived phase
        medians use this)."""
        with self._lock:
            self._record_locked(name, cat, t_start, dur, args)

    def record_dispatch(self, name: str, cat: str = "collective",
                        unique: bool = False) -> None:
        """Record a host-plane collective DISPATCH as a zero-duration
        span, suffixed with a per-name sequence number.

        The native runtime (``horovod_tpu/runtime``) calls this at every
        enqueue — the funnel all torch/TF-surface and hierarchical-leg
        collectives pass through — so eager host-plane workloads feed the
        cross-rank skew attribution, not just compiled factory steps.
        The sequence suffix makes each *instance* of a repeated name
        (``allreduce.weight`` every step) its own matched group: ranks
        run the host plane in lockstep program order, so ``name#k`` pairs
        the k-th dispatch across ranks and the skew gauges track the
        CURRENT lateness instead of the first instance ever seen. The
        counter resets with :meth:`rebase` at world join, keeping
        survivors and replacements aligned within a generation.

        ``unique=True`` marks a name that is already one-per-call
        (auto-generated ``op.N`` counters — lockstep-identical across
        ranks, so they self-match): it is recorded as-is, keeping the
        seq map bounded by the *named* collective vocabulary instead of
        growing one permanent entry per auto-named enqueue.
        """
        t0 = self.clock.now()
        with self._lock:
            if unique:
                self._record_locked(name, cat, t0, 0.0, None)
                return
            seq = self._dispatch_seq.get(name, 0) + 1
            self._dispatch_seq[name] = seq
            self._record_locked(f"{name}#{seq}", cat, t0, 0.0, None)

    def _record_locked(self, name, cat, t_start, dur, args) -> None:
        target = self._current
        if target is None:
            # Spans outside any step (eager scripting) collect into an
            # ambient pseudo-step rotated into the ring when full.
            if self._ambient is None:
                self._ambient = StepRecord(-1, "eager", t_start)
            target = self._ambient
        if len(target.spans) >= max_spans_per_step():
            target.dropped += 1
        else:
            sp = {"name": name, "cat": cat,
                  "t": round(float(t_start), 6),
                  "dur": round(float(dur), 6)}
            if args:
                sp["args"] = dict(args)
            target.spans.append(sp)
        if (target is self._ambient
                and len(target.spans) >= max_spans_per_step()):
            # Full ambient window: rotate it into the ring so eager-only
            # scripts produce bounded records too (same cap as steps).
            self._ring.append(self._ambient.as_dict())
            self._ambient = None

    # -- step scopes ----------------------------------------------------------

    def step_scope(self, kind: str = "step") -> "_StepScope":
        return _StepScope(self, kind)

    def _begin_step(self, kind: str) -> StepRecord:
        with self._lock:
            self._step_count += 1
            if self._ambient is not None and self._ambient.spans:
                self._ring.append(self._ambient.as_dict())
            self._ambient = None
            rec = StepRecord(self._step_count, kind, self.clock.now())
            self._current = rec
            return rec

    def _end_step(self, rec: StepRecord) -> None:
        rec.dur = self.clock.now() - rec.t_start
        with self._lock:
            if self._current is rec:
                self._current = None
            rec.spans.insert(0, {
                "name": rec.kind, "cat": "step",
                "t": round(rec.t_start, 6),
                "dur": round(rec.dur, 6),
                "args": {"synced": rec.synced},
            })
            self._ring.append(rec.as_dict())
        if rec.synced:
            # Synced steps carry REAL wall time, so they feed the
            # attribution plane: phase decomposition, exposed-comm and
            # MFU gauges, the local regression sentinel. Un-synced
            # steps time async dispatch only and would report garbage.
            try:
                from . import attribution

                attribution.note_step(rec.as_dict())
            except Exception:  # noqa: BLE001 — attribution is advisory
                pass
        if rec.ship:
            ship_async(self.payload())

    def sample_due(self, step: int) -> bool:
        n = sample_every()
        return n > 0 and step % n == 0

    def steps_recorded(self) -> int:
        with self._lock:
            return self._step_count

    def rebase(self) -> None:
        """Zero the step counter (ring kept — flight history across a
        recovery is the point of the recorder). Called when a worker
        (re-)joins a world epoch: skew matching keys spans on
        (generation, step, name), and SPMD lockstep keeps counters
        rank-aligned only if every member of a generation counts from
        the same join point — a survivor at step 500 next to a
        replacement at step 1 would otherwise never match."""
        with self._lock:
            self._step_count = 0
            self._dispatch_seq.clear()

    # -- snapshots ------------------------------------------------------------

    def ring_snapshot(self) -> list[dict]:
        with self._lock:
            out = list(self._ring)
            if self._ambient is not None and self._ambient.spans:
                out.append(self._ambient.as_dict())
            return out

    def flight_snapshot(self) -> dict:
        """The flight record: the ring plus any still-open spans (a
        wedged collective shows up here with its age, which is exactly
        the postmortem question)."""
        now = self.clock.now()
        with self._lock:
            open_spans = [
                {"name": name, "cat": cat, "t": round(t0, 6),
                 "age_s": round(now - t0, 6)}
                for name, cat, t0 in self._open.values()
            ]
            current = (self._current.as_dict()
                       if self._current is not None else None)
        out = {"steps": self.ring_snapshot(), "open_spans": open_spans}
        if current is not None:
            out["current_step"] = current
        return out

    def payload(self) -> dict:
        """The wire format shipped to ``PUT /trace/<host>`` and merged by
        ``GET /timeline`` / ``GET /criticalpath``. When the model's
        FLOPs-per-step were declared (``hvd.set_model_flops_per_step``)
        they ride along so the driver's critical-path merge can report
        per-rank MFU."""
        from . import metrics

        out = {
            "rank": _rank(),
            "host": _host(),
            "generation": metrics.default_generation(),
            "clock_offset_s": round(self.clock.offset(), 6),
            "clock_error_s": self.clock.error(),
            "t_ship": self.clock.now(),
            "steps": self.ring_snapshot(),
        }
        try:
            from . import attribution

            flops, peak = attribution.model_flops()
            if flops:
                out["model_flops_per_step"] = flops
            if peak:
                out["peak_flops_per_rank"] = peak
        except Exception:  # noqa: BLE001 — attribution is advisory
            pass
        return out


class _StepScope:
    def __init__(self, tracer: StepTracer, kind: str):
        self._tracer = tracer
        self._kind = kind
        self.rec: StepRecord | None = None

    def __enter__(self) -> StepRecord:
        self.rec = self._tracer._begin_step(self._kind)
        return self.rec

    def __exit__(self, exc_type, exc, tb):
        if exc is not None and self.rec is not None:
            self.rec.spans.append({
                "name": f"error:{getattr(exc_type, '__name__', 'Exception')}",
                "cat": "error",
                "t": round(self._tracer.clock.now(), 6), "dur": 0.0,
            })
        self._tracer._end_step(self.rec)
        return False


# ---------------------------------------------------------------------------
# Singletons
# ---------------------------------------------------------------------------

# RLock: get_tracer() materializes the clock sync under the same lock.
_lock = threading.RLock()
_clock_sync: ClockSync | None = None
_tracer: StepTracer | None = None


def clock_sync() -> ClockSync:
    global _clock_sync
    with _lock:
        if _clock_sync is None:
            _clock_sync = ClockSync()
        return _clock_sync


def get_tracer() -> StepTracer:
    global _tracer
    with _lock:
        if _tracer is None:
            _tracer = StepTracer(clock_sync())
        return _tracer


def reset_for_testing() -> None:
    """Fresh tracer + clock sync (re-reads the ring/sampling env)."""
    global _tracer, _clock_sync, _last_hb_ship
    with _lock:
        _tracer = None
        _clock_sync = None
    with _ship_lock:
        _last_hb_ship = 0.0


def record_span(name: str, cat: str, t_start: float, dur: float,
                args: Mapping[str, Any] | None = None) -> None:
    get_tracer().record(name, cat, t_start, dur, args)


class span:
    """Record a host-observable phase: ``with tracing.span('forward',
    'phase'): ...``.

    Triple-emits: a span into the step tracer (ring + shipping), a
    Chrome-trace event on the per-process host timeline, and a
    ``jax.profiler.TraceAnnotation`` range (both via
    :class:`horovod_tpu.timeline.activity`). Never raises — tracing must
    not take down training.
    """

    def __init__(self, name: str, cat: str = "phase",
                 args: Mapping[str, Any] | None = None):
        self.name = name
        self.cat = cat
        self.args = args
        self._token: int | None = None
        self._act = None

    def __enter__(self):
        try:
            from .timeline import activity

            self._act = activity(self.name, self.cat, self.args)
            self._act.__enter__()
        except Exception:  # noqa: BLE001
            self._act = None
        try:
            self._token = get_tracer().begin_span(self.name, self.cat)
        except Exception:  # noqa: BLE001
            self._token = None
        return self

    def __exit__(self, *exc):
        if self._token is not None:
            try:
                get_tracer().end_span(self._token, self.args)
            except Exception:  # noqa: BLE001
                pass
        if self._act is not None:
            try:
                self._act.__exit__(*exc)
            except Exception:  # noqa: BLE001
                pass
        # Memory-observatory watermark hook: every span close folds the
        # current resident total into its phase's high-water mark
        # (memory.note_phase never raises and is cheap — cached cells
        # plus two guarded supplier polls).
        try:
            from . import memory

            memory.note_phase(self.name, self.cat)
        except Exception:  # noqa: BLE001 — tracing must not fail
            pass
        return False


# ---------------------------------------------------------------------------
# Trace shipping (worker -> rendezvous KV)
# ---------------------------------------------------------------------------

_ship_lock = threading.Lock()
_ship_pending: dict | None = None
_ship_event = threading.Event()
_ship_thread: threading.Thread | None = None


def _ship_generation() -> int | None:
    """Generation stamp for trace PUTs: the elastic worker context's
    JOINED generation when one exists (the same source the heartbeat and
    abort clients fence with), else the launcher env, else None
    (static/manual launches stay unfenced)."""
    from .runner.elastic import worker as elastic_worker

    ctx = elastic_worker._context
    if ctx is not None:
        return ctx.joined_version
    from .runner.http.kv_server import env_generation

    return env_generation()


def _shipper_loop() -> None:
    global _ship_pending
    from .utils.logging import get_logger

    while True:
        _ship_event.wait()
        with _ship_lock:
            payload = _ship_pending
            _ship_pending = None
            _ship_event.clear()
        if payload is None:
            continue
        try:
            # Endpoint re-read per payload: elastic re-formations (and
            # tests) can move the rendezvous server; a cached client
            # would strand every later ship on a dead port.
            addr = os.environ.get("HOROVOD_RENDEZVOUS_ADDR", "")
            port = os.environ.get("HOROVOD_RENDEZVOUS_PORT", "")
            if not addr or not port:
                continue
            from .runner.http.kv_server import KVClient

            # Same 1-attempt/2s discipline as the heartbeat client: a
            # slow ship must never back-pressure the train loop (the
            # single pending slot just drops the stale payload). Ships
            # are generation-fenced like every other worker write — a
            # zombie rank resumed from a pre-abort world must not keep
            # repopulating the trace scope the re-formed world's
            # clear_heartbeat() just purged.
            client = KVClient(addr, int(port), timeout=2.0, retries=1,
                              generation_fn=_ship_generation)
            client.put(TRACE_SCOPE, payload.get("host", _host()),
                       json.dumps(payload).encode())
            from . import metrics

            metrics.TRACE_SHIPS.inc()
        except Exception as e:  # noqa: BLE001 — shipping is best-effort
            get_logger().debug("trace ship failed: %s", e)


def ship_async(payload: dict) -> None:
    """Queue a trace payload for the background shipper (single pending
    slot: a new sample replaces an unsent older one — the timeline wants
    the freshest window, not a backlog)."""
    global _ship_thread, _ship_pending
    with _ship_lock:
        _ship_pending = payload
        if _ship_thread is None or not _ship_thread.is_alive():
            _ship_thread = threading.Thread(
                target=_shipper_loop, name="hvd-trace-ship", daemon=True)
            _ship_thread.start()
        _ship_event.set()


def ship_interval_s() -> float:
    """Floor between heartbeat-coupled trace ships (seconds)."""
    return get_float("HOROVOD_TRACE_SHIP_SECONDS", 5.0)


_last_hb_ship = 0.0


def maybe_ship_heartbeat() -> bool:
    """Ship the current tracer window on the heartbeat cadence.

    Step-scoped workloads ship on every sampled step; eager host-plane
    workloads (the torch/TF surfaces) have no step scope, so their spans
    would collect locally and never reach the merged timeline or the
    straggler gauges. The elastic heartbeat sender calls this after each
    successful beat: when shipping is enabled (``HOROVOD_TRACE_SAMPLE >
    0``), the ring + ambient window ships at most once per
    ``HOROVOD_TRACE_SHIP_SECONDS`` — the freshness the self-healing
    policy's skew evidence rides on. Returns True when a ship was queued.
    """
    global _last_hb_ship
    if sample_every() <= 0:
        return False
    now = time.monotonic()
    with _ship_lock:
        if now - _last_hb_ship < ship_interval_s():
            return False
        _last_hb_ship = now
    ship_async(get_tracer().payload())
    return True


# ---------------------------------------------------------------------------
# Flight recorder dump
# ---------------------------------------------------------------------------


def dump_flight_record(reason: str, generation: int | None = None,
                       **fields: Any) -> dict | None:
    """Dump the last-K-steps flight record into the lifecycle journal as
    a ``flight_record`` event. Called on abort-consume, stall shutdown,
    deadman exit, and SIGTERM drain; never raises."""
    try:
        from . import metrics

        snap = get_tracer().flight_snapshot()
        # Replica-pool state rides every dump (abort-consume included):
        # which ranks' shards this process holds, at which step and
        # generation — the first question after a peer-rung recovery.
        try:
            from . import peercheck

            pool = peercheck.pool_summary()
            if pool is not None:
                snap["peer_pool"] = pool
        except Exception:  # noqa: BLE001 — the dump must still land
            pass
        # Integrity-plane state rides too (when it ever engaged): the
        # last staged fingerprint and the tripwire/rewind counters —
        # the first questions after a divergence names this rank.
        try:
            from . import integrity

            isum = integrity.flight_summary()
            if isum is not None:
                snap["integrity"] = isum
        except Exception:  # noqa: BLE001 — the dump must still land
            pass
        # Attribution rides too: the last synced step's phase
        # decomposition (where DID the wall time go before the wedge),
        # and — for a wedged collective still open — the gating rank
        # the cluster's partial critical path names (best-effort fetch
        # from GET /criticalpath; the first postmortem question).
        try:
            from . import attribution

            asum = attribution.flight_summary(snap)
            if asum is not None:
                snap["attribution"] = asum
        except Exception:  # noqa: BLE001 — the dump must still land
            pass
        # Memory snapshot rides EVERY dump: per-kind resident bytes,
        # the phase watermarks, and the footprint model's drift — the
        # first questions when the wedge or abort was memory-shaped.
        try:
            from . import memory

            msum = memory.flight_summary()
            if msum is not None:
                snap["memory"] = msum
        except Exception:  # noqa: BLE001 — the dump must still land
            pass
        metrics.FLIGHT_DUMPS.inc(reason=reason)
        metrics.event(
            "flight_record", generation=generation, reason=reason,
            rank=_rank(), host=_host(), **snap, **fields)
        return snap
    except Exception:  # noqa: BLE001 — postmortems are best-effort
        return None


# ---------------------------------------------------------------------------
# Skew attribution (runs on the driver, over shipped payloads)
# ---------------------------------------------------------------------------

#: Span categories matched across ranks for arrival-skew attribution:
#: eager/host collectives carry cat="collective"; compiled training's
#: cross-rank signal is the step span itself (all ranks enter step N of
#: the same program — a late entrant IS the straggler).
SKEW_CATS = ("collective", "step")


def straggler_warn_skew() -> float:
    """Arrival skew (seconds) past which the server journals a
    ``straggler_detected`` event."""
    return get_float("HOROVOD_STRAGGLER_WARN_SKEW", 1.0)


def compute_skew(payloads: Mapping[str, Mapping]) -> dict:
    """Per-collective arrival-skew attribution over shipped payloads.

    ``payloads`` maps host -> parsed trace payload. Spans are matched
    across ranks by ``(generation, step, name)`` within
    :data:`SKEW_CATS` — the generation scoping keeps a pre-recovery
    world's spans from matching the re-formed world's, and
    :meth:`StepTracer.rebase` (called at world join) keeps the step
    counters rank-aligned within a generation. For each matched instance
    seen by ≥2 ranks, a rank's *lateness* is its offset-corrected span
    start minus the earliest rank's. Returns::

        {"matched": N,
         "ranks": {rank: {"host", "mean_lateness_s", "max_lateness_s",
                          "samples"}},
         "worst": {"name", "step", "skew_s", "last_rank", "last_host"}
                  | None}

    ``worst`` names the single largest-skew instance — the last-arriver
    identity + magnitude the straggler gauges and journal events carry.
    """
    groups: dict[tuple, list[tuple[str, str, float]]] = {}
    rank_host: dict[str, str] = {}
    rank_err: dict[str, float] = {}
    for host, payload in payloads.items():
        if not isinstance(payload, Mapping):
            continue
        rank = str(payload.get("rank", "?"))
        try:
            offset = float(payload.get("clock_offset_s", 0.0) or 0.0)
        except (TypeError, ValueError):
            offset = 0.0
        generation = payload.get("generation")
        contributed = False
        for steprec in payload.get("steps", ()) or ():
            if not isinstance(steprec, Mapping):
                continue
            step = steprec.get("step")
            for sp in steprec.get("spans", ()) or ():
                if not isinstance(sp, Mapping):
                    continue
                if sp.get("cat") not in SKEW_CATS:
                    continue
                try:
                    t = float(sp["t"]) + offset
                except (KeyError, TypeError, ValueError):
                    continue
                key = (generation, step, sp.get("name"))
                groups.setdefault(key, []).append((rank, host, t))
                contributed = True
        # Only a payload that contributed spans may claim a rank's
        # identity: a spanless payload with a stale/default rank label
        # (a worker mid-bootstrap shipping its empty ring) must not
        # steal a real rank's host attribution — the gauges and the
        # policy would then pin the measured lateness on the wrong
        # host (or drop it entirely, hiding a straggler).
        if not contributed:
            continue
        rank_host[rank] = host
        try:
            rank_err[rank] = float(payload.get("clock_error_s") or 0.0)
        except (TypeError, ValueError):
            rank_err[rank] = 0.0
    matched = 0
    lateness: dict[str, list[float]] = {}
    worst: dict | None = None
    for (generation, step, name), arrivals in groups.items():
        ranks_seen = {r for r, _, _ in arrivals}
        if len(ranks_seen) < 2:
            continue
        matched += 1
        # One arrival per rank per instance: earliest wins (re-shipped
        # windows can repeat a step).
        first: dict[str, tuple[str, float]] = {}
        for r, h, t in arrivals:
            if r not in first or t < first[r][1]:
                first[r] = (h, t)
        first_rank, (_, t_min) = min(
            first.items(), key=lambda kv: kv[1][1])
        last_rank, (last_host, t_max) = max(
            first.items(), key=lambda kv: kv[1][1])
        skew = t_max - t_min
        for r, (_, t) in first.items():
            lateness.setdefault(r, []).append(t - t_min)
        if worst is None or skew > worst["skew_s"]:
            # Combined offset-estimation error of the two clocks being
            # differenced: consumers threshold on skew − err so clock
            # uncertainty can never register as phantom straggling.
            err = (rank_err.get(last_rank, 0.0)
                   + rank_err.get(first_rank, 0.0))
            worst = {"name": name, "step": step,
                     "skew_s": round(skew, 6),
                     "err_s": round(err, 6),
                     "last_rank": last_rank, "last_host": last_host}
    ranks = {
        r: {
            "host": rank_host.get(r, ""),
            "mean_lateness_s": round(sum(ls) / len(ls), 6),
            "max_lateness_s": round(max(ls), 6),
            "samples": len(ls),
        }
        for r, ls in lateness.items()
    }
    return {"matched": matched, "ranks": ranks, "worst": worst}


def straggler_summary(fetch_cluster: bool = True) -> dict:
    """This rank's view for ``profiler.summary()["stragglers"]``: the
    local clock-offset estimate + tracer state, plus (best-effort, when a
    rendezvous KV is configured) the server-computed cluster skew from
    ``GET /stragglers``."""
    cs = clock_sync()
    out: dict = {
        "clock_offset_s": round(cs.offset(), 6),
        "clock_error_s": cs.error(),
        "clock_synced": cs.synced(),
        "steps_recorded": get_tracer().steps_recorded(),
        "trace_sample": sample_every(),
    }
    addr = os.environ.get("HOROVOD_RENDEZVOUS_ADDR", "")
    port = os.environ.get("HOROVOD_RENDEZVOUS_PORT", "")
    if fetch_cluster and addr and port:
        try:
            from urllib.request import urlopen

            with urlopen(f"http://{addr}:{port}/stragglers",
                         timeout=2.0) as r:
                out["cluster"] = json.loads(r.read())
        except Exception as e:  # noqa: BLE001 — summary is best-effort
            out["cluster_error"] = str(e)[:200]
    return out
