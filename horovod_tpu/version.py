__version__ = "0.5.0"
