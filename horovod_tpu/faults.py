"""Deterministic fault-injection harness (the chaos plane).

The reference grew its elastic robustness through a fault-injection test
pattern (``test/integration/elastic_common.py``: mutate the discovery file,
kill workers by behavior flag). This module generalizes that into named
**injection points** wired through the control plane's hot paths:

- ``kv.request``         — every rendezvous KV client request attempt
- ``kv.fence``           — every generation-fenced KV write; firing (drop
  semantics) makes the client send a STALE generation, impersonating a
  zombie worker from the pre-abort world
- ``discovery.poll``     — every ``HostManager.update_available_hosts`` poll
- ``worker.step``        — every stall-watched step / fetch dispatch
- ``heartbeat.send``     — every worker heartbeat publish
- ``abort.poll``         — every coordinated-abort flag poll; drop/delay
  simulate delayed abort propagation
- ``checkpoint.save``    — every durable checkpoint write attempt
- ``checkpoint.restore`` — every durable checkpoint read/restore attempt
- ``policy.decide``      — every self-healing policy evaluation on the
  elastic driver (``raise`` proves a broken policy cannot take the driver
  down; ``delay`` defers decisions)
- ``spare.promote``      — every warm-spare promotion into the world
  (``raise`` forces the cold-launch fallback path)
- ``driver.snapshot``    — every durable control-plane snapshot write
  (``raise`` simulates a storage blip the driver must survive; pair
  with :func:`kill_driver` for the torn-write chaos case)
- ``driver.takeover``    — the restarted driver's snapshot-load/adopt
  path (``raise`` fails the takeover so the supervisor retries;
  ``delay`` widens the orphan window)
- ``comms.link``         — every comms-model observation of a measured
  collective; ``delay`` inflates the observed latency (a deterministic
  slow link, the injector the residual-gauge chaos tests ride)
- ``kv.serve``           — every request the rendezvous KV server
  handles; firing (drop semantics) closes the connection without
  answering — to the client that is a transport failure, exactly a
  driver mid-crash
- ``grad.corrupt``       — every elastic state commit's host snapshot;
  the ``corrupt[:nbits]`` mode flips seeded bits in the committed state
  bytes — a host whose memory/FPU silently computed wrong answers (SDC),
  the canonical injector the integrity voting plane
  (``horovod_tpu/integrity.py``) exists to catch
- ``peer.corrupt``       — every peer-replica wire blob after encoding;
  ``corrupt`` flips bits in the ENCODED record (header digest already
  computed), modeling a bit-flip on the wire — the KV server's
  install-time verification must reject it (422) with the previous good
  replica intact
- ``moe.dispatch``       — every expert-parallel MoE step's dispatch
  alltoall (``parallel/moe.py`` step wrappers). **The canonical MoE
  chaos injector**: ``drop`` loses the dispatched payload (every token
  takes its passthrough residual — a dead expert exchange, the step
  survives), ``delay`` stalls the dispatch (an expert-imbalance
  straggler for the skew gauges), ``corrupt`` flips seeded bits in the
  token batch feeding the alltoall — quantized or not, the damage
  crosses ranks, which is what the non-finite tripwire and integrity
  voting planes must catch
- ``sched.decide``       — every cross-job arbitration pass of the
  multi-tenant scheduler (``elastic/policy.py`` ``JobArbiter``; ``drop``
  skips the pass, ``raise`` proves a broken arbiter cannot take the
  scheduler down, ``delay`` defers decisions)
- ``job.preempt``        — every full-job preemption the scheduler
  actuates (SIGTERM-drain of the victim job's driver through its final
  commits)
- ``pool.assign``        — every pool-to-job host assignment
  (grant/promote out of the shared pool; ``raise`` holds the host back
  for a later tick)
- ``model.publish``      — every training-side model publication to the
  serving tier's ``modelstate`` KV scope (``horovod_tpu/serving.py``,
  fired on each elastic commit when ``HOROVOD_SERVE_PUBLISH=1``):
  ``drop`` loses the publication (training continues, the serving tier
  keeps serving last-good and its staleness gauge climbs), ``delay``
  stalls the commit-path PUT, ``corrupt`` flips seeded bits in the
  ENCODED wire record — the server's install-time verification must 422
  it with the previous good model intact (the ``peer.corrupt`` twin)
- ``serve.fetch``        — every serving-subscriber poll of the
  ``modelstate`` scope (``drop``/``raise`` fail the fetch so the
  bounded retry + ``retry_budget_exhausted`` observability is provable;
  ``delay`` stalls it past the staleness SLO)
- ``serve.swap``         — every hot-swap install attempt on the
  serving tier's RCU pointer (``drop`` skips the swap — last-good keeps
  serving, the next poll retries; ``delay`` widens the swap window the
  concurrency tests hammer)
- ``memory.pressure``    — every stall-watched factory step entry
  (``parallel/data_parallel.py``): ``drop`` raises a synthetic
  ``RESOURCE_EXHAUSTED`` at the step boundary — the deterministic
  device-OOM injector behind the memory observatory's forensics tests
  (the boundary catches it, dumps the ``oom`` flight record naming the
  top resident leaves, and re-raises)

The canonical **control-plane injectors** are these three plus
:func:`kill_driver` (SIGKILL the driver process — the KV server dies
mid-request with no cleanup, the exact crash the takeover path exists
to survive).

The canonical **straggler injector** is a ``delay`` on ``worker.step``::

    HOROVOD_FAULTS="worker.step=delay:1.5@1x999999"

Every stall-watched step on the armed worker then enters its collectives
``1.5`` seconds late — a persistently slow-but-alive host, exactly the
signal the tracing plane's skew gauges and the self-healing policy
(``horovod_tpu/elastic/policy.py``) detect and drain.

Each point can be armed (via API or env) to **drop**, **delay**, **raise**,
or **hang** on the Nth hit, for a window of consecutive hits — deterministic
by construction, so chaos tests assert exact trajectories instead of racing
``kill -9`` against a scheduler.

API::

    from horovod_tpu import faults
    faults.inject("kv.request", "raise", at=3, count=2)  # 3rd+4th hit fail
    faults.fire("kv.request")   # called by the instrumented site

Env (reaches subprocess workers; parsed lazily on first ``fire``)::

    HOROVOD_FAULTS="kv.request=raise@3x2;worker.step=hang:30;heartbeat.send=drop@1x999"

Spec grammar: ``point=mode[:arg]@N[xC]`` — arm on the Nth hit (1-based,
default 1) for C consecutive hits (default 1); ``arg`` is seconds for
``delay``/``hang``, or the bit-flip count for ``corrupt`` (default 64).
Points are cheap no-ops when nothing is armed.

The ``corrupt`` mode only acts at call sites that pass payload bytes
through :func:`corrupt_payload` (the SDC injectors ``grad.corrupt`` /
``peer.corrupt``); at a plain :func:`fire` site it is a no-op. The flips
are seeded from the point name and hit index, so the same spec mutates
the same bits every run — chaos tests assert exact trajectories.

Process-level helpers (``suspend``/``resume``/``kill_process``) wrap the
signals subprocess chaos tests need: SIGSTOP simulates the hung-but-alive
TPU VM (the failure ``stall.py`` documents — invisible to ``popen.poll``),
SIGKILL the crashed one.
"""

from __future__ import annotations

import dataclasses
import os
import signal
import threading
import time

ENV_SPEC = "HOROVOD_FAULTS"

# Canonical injection-point names (call sites use these constants).
KV_REQUEST = "kv.request"
KV_FENCE = "kv.fence"
DISCOVERY_POLL = "discovery.poll"
WORKER_STEP = "worker.step"
HEARTBEAT_SEND = "heartbeat.send"
ABORT_POLL = "abort.poll"
CHECKPOINT_SAVE = "checkpoint.save"
CHECKPOINT_RESTORE = "checkpoint.restore"
PEER_REPLICATE = "peer.replicate"
PEER_VERIFY = "peer.verify"
POLICY_DECIDE = "policy.decide"
SPARE_PROMOTE = "spare.promote"
DRIVER_SNAPSHOT = "driver.snapshot"
DRIVER_TAKEOVER = "driver.takeover"
KV_SERVE = "kv.serve"
# Every comms-model observation of a measured collective: ``delay``
# inflates the observed latency (a deterministically degraded link —
# the injector behind the hvd_comms_residual_seconds chaos tests);
# ``drop`` loses the sample, never the op.
COMMS_LINK = "comms.link"
# Silent-data-corruption injectors (the integrity defense plane's chaos
# points): grad.corrupt mutates a rank's committed state snapshot
# (self-consistent digests — only cross-rank voting can see it);
# peer.corrupt mutates the encoded replica wire blob (digest mismatch —
# the server's install gate must reject it).
GRAD_CORRUPT = "grad.corrupt"
PEER_CORRUPT = "peer.corrupt"
# The expert-parallel MoE dispatch alltoall (the canonical MoE chaos
# injector — see the module docstring): drop loses the payload
# (passthrough step), delay stalls it, corrupt flips bits in the token
# batch feeding the wire.
MOE_DISPATCH = "moe.dispatch"
# Multi-tenant scheduler plane (runner/elastic/scheduler.py): the
# cross-job arbitration loop, one job's full preemption, and every
# pool-to-job host assignment — scheduler-level chaos scriptable like
# every other plane. sched.decide ``drop`` skips an arbitration pass
# (``raise`` proves a broken arbiter cannot take the scheduler down,
# ``delay`` defers decisions past the hysteresis window); job.preempt
# fires on each full-job preemption actuation; pool.assign on each host
# grant/promote out of the shared pool (``raise`` forces the scheduler
# to hold the host back and retry the assignment on a later tick).
SCHED_DECIDE = "sched.decide"
JOB_PREEMPT = "job.preempt"
POOL_ASSIGN = "pool.assign"
# Training-to-serving bridge (horovod_tpu/serving.py): the commit-path
# model publication, the serving subscriber's scope poll, and the
# RCU hot-swap install — the canonical serving chaos injectors
# (drop/delay/corrupt), consistent with peer.replicate/peer.corrupt.
MODEL_PUBLISH = "model.publish"
SERVE_FETCH = "serve.fetch"
SERVE_SWAP = "serve.swap"
# The memory observatory's OOM injector (parallel/data_parallel.py, the
# factory step boundary): ``drop`` raises a synthetic RESOURCE_EXHAUSTED
# at the step boundary — the deterministic device-OOM the forensics
# tests ride (the boundary's catch dumps the memory flight record and
# re-raises); ``delay`` stalls the step entry like worker.step.
MEMORY_PRESSURE = "memory.pressure"

_MODES = ("drop", "delay", "raise", "hang", "corrupt")
_DEFAULT_HANG_S = 3600.0
_DEFAULT_DELAY_S = 0.1
_DEFAULT_CORRUPT_BITS = 64


class InjectedFault(OSError):
    """Raised by an armed ``raise`` fault.

    Subclasses OSError so every retry/backoff path treats it exactly like
    the transient I/O failure it impersonates.
    """


@dataclasses.dataclass
class FaultSpec:
    point: str
    mode: str                      # drop | delay | raise | hang
    arg: float | None = None       # seconds for delay/hang
    at: int = 1                    # 1-based hit index the fault arms on
    count: int = 1                 # consecutive hits it stays armed for

    def armed_for(self, hit: int) -> bool:
        return self.at <= hit < self.at + self.count


def parse_spec(spec: str) -> list[FaultSpec]:
    """Parse the ``HOROVOD_FAULTS`` grammar; invalid entries raise."""
    out: list[FaultSpec] = []
    for entry in spec.replace(",", ";").split(";"):
        entry = entry.strip()
        if not entry:
            continue
        point, _, rhs = entry.partition("=")
        if not rhs:
            raise ValueError(f"fault spec {entry!r}: missing '=mode'")
        mode_arg, _, window = rhs.partition("@")
        mode, _, arg = mode_arg.partition(":")
        if mode not in _MODES:
            raise ValueError(
                f"fault spec {entry!r}: unknown mode {mode!r} "
                f"(expected one of {_MODES})"
            )
        at, count = 1, 1
        if window:
            n, _, c = window.partition("x")
            at = int(n)
            count = int(c) if c else 1
        out.append(FaultSpec(
            point=point.strip(),
            mode=mode,
            arg=float(arg) if arg else None,
            at=at,
            count=count,
        ))
    return out


class _Registry:
    """Armed faults + per-point hit/fire counters (thread-safe)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._specs: dict[str, FaultSpec] = {}
        self._hits: dict[str, int] = {}
        self._fired: dict[str, int] = {}
        self._env_loaded = False

    def _load_env_locked(self) -> None:
        if self._env_loaded:
            return
        self._env_loaded = True
        spec = os.environ.get(ENV_SPEC, "")
        if not spec:
            return
        for s in parse_spec(spec):
            # API-armed faults win over the env (tests layer on top).
            self._specs.setdefault(s.point, s)

    def inject(self, point: str, mode: str, arg: float | None = None,
               at: int = 1, count: int = 1) -> None:
        if mode not in _MODES:
            raise ValueError(f"unknown fault mode {mode!r}")
        with self._lock:
            self._specs[point] = FaultSpec(point, mode, arg, at, count)
            self._hits.pop(point, None)
            self._fired.pop(point, None)

    def clear(self, point: str) -> None:
        with self._lock:
            self._specs.pop(point, None)

    def reset(self) -> None:
        """Drop every armed fault and counter; re-read env on next fire."""
        with self._lock:
            self._specs.clear()
            self._hits.clear()
            self._fired.clear()
            self._env_loaded = False

    def hits(self, point: str) -> int:
        with self._lock:
            return self._hits.get(point, 0)

    def fired(self, point: str) -> int:
        with self._lock:
            return self._fired.get(point, 0)

    def active(self) -> dict[str, FaultSpec]:
        with self._lock:
            self._load_env_locked()
            return dict(self._specs)

    def armed(self, point: str) -> bool:
        """Cheap armed-at-all check (any mode, any window) — call sites
        whose payload plumbing has a real cost (serializing state bytes
        for ``corrupt_payload``) gate on this so the unarmed path stays
        free. Does NOT count a hit."""
        with self._lock:
            self._load_env_locked()
            return point in self._specs

    def _take_hit(self, point: str) -> tuple[FaultSpec | None, int]:
        """Count one hit; return (armed spec or None, hit index)."""
        with self._lock:
            self._load_env_locked()
            hit = self._hits.get(point, 0) + 1
            self._hits[point] = hit  # counted even unarmed: tests assert
            spec = self._specs.get(point)  # exact attempt trajectories
            if spec is None or not spec.armed_for(hit):
                return None, hit
            self._fired[point] = self._fired.get(point, 0) + 1
            return spec, hit

    def fire(self, point: str) -> bool:
        """One hit at an injection point.

        Returns True when the caller must DROP the operation (skip it with
        that call site's drop semantics), False to proceed. ``delay``/
        ``hang`` sleep here then proceed; ``raise`` raises InjectedFault;
        ``corrupt`` is a no-op here (it only acts through
        :func:`corrupt_payload`).
        """
        spec, hit = self._take_hit(point)
        if spec is None:
            return False
        # Actions run OUTSIDE the lock (sleeps must not serialize peers).
        if spec.mode == "drop":
            return True
        if spec.mode == "corrupt":
            return False  # acts only through corrupt_payload
        self._side_action(spec, point, hit)
        return False

    @staticmethod
    def _side_action(spec: FaultSpec, point: str, hit: int) -> None:
        """The delay/hang/raise action shared by :func:`fire` and
        :func:`corrupt_payload` (one dispatch so the two injection
        surfaces cannot drift apart); other modes are a no-op here."""
        if spec.mode == "delay":
            time.sleep(spec.arg if spec.arg is not None else _DEFAULT_DELAY_S)
        elif spec.mode == "hang":
            time.sleep(spec.arg if spec.arg is not None else _DEFAULT_HANG_S)
        elif spec.mode == "raise":
            raise InjectedFault(f"injected fault at {point!r} (hit {hit})")

    def corrupt_payload(self, point: str, data: bytes) -> bytes:
        """One hit at a payload-mutating injection point.

        With a ``corrupt`` spec armed for this hit, returns ``data`` with
        ``arg`` (default 64) bit flips at positions seeded from the point
        name and hit index — deterministic by construction. Other armed
        modes keep their :func:`fire` semantics (``raise`` raises,
        ``delay``/``hang`` sleep, ``drop`` is a no-op — there is nothing
        to drop, the caller keeps its payload). Unarmed: ``data`` back
        untouched."""
        spec, hit = self._take_hit(point)
        if spec is None:
            return data
        if spec.mode != "corrupt":
            self._side_action(spec, point, hit)
            return data
        return flip_bits(
            data,
            nbits=(int(spec.arg) if spec.arg is not None
                   else _DEFAULT_CORRUPT_BITS),
            seed=f"{point}#{hit}")


def flip_bits(data: bytes, nbits: int, seed: str) -> bytes:
    """Flip ``nbits`` deterministically seeded bit positions of ``data``
    (with replacement — an even number of hits on one bit cancels, like
    real upsets). Pure stdlib: positions come from sha256 of the seed,
    extended counter-mode, so the same (payload length, nbits, seed)
    flips the same bits on every run and every host."""
    import hashlib

    if not data or nbits <= 0:
        return data
    buf = bytearray(data)
    total_bits = len(buf) * 8
    stream = b""
    counter = 0
    positions: list[int] = []
    while len(positions) < nbits:
        if len(stream) < 8:
            stream += hashlib.sha256(
                f"{seed}:{counter}".encode()).digest()
            counter += 1
        pos = int.from_bytes(stream[:8], "big") % total_bits
        stream = stream[8:]
        positions.append(pos)
    for pos in positions:
        buf[pos // 8] ^= 1 << (pos % 8)
    return bytes(buf)


_registry = _Registry()

# Module-level facade — what call sites and tests use.
inject = _registry.inject
clear = _registry.clear
reset = _registry.reset
hits = _registry.hits
fired = _registry.fired
active = _registry.active
fire = _registry.fire
armed = _registry.armed
corrupt_payload = _registry.corrupt_payload


# -- process-level chaos helpers (subprocess tests) --------------------------

def suspend(pid: int) -> None:
    """SIGSTOP a process: hung-but-alive, the hang ``popen.poll`` cannot
    see — only the heartbeat liveness plane catches it."""
    os.kill(pid, signal.SIGSTOP)


def resume(pid: int) -> None:
    os.kill(pid, signal.SIGCONT)


def kill_process(pid: int, sig: int = signal.SIGKILL) -> None:
    os.kill(pid, sig)


def self_suspend() -> None:
    """A worker SIGSTOPs itself — the deterministic in-process way for a
    chaos-test worker to become a hung host at an exact step."""
    os.kill(os.getpid(), signal.SIGSTOP)


def kill_driver(pid: int) -> None:
    """SIGKILL the elastic DRIVER process: the canonical control-plane
    crash injector. The in-process rendezvous KV server dies mid-request
    with no cleanup, workers are orphaned (their process group survives
    the driver — ``start_new_session``), and the only recovery is a
    supervisor relaunch taking over from the durable snapshot
    (``runner/elastic/driver_state.py``). Distinct from
    :func:`kill_process` only in intent — the signal is the same — but
    chaos tests naming the driver explicitly read as what they are."""
    os.kill(pid, signal.SIGKILL)
