"""Lifecycle + world facts: the ``hvd.init()/rank()/size()`` surface.

TPU-native re-design of the reference's ``horovod/common/basics.py``
(``HorovodBasics``) and the C API it binds
(``horovod/common/operations.cc — horovod_init/_rank/_size/...``).

Key divergence from the reference, by design: JAX is a single-controller SPMD
system — one Python process drives many devices, and collectives are
*compiled into* the step function rather than enqueued to a background
thread. So:

- ``size()`` is the number of **devices** (one rank per chip, like Horovod's
  one rank per GPU), not the number of processes.
- Inside a compiled step (under ``shard_map`` over the hvd axis), ``rank()``
  returns the per-device ``lax.axis_index`` — a traced value.
- Outside compiled code, ``rank()`` returns the first local device's global
  rank: it is 0 exactly on the process that should do rank-0-only work
  (checkpointing, logging), which preserves the reference idiom
  ``if hvd.rank() == 0: save(...)``.
- For input pipelines, shard data by ``process_rank()/process_count()``
  (each controller process feeds its local devices), the JAX-native
  equivalent of the reference's per-rank data sharding.

Multi-host initialization uses ``jax.distributed.initialize`` driven by the
launcher's env (coordinator address from the rendezvous server), replacing
the reference's MPI/Gloo bootstrap.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Sequence

from .exceptions import NotInitializedError
from .topology import Topology
from .utils.env import RuntimeConfig
from .utils.logging import get_logger

_lock = threading.Lock()


class _GlobalState:
    """Singleton runtime state (analog of the reference's
    ``HorovodGlobalState`` in ``horovod/common/global_state.h``), minus the
    background thread: negotiation is compiled away in the JAX path, and the
    native runtime (``horovod_tpu.runtime``) owns its own loop when used.
    """

    def __init__(self) -> None:
        self.initialized = False
        self.topology: Topology | None = None
        self.config: RuntimeConfig | None = None
        self.mesh = None  # global 1-D jax Mesh over all ranks, axis 'hvd'
        self.axis_name = "hvd"
        self.distributed_initialized = False

    def require_init(self) -> "_GlobalState":
        if not self.initialized:
            raise NotInitializedError()
        return self


_state = _GlobalState()


def _maybe_init_distributed() -> None:
    """Multi-host bootstrap over DCN via jax.distributed.

    The launcher (``horovod_tpu.runner``) writes the coordinator address in
    env; on managed TPU slices JAX can also discover it from metadata, in
    which case this is a no-op.
    """
    import jax

    # Elastic mode: the world config lives in the rendezvous KV (it changes
    # across epochs); refresh the env contract before reading it. Env check
    # first so non-elastic workers never import the launcher machinery.
    if os.environ.get("HOROVOD_ELASTIC", "") == "1":
        from .runner.elastic import worker as elastic_worker

        ctx = elastic_worker.get_worker_context()
        if elastic_worker.spare_mode():
            # Warm spare: no assignment exists yet by design. Start the
            # poll loop (advances the generation view so KV writes stay
            # fenced) and the heartbeat sender (the driver's liveness
            # plane watches spares too) FIRST, then park until the driver
            # publishes a world that includes this host — promotion costs
            # one re-rendezvous, not a cold launch.
            ctx.start_polling()
            ctx.start_heartbeat()
            ctx.apply_to_env(ctx.wait_for_assignment())
        else:
            ctx.apply_to_env(ctx.fetch_assignment())
            ctx.start_polling()
            # Liveness plane: publish heartbeats so the driver can tell a
            # hung host (SIGSTOP'd, wedged VM) from a slow one —
            # popen.poll() alone cannot. No-op when
            # HOROVOD_ELASTIC_HEARTBEAT_INTERVAL <= 0.
            ctx.start_heartbeat()

    coord = os.environ.get("HOROVOD_COORDINATOR_ADDR", "")
    nprocs = int(os.environ.get("HOROVOD_NUM_PROCESSES", "0") or 0)
    proc_id = int(os.environ.get("HOROVOD_PROCESS_ID", "-1") or -1)
    if (os.environ.get("HOROVOD_ELASTIC", "") == "1"
            and os.environ.get("HOROVOD_ELASTIC_JAX_DISTRIBUTED", "") != "1"):
        # Elastic default: NO jax.distributed. Its coordination client
        # FATALLY ABORTS the surviving processes when a peer dies (C++
        # terminate, uncatchable) — the exact event elastic exists to
        # survive. Cross-process collectives ride the native host plane,
        # which re-forms in-process (tested); each process keeps a local
        # jax device world. Opt back in with
        # HOROVOD_ELASTIC_JAX_DISTRIBUTED=1 if you accept that any peer
        # death restarts every worker (the driver relaunches them).
        get_logger().info(
            "elastic: skipping jax.distributed (in-process recovery); set "
            "HOROVOD_ELASTIC_JAX_DISTRIBUTED=1 for a global jax world")
        return
    if coord and nprocs > 1 and proc_id >= 0:
        coord = _exchange_coordinator_port(coord, proc_id)
        # Write the resolved address back so downstream consumers (e.g. the
        # native host world, which shares the coordinator host) never see
        # the unresolved 'self' sentinel.
        os.environ["HOROVOD_COORDINATOR_ADDR"] = coord
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=nprocs,
            process_id=proc_id,
        )
        _state.distributed_initialized = True


def _exchange_coordinator_port(coord: str, proc_id: int) -> str:
    """Let process 0 pick the coordinator port ON ITS OWN HOST and publish
    it via the rendezvous KV; everyone else polls for it.

    The launcher cannot probe a free port on a remote coordinator host
    (classic TOCTOU across machines); its port choice is only a fallback
    for worlds launched without a rendezvous server.
    """
    import time

    addr = os.environ.get("HOROVOD_RENDEZVOUS_ADDR", "")
    port = int(os.environ.get("HOROVOD_RENDEZVOUS_PORT", "-1") or -1)
    if not addr or port < 0:
        return coord  # manual launch: trust the env as given
    from .runner.http.kv_server import KVClient, env_generation
    from .runner.network import free_port, routable_addr

    host = coord.rsplit(":", 1)[0]
    if host == "self":
        # Cluster integrations (Ray/Spark) can't know which node rank 0
        # lands on; the sentinel makes process 0 publish its own address.
        host = routable_addr()
    version = os.environ.get("HOROVOD_WORLD_VERSION", "static")
    scope = f"coord/{version}"
    # Generation-fenced: a zombie rank 0 resumed from a pre-abort world
    # must not republish a stale coordinator endpoint.
    kv = KVClient(addr, port, generation_fn=env_generation)
    if proc_id == 0:
        chosen = f"{host}:{free_port()}"
        kv.put(scope, "addr", chosen.encode())
        return chosen
    deadline = time.time() + 60.0
    while time.time() < deadline:
        val = kv.get(scope, "addr")
        if val is not None:
            return val.decode()
        time.sleep(0.05)
    raise TimeoutError(
        f"coordinator address not published to rendezvous KV scope {scope!r}"
    )


def init(devices: Sequence[Any] | None = None) -> None:
    """Initialize the framework: topology, global mesh, process sets.

    Replaces the reference's ``InitializeHorovodOnce()`` — but where that
    spawned a background negotiation thread, this derives the static world:
    sorted device list (ICI order), the global 1-D mesh (axis ``'hvd'``)
    that every collective and the DistributedOptimizer shard over, and the
    global process set. Idempotent.
    """
    import jax
    from jax.sharding import Mesh
    import numpy as np

    with _lock:
        if _state.initialized:
            return
        # Distributed bootstrap first: in elastic mode it refreshes the env
        # world facts from the KV, which from_env() must then see.
        _maybe_init_distributed()
        config = RuntimeConfig.from_env()
        topo = Topology(devices)
        _state.topology = topo
        _state.config = config
        _state.mesh = Mesh(np.array(topo.devices), (_state.axis_name,))
        _state.initialized = True

        # Register the global process set (id 0) now that the world exists.
        from . import process_sets

        process_sets._reset(topo, _state.mesh)
        # Honor HOROVOD_PROFILER_LOGDIR (xprof capture; the reference's
        # NVTX-activation-by-env contract).
        from . import profiler

        profiler.maybe_start_from_env()
        get_logger().info(
            "horovod_tpu initialized: %d rank(s), %d host(s), backend=%s",
            topo.size,
            topo.cross_size,
            jax.default_backend(),
        )


def shutdown() -> None:
    """Tear down world state (elastic re-init calls this before re-forming)."""
    with _lock:
        # Distributed teardown runs even when init() died half-way (after
        # jax.distributed came up but before _state.initialized was set) —
        # otherwise the next init() hits "already initialized" forever.
        if _state.distributed_initialized:
            import jax

            try:
                jax.distributed.shutdown()
            except Exception as e:  # broken world: still clear the flag
                get_logger().warning("jax.distributed.shutdown failed: %s", e)
            _state.distributed_initialized = False
        # The native host world (libhvdrt) is per-epoch too: tear it down
        # so elastic re-init forms a fresh one instead of retrying against
        # a dead runtime forever.
        from .parallel import hierarchical

        if hierarchical._host_world is not None:
            try:
                hierarchical._host_world.shutdown()
            except Exception as e:
                get_logger().warning("native world shutdown failed: %s", e)
            hierarchical._host_world = None
        if not _state.initialized:
            return
        from . import process_sets
        from .ops.executable_cache import global_cache

        # Compiled executables are sharded over this epoch's mesh; a new
        # world must not hit them (stale devices / reused process-set ids).
        global_cache().clear()
        process_sets._clear()
        _state.initialized = False
        _state.topology = None
        _state.mesh = None
        _state.config = None


def is_initialized() -> bool:
    return _state.initialized


def in_axis_scope(axis_name) -> bool:
    """True when called under shard_map/pmap with `axis_name` bound.

    The single shared probe used by every dual-regime API (rank(),
    local_rank(), the collective ops) to decide traced vs eager dispatch.
    Accepts a tuple of axis names (the hierarchical ``(cross, local)``
    mesh); all must be bound.
    """
    import jax

    if isinstance(axis_name, (tuple, list)):
        return all(in_axis_scope(a) for a in axis_name)
    try:
        jax.lax.axis_index(axis_name)
        return True
    except (NameError, KeyError, TypeError):
        return False


def _axis_index_or_none(axis_name):
    """Per-device rank if called under a mapped axis, else None.

    Falls back to the hierarchical ``(cross, local)`` axes when the flat
    axis is unbound: ``lax.axis_index`` over the tuple yields the
    flattened (cross-major) index, which is the rank order of the
    hierarchical mesh.
    """
    import jax

    if in_axis_scope(axis_name):
        return jax.lax.axis_index(axis_name)
    if axis_name == _state.axis_name:
        from .parallel.hierarchical import HIERARCHICAL_AXES

        if in_axis_scope(HIERARCHICAL_AXES):
            return jax.lax.axis_index(HIERARCHICAL_AXES)
    return None


def rank(axis_name: str | None = None):
    """Global rank. Traced (per-device) inside shard_map; else process view."""
    st = _state.require_init()
    idx = _axis_index_or_none(axis_name or st.axis_name)
    if idx is not None:
        return idx
    return st.topology.rank


def size() -> int:
    """Total number of ranks (devices) in the world."""
    return _state.require_init().topology.size


def local_rank(axis_name: str | None = None):
    st = _state.require_init()
    idx = _axis_index_or_none(axis_name or st.axis_name)
    if idx is not None:
        import jax.numpy as jnp

        # Table lookup: hosts are not contiguous in ICI rank order.
        return jnp.asarray(st.topology.local_rank_table)[idx]
    return st.topology.local_rank


def local_size() -> int:
    return _state.require_init().topology.local_size


def cross_rank() -> int:
    return _state.require_init().topology.cross_rank


def cross_size() -> int:
    return _state.require_init().topology.cross_size


def process_rank() -> int:
    """This controller process's index — shard input pipelines by this."""
    return _state.require_init().topology.process_index


def process_count() -> int:
    return _state.require_init().topology.process_count


def global_mesh():
    """The global 1-D mesh (axis 'hvd') in canonical ICI rank order."""
    return _state.require_init().mesh


def global_axis_name() -> str:
    return _state.axis_name


def config() -> RuntimeConfig:
    return _state.require_init().config


def is_homogeneous() -> bool:
    """True if every host has the same number of local ranks."""
    topo = _state.require_init().topology
    return topo.size == topo.local_size * topo.cross_size


# -- build/capability introspection (parity: HorovodBasics' *_built/*_enabled
# surface — scripts use these to pick code paths; each answer names the
# TPU-native subsystem playing the reference role) -------------------------


def mpi_enabled() -> bool:
    """False: there is no MPI path — the control plane is the rendezvous
    KV + TCP star (reference's Gloo role); the data plane is XLA/ICI."""
    return False


def mpi_built() -> bool:
    return False


def gloo_enabled() -> bool:
    """True: the native TCP runtime (libhvdrt) plays Gloo's role — the
    CPU/host data plane and the elastic substrate."""
    return True


def gloo_built() -> bool:
    try:
        from .runtime import load_library

        load_library()
        return True
    except Exception:
        return False


def nccl_built() -> bool:
    """True: XLA collectives over ICI play NCCL's role (AllReduce/
    AllGather/AllToAll/ReduceScatter HLOs compiled into the step)."""
    return True


def ddl_built() -> bool:
    return False  # removed upstream too


def ccl_built() -> bool:
    return False


def cuda_built() -> bool:
    """False — and intentionally so: this framework targets TPUs; the
    accelerator data plane is ICI, not CUDA."""
    return False


def rocm_built() -> bool:
    return False


def mpi_threads_supported() -> bool:
    """Parity shim: the native runtime's enqueue API is thread-safe (the
    property this reference check actually gates on)."""
    return True
