"""Gradient compression for the wire (ICI) leg of allreduce.

Parity: ``horovod/torch/compression.py`` / ``horovod/tensorflow/compression.py``
(``Compression.none`` / ``Compression.fp16``). TPU-native addition:
``Compression.bf16`` — bfloat16 is the MXU's native reduced precision and
halves ICI bytes without fp16's range cliffs, so it is the compressor TPU
users should reach for; fp16 is kept for script parity.

A compressor is a pair of pure functions used around the collective:
``compress(tensor) -> (wire_tensor, ctx)`` and
``decompress(wire_tensor, ctx) -> tensor``. Both compile into the step.
"""

from __future__ import annotations

import jax.numpy as jnp


class Compressor:
    """Base compressor: subclasses override compress/decompress."""

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        del ctx
        return tensor


class NoneCompressor(Compressor):
    """Identity (default)."""


class _CastCompressor(Compressor):
    wire_dtype: jnp.dtype = None

    @classmethod
    def compress(cls, tensor):
        tensor = jnp.asarray(tensor)
        ctx = tensor.dtype
        if jnp.issubdtype(tensor.dtype, jnp.floating):
            return tensor.astype(cls.wire_dtype), ctx
        return tensor, ctx

    @classmethod
    def decompress(cls, tensor, ctx):
        if ctx is not None and tensor.dtype != ctx:
            return tensor.astype(ctx)
        return tensor


class FP16Compressor(_CastCompressor):
    """Cast float grads to float16 on the wire (reference parity)."""

    wire_dtype = jnp.float16


class BF16Compressor(_CastCompressor):
    """Cast float grads to bfloat16 on the wire (TPU-native choice)."""

    wire_dtype = jnp.bfloat16


class Int8Compressor(Compressor):
    """Int8 wire (EQuARX-style): blockwise-quantized exchange at ~2
    bytes/element of ICI traffic vs bf16's ~4.

    Unlike the cast compressors this changes the EXCHANGE, not just the
    wire dtype — int8 contributions cannot be summed on the wire
    (overflow), so the DistributedOptimizer routes int8 through
    :func:`ops.quantization.int8_fused_allreduce` (quantize →
    all_to_all → dequant-sum → requant → all_gather). ``compress`` /
    ``decompress`` are therefore identities here; using this compressor
    outside the compiled optimizer path raises."""

    marker = "int8"

    @staticmethod
    def compress(tensor):
        raise ValueError(
            "Compression.int8 changes the exchange itself and only "
            "composes with the compiled DistributedOptimizer / hvd.grad "
            "paths (ops.quantization.int8_fused_allreduce); use "
            "Compression.fp16/bf16 for plain wire casts")

    @staticmethod
    def decompress(tensor, ctx):  # same guard, 2-arg contract signature
        del ctx
        return Int8Compressor.compress(tensor)


class Compression:
    """Namespace mirroring ``hvd.Compression`` (+ TPU-native additions
    ``bf16`` and ``int8``)."""

    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
    int8 = Int8Compressor
