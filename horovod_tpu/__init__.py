"""horovod_tpu: a TPU-native distributed training framework with the
capabilities of Horovod (reference: JayjeetAtGithub/horovod), re-designed
for XLA/ICI rather than ported from NCCL/MPI.

Quick start (the reference's ``import horovod.torch as hvd`` idiom)::

    import horovod_tpu as hvd
    hvd.init()
    out = hvd.allreduce(stacked, op=hvd.Sum)       # eager collective
    # ... or call the same ops inside a jitted shard_map step.

Layer map (vs SURVEY.md §1): the user API here is L5; collectives compile
to XLA HLOs over the device mesh (replacing L2b/L1's NCCL/MPI data plane).
"""

from . import _jax_compat

_jax_compat.install()

from .version import __version__  # noqa: F401

from .basics import (  # noqa: F401
    ccl_built,
    cuda_built,
    ddl_built,
    gloo_built,
    gloo_enabled,
    mpi_built,
    mpi_enabled,
    mpi_threads_supported,
    nccl_built,
    rocm_built,
    config,
    cross_rank,
    cross_size,
    global_axis_name,
    global_mesh,
    init,
    is_homogeneous,
    is_initialized,
    local_rank,
    local_size,
    process_count,
    process_rank,
    rank,
    shutdown,
    size,
)
from .exceptions import (  # noqa: F401
    HorovodInternalError,
    HorovodTpuError,
    HostsUpdatedInterrupt,
    NotInitializedError,
    RecoveryExhaustedError,
    SyncModeIneligibleError,
)
from .ops import (  # noqa: F401
    Adasum,
    Average,
    Max,
    Min,
    Product,
    Sum,
    allgather,
    allreduce,
    alltoall,
    barrier,
    broadcast,
    grouped_allgather,
    grouped_allreduce,
    grouped_reducescatter,
    reducescatter,
)
from .process_sets import (  # noqa: F401
    ProcessSet,
    add_process_set,
    get_process_set_ids,
    global_process_set,
    remove_process_set,
)
from .compression import Compression  # noqa: F401
from .optimizer import (  # noqa: F401
    DistributedOptimizer,
    ReduceSpec,
    grad,
    init_sharded_state,
    reduce_spec_of,
    reshard_opt_state,
    resolve_sync_mode,
    sharded_step_update,
    unshard_opt_state,
)
from .ops.collective_ops import cache_stats, run_comms_microprobe  # noqa: F401
from .functions import (  # noqa: F401
    allgather_object,
    broadcast_object,
    broadcast_optimizer_state,
    broadcast_parameters,
    join,
    masked_average,
    to_local,
)
from . import abort  # noqa: F401
from . import attribution  # noqa: F401
from .attribution import set_model_flops_per_step  # noqa: F401
from . import autotune  # noqa: F401
from . import comms_model  # noqa: F401
from . import memory  # noqa: F401
from .ops import comms_planner  # noqa: F401
from . import faults  # noqa: F401
from . import metrics  # noqa: F401
from . import peercheck  # noqa: F401
from . import profiler  # noqa: F401
from . import tracing  # noqa: F401
from . import callbacks  # noqa: F401
from . import elastic  # noqa: F401
from . import parallel  # noqa: F401
from .parallel import data_parallel  # noqa: F401
from .parallel.data_parallel import (  # noqa: F401
    DeferredParams,
    make_overlapped_train_step,
    overlap_gradient_sync,
    shard_state,
)
from .parallel.param_sharding import (  # noqa: F401
    ShardedParams,
    reshard_params,
    shard_params,
    unshard_params,
)
from .stall import fetch  # noqa: F401
from .sync_batch_norm import SyncBatchNorm  # noqa: F401
from .timeline import start_timeline, stop_timeline  # noqa: F401
