"""Small networking helpers shared by the launcher and the elastic driver
(parity: ``horovod/runner/util/network.py``)."""

from __future__ import annotations

import socket

LOCAL_NAMES = {"localhost", "127.0.0.1", "::1"}


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def is_local(hostname: str) -> bool:
    # The whole 127.0.0.0/8 block is the loopback device: any 127.x.y.z
    # literal names THIS machine (the kernel routes the full /8), which
    # is what lets the localhost-as-cluster test harness emulate more
    # distinct "hosts" than the three canonical local names — a shared
    # multi-job pool needs disjoint per-job host sets plus spares.
    return (hostname in LOCAL_NAMES
            or hostname.startswith("127.")
            or hostname == socket.gethostname())


def routable_addr() -> str:
    """This host's address as reachable from other machines."""
    try:
        return socket.gethostbyname(socket.gethostname())
    except OSError:
        return socket.gethostname()


def driver_addr(hostnames: list[str]) -> str:
    """The address workers use to reach services running in the launcher
    (rendezvous KV). Loopback only when the world is known-local (a
    NON-EMPTY all-local host list); otherwise this host's routable address
    — an empty/unknown list must assume remote workers."""
    if hostnames and all(is_local(h) for h in hostnames):
        return "127.0.0.1"
    return routable_addr()


def coordinator_addr(hostnames: list[str]) -> str:
    """The address of the jax.distributed coordinator — process 0's host."""
    first = hostnames[0]
    if first in LOCAL_NAMES:
        return "127.0.0.1"
    return first
