"""Multi-tenant pod: gang-scheduling, preemption-arbitrating scheduler.

One scheduler process owns a shared host pool and gang-schedules N jobs
(:class:`JobSpec` — ``min_np``/``max_np``/``priority``/per-job
``HOROVOD_TARGET_GOODPUT``) onto **disjoint** host sets, running the
existing elastic driver once per job as a subprocess. Nothing about the
single-job stack changes: each job keeps its own rendezvous KV server,
HMAC secret, driver-state dir (epoch fence), and lifecycle journal —
the scheduler composes whole drivers, it does not reach inside them.

Actuation is the discovery contract the driver already honors: each
job's ``--host-discovery-script`` reads a scheduler-owned **lease file**
(``<job>/hosts.txt``), so growing/shrinking/healing a job is a lease
rewrite the driver's 1 s discovery poll picks up and turns into a
generation fence. Shrinks additionally ride the preemption-notice scope
(``PUT /preempt/<host>`` on the job's KV) so the departing host drains
through the worker's final-commit path before the lease changes — the
same two-fence drain→reassign sequence a human operator would run.

The pool tier generalizes the driver's per-job ``HostManager``:

- **blacklist cooldowns are pool-wide** — a host condemned by job A's
  driver (its ``blacklist`` journal event) carries that evidence into
  the pool record and is never handed to job B inside the cooldown;
- **spares are pool-wide** — a surplus host from a shrunk/finished job
  re-enters the pool as a spare ANY job can promote at its next fence.

Cross-job arbitration lives in :class:`~horovod_tpu.elastic.policy.
JobArbiter` (same deliberate-only contract as ``PolicyController``):
when no pool spare can heal the job furthest under its goodput SLO, the
arbiter picks a victim — a one-host **shrink** (victim stays >= its
``min_np``) or a full **preempt** (victim drains entirely via SIGTERM
through final commits and re-queues), in priority order, guarded by
hysteresis/cooldown/pins so two starving jobs never trade hosts.

Every executed action journals **exactly one** ``sched_decision`` event
with the predicted AND realized goodput (realized is measured when the
recipient's republished world actually contains the capacity — the
``policy_decision`` finalize pattern). Observability: ``GET /metrics``
(``hvd_pool_*``, ``hvd_jobs_*``, ``hvd_sched_decisions_total`` —
zero-materialized — plus per-job gauges) and ``GET /pool`` (pool
membership + per-job world/goodput/SLO state). SIGTERM on the scheduler
drains every job through final commits.

Inert by construction: nothing imports this module on the single-job
path, and ``HOROVOD_JOB_ID`` (the env key stamped into each job's
process tree) is never set outside it.

Stdlib-only and jax-free: the scheduler runs on the pod controller
before any framework init, like the driver it launches.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import socketserver
import subprocess
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, HTTPServer
from typing import Any, Iterable
from urllib.request import Request, urlopen

from ... import faults
from ... import metrics as _metrics
from ...elastic.policy import ArbiterDecision, JobArbiter
from ...utils.env import get_float
from ...utils.logging import get_logger
from ..http.kv_server import AUTH_HEADER, PREEMPT_SCOPE, _auth_payload
from .. import secret as _secret
from . import driver_state

#: The env key that stamps a process tree with its scheduling key. Set by
#: the scheduler on every job driver (workers inherit it through the
#: driver's env block); NEVER set on the single-job path — every
#: multi-tenant branch in the stack gates on it.
ENV_JOB_ID = "HOROVOD_JOB_ID"

#: The `action` vocabulary of hvd_sched_decisions_total (and the
#: sched_decision journal event) — zero-materialized on every scrape.
SCHED_ACTIONS = ("grant", "shrink", "preempt", "promote")


@dataclasses.dataclass
class JobSpec:
    """One gang-scheduled job: the elastic window, the arbitration key,
    and the per-job SLO the scheduler heals toward."""

    job_id: str
    command: list[str]                  # the worker command line
    min_np: int                         # gang floor (whole hosts)
    max_np: int                         # elastic ceiling
    priority: int = 0                   # higher wins arbitration
    target_goodput: float | None = None  # per-job HOROVOD_TARGET_GOODPUT
    env: dict = dataclasses.field(default_factory=dict)
    cpu_mode: bool = True
    elastic_timeout: float = 600.0

    def __post_init__(self):
        if not self.job_id or "/" in self.job_id:
            raise ValueError(f"bad job_id {self.job_id!r}")
        if self.min_np < 1 or self.max_np < self.min_np:
            raise ValueError(
                f"job {self.job_id}: need 1 <= min_np <= max_np, got "
                f"{self.min_np}/{self.max_np}")


class HostPool:
    """The pool tier: every host the scheduler owns, with pool-wide
    condemnation evidence and cooldowns (generalizing the per-job
    ``HostManager`` blacklist) and pool-wide spares.

    A condemned record — ``{t, job, reason}`` — is the evidence a job's
    driver produced when it blacklisted the host; it rides the pool
    record so the host is never handed to ANOTHER job inside the
    cooldown (``HOROVOD_SCHED_BLACKLIST_COOLDOWN``, defaulting to the
    driver's ``HOROVOD_BLACKLIST_COOLDOWN``, 600 s; 0 = permanent).
    Expired condemnations re-enter the host as a pool spare, mirroring
    the driver's cooldown-return path.
    """

    def __init__(self, hosts: Iterable[str], slots: int = 1,
                 clock=time.monotonic):
        self._clock = clock
        self.cooldown_s = get_float(
            "HOROVOD_SCHED_BLACKLIST_COOLDOWN",
            get_float("HOROVOD_BLACKLIST_COOLDOWN", 600.0))
        self._lock = threading.Lock()
        self._hosts: dict[str, dict] = {}
        for h in hosts:
            name, _, s = str(h).partition(":")
            self._hosts[name] = {
                "slots": int(s) if s else slots,
                "job": None,
                "condemned": None,
            }

    # -- condemnation (pool-wide blacklist) ---------------------------------

    def condemn(self, host: str, job: str | None, reason: str) -> None:
        """Record a job driver's blacklist evidence pool-wide: the host
        leaves its job and cannot be assigned to ANY job inside the
        cooldown."""
        with self._lock:
            rec = self._hosts.get(host)
            if rec is None:
                return
            rec["job"] = None
            rec["condemned"] = {
                "t": self._clock(), "job": job, "reason": reason}

    def prune(self) -> list[str]:
        """Expire condemnations past the cooldown; returns the hosts
        that just re-entered the pool as spares (for journaling)."""
        if self.cooldown_s <= 0:
            return []
        now = self._clock()
        returned = []
        with self._lock:
            for name, rec in self._hosts.items():
                c = rec["condemned"]
                if c is not None and now - c["t"] >= self.cooldown_s:
                    rec["condemned"] = None
                    returned.append(name)
        return returned

    def condemned_record(self, host: str) -> dict | None:
        with self._lock:
            rec = self._hosts.get(host)
            c = rec and rec["condemned"]
            return dict(c) if c else None

    # -- assignment ----------------------------------------------------------

    def assign(self, host: str, job: str) -> bool:
        """Hand a free, un-condemned host to a job. Fires the
        ``pool.assign`` fault point — a drop returns False (the caller
        holds the host back for a later tick); ``raise`` propagates
        :class:`~horovod_tpu.faults.InjectedFault` for the caller's
        containment to prove the scheduler survives it."""
        if faults.fire(faults.POOL_ASSIGN):
            return False
        with self._lock:
            rec = self._hosts.get(host)
            if rec is None or rec["job"] is not None or rec["condemned"]:
                return False
            rec["job"] = job
            return True

    def release(self, host: str) -> None:
        """The host leaves its job WITHOUT evidence against it (shrink
        surplus, job exit): it re-enters immediately as a pool spare any
        job can promote."""
        with self._lock:
            rec = self._hosts.get(host)
            if rec is not None:
                rec["job"] = None

    # -- views ---------------------------------------------------------------

    def spares(self) -> list[str]:
        """Free, un-condemned hosts, stable order."""
        with self._lock:
            return [n for n, r in self._hosts.items()
                    if r["job"] is None and r["condemned"] is None]

    def assigned_to(self, job: str) -> list[str]:
        with self._lock:
            return [n for n, r in self._hosts.items() if r["job"] == job]

    def slots_of(self, host: str) -> int:
        with self._lock:
            rec = self._hosts.get(host)
            return rec["slots"] if rec else 1

    def counts(self) -> dict:
        with self._lock:
            hosts = len(self._hosts)
            blacklisted = sum(
                1 for r in self._hosts.values() if r["condemned"])
            spares = sum(1 for r in self._hosts.values()
                         if r["job"] is None and not r["condemned"])
        return {"hosts": hosts, "spares": spares,
                "blacklisted": blacklisted}

    def export(self) -> list[dict]:
        """Per-host membership for ``GET /pool`` (condemnation ages are
        relative, like the driver's blacklist export, so the view is
        meaningful across restarts)."""
        now = self._clock()
        out = []
        with self._lock:
            for name, rec in sorted(self._hosts.items()):
                c = rec["condemned"]
                out.append({
                    "host": name,
                    "slots": rec["slots"],
                    "job": rec["job"],
                    "condemned": ({
                        "age_s": round(now - c["t"], 3),
                        "job": c["job"],
                        "reason": c["reason"],
                    } if c else None),
                })
        return out


class _JobHandle:
    """Scheduler-internal state for one job: the lease, the driver
    subprocess, and the journal-tail cursor."""

    def __init__(self, spec: JobSpec, root: str, index: int):
        self.spec = spec
        self.index = index
        self.state = "pending"   # pending|running|preempting|done|failed
        self.dir = os.path.join(root, spec.job_id)
        os.makedirs(self.dir, exist_ok=True)
        self.lease_path = os.path.join(self.dir, "hosts.txt")
        self.script_path = os.path.join(self.dir, "discover.sh")
        self.state_dir = os.path.join(self.dir, "state")
        self.journal_path = os.path.join(self.dir, "events.jsonl")
        self.log_path = os.path.join(self.dir, "driver.log")
        self.secret = _secret.make_secret_key()
        self.lease: list[str] = []
        self.proc: subprocess.Popen | None = None
        self.log_fh = None
        self.journal_offset = 0
        self.world: dict | None = None   # latest world_published facts
        self.rc: int | None = None
        self.not_before = 0.0            # requeue backoff (monotonic)
        with open(self.script_path, "w", encoding="utf-8") as f:
            f.write(f"#!/bin/sh\ncat {self.lease_path}\n")
        os.chmod(self.script_path, 0o755)

    @property
    def job_id(self) -> str:
        return self.spec.job_id

    def granted_np(self) -> int:
        return len(self.lease)

    def goodput(self) -> float:
        return JobArbiter.goodput_of(len(self.lease), self.spec.max_np)


class MultiJobScheduler:
    """The gang scheduler: owns the pool, runs one elastic driver per
    job, heals with pool spares, arbitrates with :class:`JobArbiter`,
    and serves ``GET /metrics`` + ``GET /pool``."""

    def __init__(self, jobs: Iterable[JobSpec], hosts: Iterable[str],
                 workdir: str, tick: float | None = None,
                 clock=time.monotonic, http_port: int | None = None):
        self._clock = clock
        self._log = get_logger()
        self._tick_s = (get_float("HOROVOD_SCHED_TICK", 1.0)
                        if tick is None else tick)
        self._realize_timeout = get_float(
            "HOROVOD_SCHED_REALIZE_TIMEOUT", 120.0)
        self._requeue_backoff = get_float(
            "HOROVOD_SCHED_REQUEUE_BACKOFF", 5.0)
        self._root = workdir
        os.makedirs(workdir, exist_ok=True)
        self._pool = HostPool(hosts)
        self._arbiter = JobArbiter(clock=clock)
        self._lock = threading.RLock()
        self._jobs: dict[str, _JobHandle] = {}
        for i, spec in enumerate(jobs):
            if spec.job_id in self._jobs:
                raise ValueError(f"duplicate job_id {spec.job_id!r}")
            self._jobs[spec.job_id] = _JobHandle(spec, workdir, i)
        self._pending: list[dict] = []   # in-flight actions to realize
        self._decisions = {a: 0 for a in SCHED_ACTIONS}
        self._preempted_total = 0
        self._stop = False
        self._drain_signaled = False
        self._httpd: HTTPServer | None = None
        self._http_thread: threading.Thread | None = None
        self._http_port = http_port
        self.port: int | None = None

    # -- HTTP observability --------------------------------------------------

    def metrics_text(self) -> str:
        """The scheduler's own Prometheus scrape: pool/job gauges and the
        decision counter, all zero-materialized so dashboards can tell
        'no decisions yet' from 'not measuring'."""
        with self._lock:
            counts = self._pool.counts()
            running = [h for h in self._jobs.values()
                       if h.state in ("running", "preempting")]
            decisions = dict(self._decisions)
            preempted = self._preempted_total
            job_np = [({"job": h.job_id}, h.granted_np()) for h in running]
            job_gp = [({"job": h.job_id}, h.goodput()) for h in running]
        fams = [
            _metrics.make_family(
                "hvd_pool_hosts", "gauge",
                "Hosts owned by the multi-tenant pool scheduler.",
                [({}, counts["hosts"])]),
            _metrics.make_family(
                "hvd_pool_spares", "gauge",
                "Pool hosts currently free and assignable to any job.",
                [({}, counts["spares"])]),
            _metrics.make_family(
                "hvd_pool_blacklisted", "gauge",
                "Pool hosts inside a pool-wide condemnation cooldown "
                "(evidence carried from the condemning job's driver).",
                [({}, counts["blacklisted"])]),
            _metrics.make_family(
                "hvd_jobs_running", "gauge",
                "Jobs currently holding a lease on the pool.",
                [({}, len(running))]),
            _metrics.make_family(
                "hvd_jobs_preempted_total", "counter",
                "Full-job preemptions executed by the scheduler "
                "(victim drained through final commits and re-queued).",
                [({}, preempted)]),
            _metrics.make_family(
                "hvd_sched_decisions_total", "counter",
                "Scheduler decisions executed, by action "
                "(grant|shrink|preempt|promote).",
                [({"action": a}, decisions[a]) for a in SCHED_ACTIONS]),
            _metrics.make_family(
                "hvd_job_np", "gauge",
                "Hosts currently leased to each running job (the job "
                "dimension of the pool).", job_np),
            _metrics.make_family(
                "hvd_job_goodput_ratio", "gauge",
                "Capacity goodput of each running job: leased hosts / "
                "max_np — what the arbiter compares to the job's "
                "HOROVOD_TARGET_GOODPUT.", job_gp),
        ]
        return _metrics.render_families([({}, fams)])

    def pool_state(self) -> dict:
        """The ``GET /pool`` body: pool membership plus per-job
        world/goodput/SLO state."""
        with self._lock:
            jobs = {}
            for h in self._jobs.values():
                arb = self._arbiter.job_state(h.job_id)
                jobs[h.job_id] = {
                    "state": h.state,
                    "priority": h.spec.priority,
                    "min_np": h.spec.min_np,
                    "max_np": h.spec.max_np,
                    "target_goodput": h.spec.target_goodput,
                    "lease": list(h.lease),
                    "goodput": round(h.goodput(), 6),
                    "world": dict(h.world) if h.world else None,
                    "rc": h.rc,
                    "arbiter": arb,
                }
            return {
                "hosts": self._pool.export(),
                "spares": self._pool.spares(),
                "jobs": jobs,
                "decisions": dict(self._decisions),
                "preempted_total": self._preempted_total,
            }

    def _start_http(self) -> None:
        sched = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # noqa: D102 — quiet server
                pass

            def _send(self, code, body, ctype):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 — http.server contract
                if self.path == "/metrics":
                    self._send(200, sched.metrics_text().encode(),
                               "text/plain; version=0.0.4")
                elif self.path == "/pool":
                    self._send(200, json.dumps(
                        sched.pool_state()).encode(), "application/json")
                else:
                    self._send(404, b"not found", "text/plain")

        class Server(socketserver.ThreadingMixIn, HTTPServer):
            daemon_threads = True

        self._httpd = Server(("0.0.0.0", self._http_port or 0), Handler)
        self.port = self._httpd.server_address[1]
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, name="sched-http",
            daemon=True)
        self._http_thread.start()

    # -- lease + driver actuation -------------------------------------------

    def _write_lease(self, job: _JobHandle) -> None:
        tmp = job.lease_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            for h in job.lease:
                f.write(f"{h}:{self._pool.slots_of(h)}\n")
        os.replace(tmp, job.lease_path)

    def _launch_driver(self, job: _JobHandle) -> None:
        spec = job.spec
        env = dict(os.environ)
        env.update(spec.env)
        env.update({
            ENV_JOB_ID: spec.job_id,
            "HOROVOD_SECRET_KEY": job.secret,
            driver_state.ENV_STATE_DIR: job.state_dir,
            "HOROVOD_EVENT_LOG": job.journal_path,
        })
        if spec.target_goodput is not None:
            env["HOROVOD_TARGET_GOODPUT"] = str(spec.target_goodput)
        else:
            env.pop("HOROVOD_TARGET_GOODPUT", None)
        # The driver must resolve horovod_tpu the way THIS process did
        # (checkout runs aren't pip-installed): prepend our import root.
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))))
        pp = env.get("PYTHONPATH", "")
        if pkg_root not in pp.split(os.pathsep):
            env["PYTHONPATH"] = (f"{pkg_root}{os.pathsep}{pp}" if pp
                                 else pkg_root)
        cmd = [sys.executable, "-m", "horovod_tpu.runner",
               "--host-discovery-script", job.script_path,
               "--min-np", str(spec.min_np),
               "--max-np", str(spec.max_np),
               "--elastic-timeout", str(spec.elastic_timeout)]
        if spec.cpu_mode:
            cmd.append("--cpu-mode")
        cmd += list(spec.command)
        job.log_fh = open(job.log_path, "ab")
        # Its own session: pod-level signals reach job drivers only
        # through the scheduler's drain path, never as a group side
        # effect — each driver owns SIGTERM semantics for its workers.
        job.proc = subprocess.Popen(
            cmd, env=env, stdout=job.log_fh,
            stderr=subprocess.STDOUT, start_new_session=True)
        job.state = "running"
        job.world = None
        job.rc = None
        _metrics.event("sched_job", job=job.job_id, state="launched",
                       hosts=list(job.lease), pid=job.proc.pid)
        self._log.warning(
            "sched: launched job %s on %s (pid %d)", job.job_id,
            job.lease, job.proc.pid)

    def _signed_preempt_put(self, job: _JobHandle, host: str) -> bool:
        """``PUT /preempt/<host>`` on the victim job's rendezvous KV,
        signed with THAT job's secret (the scheduler holds every job's
        key — it minted them). The driver's next policy tick drains the
        host through the worker's final commit."""
        ep = driver_state.read_endpoint(job.state_dir)
        if ep is None:
            return False
        path = f"/{PREEMPT_SCOPE}/{host}"
        body = json.dumps({"reason": "scheduler shrink",
                           "by": "multi-job-scheduler"}).encode()
        req = Request(f"http://{ep['addr']}:{ep['port']}{path}",
                      data=body, method="PUT")
        tag = _secret.sign(_auth_payload("PUT", path, body),
                           key=job.secret.encode())
        if tag:
            req.add_header(AUTH_HEADER, tag)
        try:
            with urlopen(req, timeout=10.0):
                return True
        except OSError:
            return False

    # -- journal ingestion (the scheduler's sensors) -------------------------

    def _ingest_journals(self) -> None:
        for job in self._jobs.values():
            if job.state not in ("running", "preempting"):
                continue
            try:
                with open(job.journal_path, "rb") as f:
                    f.seek(job.journal_offset)
                    chunk = f.read()
            except OSError:
                continue
            if not chunk:
                continue
            # Complete lines only: a concurrent writer may be mid-line.
            upto = chunk.rfind(b"\n")
            if upto < 0:
                continue
            job.journal_offset += upto + 1
            for line in chunk[:upto].split(b"\n"):
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                self._handle_job_event(job, rec)

    def _handle_job_event(self, job: _JobHandle, rec: dict) -> None:
        event = rec.get("event")
        if event == "world_published":
            job.world = {
                "np": rec.get("np"),
                "hosts": rec.get("hosts"),
                "generation": rec.get("generation"),
            }
        elif event == "blacklist":
            host = rec.get("host")
            reason = str(rec.get("reason", ""))
            if not host:
                return
            # A blacklist the scheduler itself caused (the shrink's
            # preempt-drain) is drain-completion, not evidence.
            for p in self._pending:
                if (p["action"] == "shrink" and p["stage"] == "drain"
                        and p["victim"] == job.job_id
                        and p["host"] == host):
                    p["stage"] = "reassign"
                    return
            if host in job.lease:
                # Pool-wide condemnation: the evidence (the driver's
                # blacklist reason) rides the pool record, so no other
                # job is handed this host inside the cooldown.
                self._pool.condemn(host, job.job_id, reason)
                job.lease.remove(host)
                self._write_lease(job)
                _metrics.event(
                    "sched_pool", job=job.job_id, host=host,
                    change="condemned", reason=reason)
                self._log.warning(
                    "sched: pool condemned %s (evidence from job %s: %s)",
                    host, job.job_id, reason)

    # -- tick phases ---------------------------------------------------------

    def _reap(self) -> None:
        for job in self._jobs.values():
            if job.proc is None or job.proc.poll() is None:
                continue
            job.rc = job.proc.returncode
            job.proc = None
            if job.log_fh is not None:
                job.log_fh.close()
                job.log_fh = None
            for host in list(job.lease):
                self._pool.release(host)
            job.lease = []
            self._write_lease(job)
            self._arbiter.forget_job(job.job_id)
            if job.state == "preempting":
                # The drained victim re-queues; its sched_decision event
                # realizes now (goodput 0 until re-granted).
                self._preempted_total += 1
                for p in self._pending:
                    if (p["action"] == "preempt"
                            and p["victim"] == job.job_id
                            and p["stage"] == "drain"):
                        p["stage"] = "realized"
                        p["realized"] = {"victim_rc": job.rc,
                                         "victim_goodput": 0.0}
                job.state = "pending"
                job.not_before = self._clock() + self._requeue_backoff
                _metrics.event("sched_job", job=job.job_id,
                               state="requeued", rc=job.rc)
            else:
                job.state = "done" if job.rc == 0 else "failed"
                _metrics.event("sched_job", job=job.job_id,
                               state="exit", rc=job.rc)
                self._log.warning("sched: job %s exited rc=%s",
                                  job.job_id, job.rc)

    def _prune_pool(self) -> None:
        for host in self._pool.prune():
            _metrics.event("sched_pool", host=host, change="returned",
                           reason="condemnation cooldown expired")

    def _grant_pending(self) -> None:
        now = self._clock()
        waiting = sorted(
            (j for j in self._jobs.values()
             if j.state == "pending" and now >= j.not_before),
            key=lambda j: (-j.spec.priority, j.index))
        for job in waiting:
            spares = self._pool.spares()
            if len(spares) < job.spec.min_np:
                continue
            granted = []
            for host in spares:
                if len(granted) >= job.spec.min_np:
                    break
                try:
                    if self._pool.assign(host, job.job_id):
                        granted.append(host)
                except faults.InjectedFault as e:
                    self._log.warning(
                        "sched: pool.assign fault (%s); holding %s back",
                        e, host)
            if len(granted) < job.spec.min_np:
                for host in granted:     # partial gang: give it back
                    self._pool.release(host)
                continue
            job.lease = granted
            self._write_lease(job)
            self._admission_memory_check(job)
            self._launch_driver(job)
            self._pending.append({
                "action": "grant", "job": job.job_id, "victim": None,
                "host": None, "stage": "adopt",
                "reason": (f"gang grant of {job.spec.min_np} pool hosts "
                           f"at priority {job.spec.priority}"),
                "predicted": {"goodput_after": job.goodput(),
                              "target_goodput": job.spec.target_goodput},
                "deadline": now + self._realize_timeout,
            })

    def _admission_memory_check(self, job: "_JobHandle") -> None:
        """Advisory HBM admission check at grant time: compare the
        job's declared per-rank footprint (``HOROVOD_HBM_PREDICTED_BYTES``
        in its env block — e.g. a prior run's ``predict_footprint``)
        against the pool's advertised per-device HBM
        (``HOROVOD_SCHED_HOST_HBM_BYTES``, falling back to the job's own
        ``HOROVOD_HBM_BYTES_PER_DEVICE``). A predicted overrun journals
        ONE ``admission_memory_risk`` event naming the deficit — the
        grant itself is NEVER changed (with both knobs unset this is a
        no-op, and scheduling decisions stay bit-for-bit identical)."""
        try:
            from ... import memory as _memory

            predicted = job.spec.env.get("HOROVOD_HBM_PREDICTED_BYTES")
            capacity = (os.environ.get("HOROVOD_SCHED_HOST_HBM_BYTES")
                        or job.spec.env.get("HOROVOD_HBM_BYTES_PER_DEVICE"))
            risk = _memory.admission_check(
                int(predicted) if predicted else None,
                int(capacity) if capacity else None)
            if risk is not None:
                self._log.warning(
                    "sched: job %s predicts %d bytes/rank against %d "
                    "bytes of host HBM (deficit %d); granting anyway "
                    "(advisory)", job.job_id, risk["predicted_bytes"],
                    risk["capacity_bytes"], risk["deficit_bytes"])
                _metrics.event("admission_memory_risk", job=job.job_id,
                               **risk)
        except Exception:  # noqa: BLE001 — advisory only, never blocks
            pass

    def _deficit_order(self) -> list[_JobHandle]:
        """Running jobs by healing urgency (the arbiter's recipient
        ordering): furthest under SLO first. Computed directly from the
        spec and the live lease — NOT from the arbiter's observation
        history, which is empty until the first arbitration pass, while
        spare promotion must already order correctly on the very tick
        the gangs are granted."""
        def key(job: _JobHandle):
            deficit = JobArbiter._deficit({
                "granted": job.granted_np(),
                "min_np": job.spec.min_np,
                "max_np": job.spec.max_np,
                "target": job.spec.target_goodput,
            })
            return (-deficit, -job.spec.priority, job.index)
        return sorted((j for j in self._jobs.values()
                       if j.state == "running"), key=key)

    def _promote_spares(self) -> None:
        """Pool healing: idle spares flow to running jobs below their
        ``max_np``, furthest-under-SLO first — a condemned host's
        replacement joins at the job's next generation fence."""
        now = self._clock()
        progress = True
        while progress:
            progress = False
            spares = self._pool.spares()
            if not spares:
                return
            for job in self._deficit_order():
                if job.granted_np() >= job.spec.max_np:
                    continue
                host = spares[0]
                try:
                    if not self._pool.assign(host, job.job_id):
                        continue
                except faults.InjectedFault as e:
                    self._log.warning(
                        "sched: pool.assign fault (%s); holding %s back",
                        e, host)
                    continue
                before = job.goodput()
                job.lease.append(host)
                self._write_lease(job)
                self._pending.append({
                    "action": "promote", "job": job.job_id,
                    "victim": None, "host": host, "stage": "adopt",
                    "reason": f"pool spare {host} promoted into "
                              f"{job.job_id}",
                    "predicted": {
                        "goodput_before": round(before, 6),
                        "goodput_after": round(job.goodput(), 6),
                        "target_goodput": job.spec.target_goodput},
                    "deadline": now + self._realize_timeout,
                })
                self._log.warning(
                    "sched: promoted spare %s into job %s", host,
                    job.job_id)
                progress = True
                break

    def _arbitrate(self) -> None:
        running = [j for j in self._jobs.values() if j.state == "running"]
        for job in running:
            self._arbiter.note_job(
                job.job_id, job.granted_np(), job.spec.min_np,
                job.spec.max_np, priority=job.spec.priority,
                target=job.spec.target_goodput)
        if len(running) < 2:
            return
        if any(p["action"] in ("shrink", "preempt")
               and p["stage"] != "realized" for p in self._pending):
            return  # one capacity surgery at a time
        try:
            decision = self._arbiter.decide(len(self._pool.spares()))
        except faults.InjectedFault as e:
            # sched.decide raise mode: a broken arbiter must never take
            # the scheduler (and every job under it) down with it.
            self._log.error("sched: arbiter pass failed (%s); holding", e)
            return
        if decision is None:
            return
        if decision.action == "shrink":
            self._actuate_shrink(decision)
        else:
            self._actuate_preempt(decision)

    def _actuate_shrink(self, decision: ArbiterDecision) -> None:
        victim = self._jobs[decision.victim]
        if not victim.lease:
            return
        host = victim.lease[-1]
        if not self._signed_preempt_put(victim, host):
            self._log.warning(
                "sched: shrink of %s deferred — no reachable endpoint "
                "for its driver yet", victim.job_id)
            return
        self._arbiter.record_action(decision)
        self._pending.append({
            "action": "shrink", "job": decision.recipient,
            "victim": decision.victim, "host": host, "stage": "drain",
            "reason": decision.reason, "predicted": decision.predicted,
            "deadline": self._clock() + self._realize_timeout,
        })
        self._log.warning(
            "sched: shrinking job %s by %s to heal %s (%s)",
            decision.victim, host, decision.recipient, decision.reason)

    def _actuate_preempt(self, decision: ArbiterDecision) -> None:
        victim = self._jobs[decision.victim]
        if victim.proc is None:
            return
        try:
            if faults.fire(faults.JOB_PREEMPT):
                return  # injected drop: the preemption never happens
        except faults.InjectedFault as e:
            self._log.error("sched: job.preempt fault (%s); holding", e)
            return
        self._arbiter.record_action(decision)
        victim.state = "preempting"
        # SIGTERM the victim's DRIVER: its forwarder drains every worker
        # through a final commit, then the driver exits 0 — the job's
        # state survives for the re-grant.
        victim.proc.send_signal(signal.SIGTERM)
        self._pending.append({
            "action": "preempt", "job": decision.recipient,
            "victim": decision.victim, "host": None, "stage": "drain",
            "reason": decision.reason, "predicted": decision.predicted,
            "deadline": self._clock() + self._realize_timeout,
        })
        self._log.warning(
            "sched: preempting job %s to heal %s (%s)", decision.victim,
            decision.recipient, decision.reason)

    def _finalize_pending(self) -> None:
        """Advance in-flight actions toward their realized measurement;
        each emits EXACTLY ONE ``sched_decision`` journal event, with
        predicted + realized goodput, when its effect is observed in the
        recipient's republished world (the ``policy_decision`` finalize
        contract)."""
        now = self._clock()
        done: list[dict] = []
        for p in self._pending:
            job = self._jobs.get(p["job"])
            if p["action"] == "shrink" and p["stage"] == "reassign":
                victim = self._jobs[p["victim"]]
                if p["host"] in victim.lease:
                    victim.lease.remove(p["host"])
                    self._write_lease(victim)
                self._pool.release(p["host"])
                try:
                    assigned = self._pool.assign(p["host"], p["job"])
                except faults.InjectedFault:
                    assigned = False
                if not assigned:
                    continue  # held back; retried next tick
                if job is not None:
                    job.lease.append(p["host"])
                    self._write_lease(job)
                p["stage"] = "adopt"
            if p["stage"] == "adopt" and job is not None:
                world = job.world or {}
                hosts = world.get("hosts") or []
                adopted = (
                    p["host"] in hosts if p["host"] is not None
                    else (world.get("np") or 0) >= job.spec.min_np)
                if adopted:
                    realized = {
                        "goodput": round(job.goodput(), 6),
                        "np": world.get("np"),
                        "generation": world.get("generation"),
                    }
                    if p["action"] == "shrink":
                        victim = self._jobs[p["victim"]]
                        realized["victim_goodput"] = round(
                            victim.goodput(), 6)
                    p["realized"] = realized
                    p["stage"] = "realized"
            if p["stage"] == "realized":
                self._emit_decision(p)
                done.append(p)
            elif now >= p["deadline"]:
                # Never realized inside the window: emit honestly with
                # realized=null rather than pretending or re-emitting.
                p["realized"] = None
                self._emit_decision(p)
                done.append(p)
        for p in done:
            self._pending.remove(p)

    def _emit_decision(self, p: dict) -> None:
        self._decisions[p["action"]] += 1
        _metrics.event(
            "sched_decision", action=p["action"], job=p["job"],
            victim=p["victim"], host=p["host"], reason=p["reason"],
            predicted=p["predicted"], realized=p.get("realized"))

    # -- lifecycle -----------------------------------------------------------

    def _request_stop(self, *_args) -> None:
        self._stop = True

    def _drain_all(self) -> None:
        if self._drain_signaled:
            return
        self._drain_signaled = True
        running = [j for j in self._jobs.values() if j.proc is not None]
        _metrics.event("sched_drain", jobs=[j.job_id for j in running])
        self._log.warning(
            "sched: SIGTERM — draining %d job(s) through final commits",
            len(running))
        for job in running:
            try:
                job.proc.send_signal(signal.SIGTERM)
            except OSError:
                pass

    def tick(self) -> None:
        """One scheduling pass (public for unit tests)."""
        with self._lock:
            self._reap()
            self._ingest_journals()
            self._prune_pool()
            if not self._stop:
                self._grant_pending()
                self._promote_spares()
                self._arbitrate()
            self._finalize_pending()

    def _all_settled(self) -> bool:
        return all(j.state in ("done", "failed") for j in
                   self._jobs.values())

    def _all_reaped(self) -> bool:
        return all(j.proc is None for j in self._jobs.values())

    def run(self) -> int:
        """Schedule until every job completes (or SIGTERM drains the
        pod). Returns 0 iff every job finished rc=0 (a drained pod
        counts: final commits landed)."""
        if threading.current_thread() is threading.main_thread():
            signal.signal(signal.SIGTERM, self._request_stop)
            signal.signal(signal.SIGINT, self._request_stop)
        self._start_http()
        counts = self._pool.counts()
        _metrics.event(
            "sched_start", jobs=sorted(self._jobs), port=self.port,
            pool_hosts=counts["hosts"])
        self._log.warning(
            "sched: multi-tenant pod up — %d job(s), %d host(s), "
            "http :%d", len(self._jobs), counts["hosts"], self.port)
        try:
            while True:
                if self._stop:
                    self._drain_all()
                self.tick()
                if self._stop and self._all_reaped():
                    break
                if not self._stop and self._all_settled():
                    break
                time.sleep(self._tick_s)
            rcs = {j.job_id: j.rc for j in self._jobs.values()}
            _metrics.event("sched_stop", rcs=rcs,
                           drained=self._drain_signaled)
            ok = all(rc == 0 for rc in rcs.values())
            return 0 if ok else 1
        finally:
            if self._httpd is not None:
                self._httpd.shutdown()
                self._httpd.server_close()


def _specs_from_config(config: dict) -> list[JobSpec]:
    return [JobSpec(
        job_id=str(j["job_id"]),
        command=list(j["command"]),
        min_np=int(j["min_np"]),
        max_np=int(j["max_np"]),
        priority=int(j.get("priority", 0)),
        target_goodput=(float(j["target_goodput"])
                        if j.get("target_goodput") is not None else None),
        env={str(k): str(v) for k, v in (j.get("env") or {}).items()},
        cpu_mode=bool(j.get("cpu_mode", True)),
        elastic_timeout=float(j.get("elastic_timeout", 600.0)),
    ) for j in config["jobs"]]


def main(argv: list[str] | None = None) -> int:
    """``python -m horovod_tpu.runner.elastic.scheduler pod.json``:
    the config document carries ``{"hosts": [...], "workdir": ...,
    "jobs": [{job_id, command, min_np, max_np, priority,
    target_goodput, env, cpu_mode, elastic_timeout}, ...]}``."""
    import argparse

    p = argparse.ArgumentParser(
        prog="horovod-scheduler",
        description="Gang-schedule N elastic jobs onto one host pool.")
    p.add_argument("config", help="pod config JSON (hosts + jobs)")
    p.add_argument("--workdir", default=None,
                   help="override the config's workdir")
    p.add_argument("--http-port", type=int, default=None)
    args = p.parse_args(argv)
    with open(args.config, encoding="utf-8") as f:
        config = json.load(f)
    workdir = args.workdir or config.get("workdir") or os.path.join(
        os.path.dirname(os.path.abspath(args.config)), "pod")
    sched = MultiJobScheduler(
        _specs_from_config(config), config["hosts"], workdir,
        http_port=args.http_port)
    return sched.run()


if __name__ == "__main__":
    sys.exit(main())
