"""Shared driver↔worker constants (parity:
``horovod/runner/elastic/constants.py``)."""

# Exit code for a worker whose host was dropped from the world: neither
# success (which would end the whole job) nor failure (which would
# blacklist a healthy host).
EXIT_REMOVED = 202

# Exit code for a worker that gave up on a lost driver: the rendezvous KV
# stayed unreachable past HOROVOD_ELASTIC_DRIVER_LOST_TIMEOUT. Distinct
# from EXIT_REMOVED so an operator (or a supervising scheduler) can tell
# "the driver dropped me" from "the driver vanished" at a glance.
EXIT_DRIVER_LOST = 203

# Exit code for a worker whose stall inspector crossed the shutdown
# deadline but whose MAIN THREAD never acted on the interrupt (wedged in
# an uninterruptible C/XLA call — signal handlers only run between Python
# bytecodes). The inspector's deadman timer hard-exits with this code so
# the driver reaps, blacklists, and re-forms the world without the host;
# its heartbeats alone would have kept it looking alive forever.
EXIT_STALL_ABANDONED = 204

# Exit code for a DRIVER that discovered it was superseded: a newer
# driver epoch owns the durable control-plane state (driver_state.py),
# meaning a supervisor already relaunched the control plane — typically
# after this driver was SIGSTOP'd/partitioned through its own liveness
# deadline. The stale driver stands down WITHOUT terminating its former
# workers (the successor adopted them); killing them would be sabotage.
EXIT_DRIVER_SUPERSEDED = 205

# Consecutive KV poll failures before the worker escalates its logging
# from debug to warning (the first couple of blips are routine — a driver
# mid-reconfiguration answers late; a streak is a signal).
POLL_FAILURE_WARN_AFTER = 3
