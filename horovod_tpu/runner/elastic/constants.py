"""Shared driver↔worker constants (parity:
``horovod/runner/elastic/constants.py``)."""

# Exit code for a worker whose host was dropped from the world: neither
# success (which would end the whole job) nor failure (which would
# blacklist a healthy host).
EXIT_REMOVED = 202

# Exit code for a worker that gave up on a lost driver: the rendezvous KV
# stayed unreachable past HOROVOD_ELASTIC_DRIVER_LOST_TIMEOUT. Distinct
# from EXIT_REMOVED so an operator (or a supervising scheduler) can tell
# "the driver dropped me" from "the driver vanished" at a glance.
EXIT_DRIVER_LOST = 203

# Consecutive KV poll failures before the worker escalates its logging
# from debug to warning (the first couple of blips are routine — a driver
# mid-reconfiguration answers late; a streak is a signal).
POLL_FAILURE_WARN_AFTER = 3
