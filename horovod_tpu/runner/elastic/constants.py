"""Shared driver↔worker constants (parity:
``horovod/runner/elastic/constants.py``)."""

# Exit code for a worker whose host was dropped from the world: neither
# success (which would end the whole job) nor failure (which would
# blacklist a healthy host).
EXIT_REMOVED = 202
