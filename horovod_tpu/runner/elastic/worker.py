"""Worker-side elastic plumbing: world-version polling, heartbeats,
assignment fetch.

Parity with ``horovod/runner/elastic/worker.py`` (``WorkerNotificationClient``
/ ``WorkerNotificationService``), inverted for the KV-polling contract (see
``driver.py``): instead of the driver pushing to a TCP listener in every
worker, workers poll the rendezvous KV's world version — a bump arms
``notification_manager`` so the next ``state.commit()`` raises
``HostsUpdatedInterrupt`` (SURVEY.md §4.4 recovery loop).

Liveness plane (the hung-host gap): alongside the poller, each worker
publishes a heartbeat to ``PUT /heartbeat/<host>`` every
``HOROVOD_ELASTIC_HEARTBEAT_INTERVAL`` seconds, piggybacking its step and
commit counters. The driver's monitor declares a host dead after
``HOROVOD_ELASTIC_HEARTBEAT_TIMEOUT`` of silence — a SIGSTOP'd process, a
wedged TPU VM, or a livelocked trainer all stop heartbeating (every thread
freezes), which ``popen.poll()`` alone can never see.

Driver-loss escalation: the poll loop counts consecutive KV failures,
raises its logging to ``warning`` after ``POLL_FAILURE_WARN_AFTER``, and —
once failures have been continuous for
``HOROVOD_ELASTIC_DRIVER_LOST_TIMEOUT`` seconds — exits the process with
``EXIT_DRIVER_LOST`` instead of polling a dead driver forever (the main
thread may be wedged in a collective precisely because the world died, so
the poller owns the exit).
"""

from __future__ import annotations

import json
import os
import threading
import time

from ... import abort, faults
from ... import metrics as _metrics
from ...elastic.runner import notification_manager
from ...utils.env import get_float
from ...utils.logging import get_logger
from ..http.kv_server import HEARTBEAT_SCOPE, KVClient
from .constants import EXIT_DRIVER_LOST, POLL_FAILURE_WARN_AFTER


def elastic_enabled() -> bool:
    return os.environ.get("HOROVOD_ELASTIC", "") == "1"


def spare_mode() -> bool:
    """True when this worker was launched as a WARM SPARE: discovered,
    heartbeating, framework-imported, but deliberately excluded from the
    world until the driver publishes an epoch that includes its host."""
    return os.environ.get("HOROVOD_SPARE", "") == "1"


class _HeartbeatCounters:
    """Process-wide progress counters piggybacked on every heartbeat, so
    the driver's liveness record doubles as a progress trace."""

    __slots__ = ("steps", "commits", "last_commit_pc")

    def __init__(self):
        self.steps = 0
        self.commits = 0
        # perf_counter stamp of the last landed commit: the goodput
        # ledger splits a failed attempt at this point — productive up to
        # the last commit, lost{failed_attempt} after it.
        self.last_commit_pc: float | None = None


_counters = _HeartbeatCounters()


def record_step() -> None:
    _counters.steps += 1


def record_commit() -> None:
    _counters.commits += 1
    _counters.last_commit_pc = time.perf_counter()


class ElasticWorkerContext:
    """This worker's view of the elastic world, refreshed per epoch."""

    def __init__(self, on_driver_lost=None):
        addr = os.environ["HOROVOD_RENDEZVOUS_ADDR"]
        port = int(os.environ["HOROVOD_RENDEZVOUS_PORT"])
        self.hostname = os.environ.get("HOROVOD_HOSTNAME", "localhost")
        # Every client stamps writes with this worker's live generation
        # view, so the server's fence can reject a zombie's replays (a
        # SIGSTOP'd-through-recovery worker resumes with a stale version).
        gen_fn = lambda: self.version  # noqa: E731
        self.client = KVClient(addr, port, generation_fn=gen_fn)
        # Dedicated heartbeat client: ONE attempt, short timeout. The beat
        # loop itself is the retry — a beat that inherited the full KV
        # retry budget (3 × 10s timeout + backoff) could block the sender
        # past the driver's heartbeat deadline and get a healthy worker
        # killed for the very silence the budget was absorbing.
        self._hb_client = KVClient(addr, port, timeout=2.0, retries=1,
                                   generation_fn=gen_fn)
        # Dedicated abort-poll client, same 1-attempt/2s discipline: the
        # abort poll bounds wedged survivors' unblock latency and must
        # never stretch it by inheriting the fat retry budget.
        self._abort_client = KVClient(addr, port, timeout=2.0, retries=1,
                                      generation_fn=gen_fn)
        self.version = int(os.environ.get("HOROVOD_WORLD_VERSION", "0"))
        # The generation this worker last actually JOINED (fetch_assignment)
        # — distinct from `version`, which the poll loop advances the
        # moment the driver bumps the epoch. The abort monitor must poll
        # the JOINED generation: a survivor wedged in world g's collectives
        # is still in world g even after its poller has seen g+1 announced.
        self.joined_version = self.version
        # True while a warm spare is parked on the assignment wait: it
        # has no world rank yet, so its tracer must not ship (its dummy
        # launch-env rank label would collide with a real rank's in the
        # skew attribution).
        self.parked = False
        self.consecutive_poll_failures = 0
        self._on_driver_lost = on_driver_lost or self._exit_driver_lost
        self._poller: threading.Thread | None = None
        self._heartbeater: threading.Thread | None = None
        self._abort_poller: threading.Thread | None = None
        self._stop = threading.Event()

    def fetch_assignment(self, version: int | None = None) -> dict:
        """Read this host's assignment for a world version (JSON dict with
        process_id / num_processes / coordinator / slots / hosts).

        Raises ``RemovedFromWorldError`` when the epoch exists but this host
        is not in it, and ``HorovodInternalError`` for transient KV failures
        (driver restarting / network blip) so the elastic loop retries.
        """
        from ...exceptions import HorovodInternalError, RemovedFromWorldError

        # Always read the *latest* world: a worker re-initializing after an
        # interrupt must join the current epoch, not the one it started in.
        try:
            v = self.client.world_version() if version is None else version
            if v < self.version:
                v = self.version
            raw = self.client.get(f"world/{v}", self.hostname)
        except Exception as e:
            raise HorovodInternalError(f"rendezvous KV unreachable: {e}") from e
        if raw is None:
            raise RemovedFromWorldError(
                f"host {self.hostname!r} has no assignment in world v{v}"
            )
        self.version = v
        self.joined_version = v
        # Joining the latest epoch satisfies any pending hosts-updated
        # notification — clearing it avoids a spurious second teardown —
        # and moots any abort armed for the pre-recovery generation. An
        # abort record ALREADY posted for this generation (stall-only
        # recoveries rejoin the same generation; records are never
        # deleted) describes the failure we just recovered from, so it is
        # pre-consumed — only a record posted AFTER this join re-aborts.
        notification_manager.clear()
        try:
            from ..http.kv_server import ABORT_SCOPE

            stale = self.client.get(ABORT_SCOPE, str(v))
        except Exception:  # noqa: BLE001 — best-effort staleness marking
            stale = None
        abort.joined_generation(v, stale_record=stale)
        # Tracing plane: re-joining a world rebases the step counter so
        # every member of this generation counts steps from the same
        # point — cross-rank skew matching keys on (generation, step,
        # name), and a survivor's process-local count would otherwise
        # never line up with a replacement's.
        try:
            from ... import tracing

            tracing.get_tracer().rebase()
        except Exception:  # noqa: BLE001 — tracing is best-effort
            pass
        return json.loads(raw)

    def wait_for_assignment(self, poll_s: float | None = None) -> dict:
        """Spare-mode parking orbit: register as a warm spare, then poll
        until the driver publishes a world that includes this host.

        The caller must have started the poll loop (which advances
        ``self.version`` so KV writes stay inside the generation fence)
        and the heartbeat sender (the driver's liveness plane watches
        spares too) BEFORE parking here. A SIGTERM drain while waiting
        raises ``RemovedFromWorldError`` so the spare exits cleanly with
        ``EXIT_REMOVED``; transient KV failures propagate as
        ``HorovodInternalError`` and the elastic retry loop re-enters the
        wait (registration is idempotent).
        """
        if poll_s is None:
            poll_s = get_float("HOROVOD_SPARE_POLL_INTERVAL", 0.5)
        self.parked = True
        try:
            return self._wait_for_assignment_parked(poll_s)
        finally:
            self.parked = False

    def _wait_for_assignment_parked(self, poll_s: float) -> dict:
        from ...elastic.runner import drain_requested
        from ...exceptions import RemovedFromWorldError
        from ..http.kv_server import SPARE_SCOPE

        announced = False
        registered = False
        while True:
            try:
                assignment = self.fetch_assignment()
            except RemovedFromWorldError:
                if drain_requested():
                    raise RemovedFromWorldError(
                        "spare drained (SIGTERM) while waiting for an "
                        "assignment") from None
                if not announced:
                    # Park only after the first miss: a PROMOTED spare
                    # re-entering init() after a recovery fetches its
                    # assignment immediately and must not re-appear in
                    # the driver's spare roster.
                    announced = True
                    get_logger().info(
                        "elastic: warm spare on %s — framework ready, "
                        "waiting for a world assignment", self.hostname)
                    _metrics.event("spare_wait", generation=self.version,
                                   host=self.hostname)
                if not registered:
                    # Retried on every poll until it lands: a transient
                    # KV blip or a generation-fence 409 (the world
                    # reconfigured during this worker's long framework
                    # import) must not leave a warm, heartbeating spare
                    # permanently invisible to the policy's
                    # replacement-availability gate. Idempotent by
                    # construction.
                    try:
                        self.client.put(
                            SPARE_SCOPE, self.hostname, json.dumps({
                                "host": self.hostname,
                                "pid": os.getpid(),
                                "t": time.time(),
                            }).encode())
                        registered = True
                    except Exception as e:  # noqa: BLE001 — advisory
                        get_logger().debug(
                            "elastic: spare registration failed "
                            "(will retry): %s", e)
                time.sleep(poll_s)
                continue
            if announced:
                _metrics.event("spare_joined", generation=self.version,
                               host=self.hostname,
                               rank=assignment.get("process_id"))
                get_logger().info(
                    "elastic: spare on %s promoted into world v%d "
                    "(rank %s)", self.hostname, self.version,
                    assignment.get("process_id"))
            return assignment

    def apply_to_env(self, assignment: dict) -> None:
        """Refresh the env contract so re-init picks up the new world."""
        # The version keys the coordinator-port KV scope; survivors and
        # newly spawned workers must agree on it.
        os.environ["HOROVOD_WORLD_VERSION"] = str(self.version)
        os.environ["HOROVOD_PROCESS_ID"] = str(assignment["process_id"])
        os.environ["HOROVOD_NUM_PROCESSES"] = str(assignment["num_processes"])
        os.environ["HOROVOD_COORDINATOR_ADDR"] = assignment["coordinator"]
        if assignment.get("native_port"):
            os.environ["HOROVOD_NATIVE_PORT"] = str(assignment["native_port"])
        os.environ["HOROVOD_RANK"] = str(assignment["process_id"])
        os.environ["HOROVOD_SIZE"] = str(assignment["num_processes"])
        os.environ["HOROVOD_CROSS_RANK"] = str(assignment["process_id"])
        os.environ["HOROVOD_CROSS_SIZE"] = str(assignment["num_processes"])

    def check_for_update(self) -> bool:
        """One poll: True (and notification armed) if the world moved on."""
        current = self.client.world_version()
        if current != self.version:
            self.version = current
            notification_manager.handle_hosts_updated()
            return True
        return False

    # -- poll loop (with driver-loss escalation) -----------------------------

    def _exit_driver_lost(self, silent_s: float) -> None:
        get_logger().error(
            "elastic: rendezvous KV unreachable for %.0fs "
            "(%d consecutive poll failures) — driver lost; exiting %d",
            silent_s, self.consecutive_poll_failures, EXIT_DRIVER_LOST,
        )
        # os._exit, not sys.exit: this runs on the poller thread while the
        # main thread may be wedged in a collective whose peers died with
        # the driver — a SystemExit there would never be seen.
        os._exit(EXIT_DRIVER_LOST)

    def start_polling(self, interval: float = 1.0) -> None:
        if self._poller is not None:
            return
        lost_timeout = get_float("HOROVOD_ELASTIC_DRIVER_LOST_TIMEOUT", 300.0)

        def loop():
            log = get_logger()
            first_failure: float | None = None
            while not self._stop.wait(interval):
                try:
                    self.check_for_update()
                except Exception as e:  # KV unreachable: driver died/restarting
                    now = time.monotonic()
                    if first_failure is None:
                        first_failure = now
                    self.consecutive_poll_failures += 1
                    n = self.consecutive_poll_failures
                    if n >= POLL_FAILURE_WARN_AFTER:
                        log.warning(
                            "elastic poll failed (%d consecutive, "
                            "driver silent %.0fs): %s",
                            n, now - first_failure, e,
                        )
                    else:
                        log.debug("elastic poll failed: %s", e)
                    if (lost_timeout > 0
                            and now - first_failure >= lost_timeout):
                        self._on_driver_lost(now - first_failure)
                else:
                    if self.consecutive_poll_failures >= \
                            POLL_FAILURE_WARN_AFTER:
                        log.info(
                            "elastic: rendezvous KV reachable again after "
                            "%d failed polls", self.consecutive_poll_failures,
                        )
                    self.consecutive_poll_failures = 0
                    first_failure = None

        self._poller = threading.Thread(
            target=loop, name="hvd-elastic-poll", daemon=True
        )
        self._poller.start()
        self.start_abort_monitor()

    # -- coordinated-abort monitor -------------------------------------------

    def start_abort_monitor(self, interval: float | None = None) -> None:
        """Mirror the KV's ``abort/<generation>`` flag into process-local
        state (``horovod_tpu.abort``) so every blocking site — native
        synchronize, stall.watch, fetch — can convert a wedge into
        ``HorovodInternalError`` within one poll interval. Started with
        the poll loop; rides a dedicated 1-attempt/2s client."""
        if self._abort_poller is not None:
            return
        if interval is None:
            interval = abort.poll_interval()
        if interval <= 0:
            return  # explicitly disabled

        def loop():
            log = get_logger()
            while not self._stop.wait(interval):
                try:
                    abort.poll_once(self._abort_client,
                                    generation=self.joined_version)
                except Exception as e:  # KV unreachable: the poll loop
                    log.debug("abort poll failed: %s", e)  # owns escalation

        self._abort_poller = threading.Thread(
            target=loop, name="hvd-elastic-abort", daemon=True
        )
        self._abort_poller.start()

    # -- heartbeat sender ----------------------------------------------------

    def send_heartbeat(self) -> bool:
        """Publish one heartbeat; returns False when dropped/failed.

        Failures are swallowed (the poll loop owns driver-loss escalation;
        a missed heartbeat only matters to the DRIVER's deadline).

        The heartbeat doubles as this worker's metrics publication: the
        full instrument snapshot rides the PUT (``"metrics"`` key) so the
        driver's ``GET /metrics`` serves a cluster-wide aggregate with
        per-rank labels — no extra connection, no extra poll loop.
        ``HOROVOD_METRICS_PIGGYBACK=0`` strips it (liveness-only beats).

        It also doubles as the clock-alignment exchange: the server's 200
        reply carries its wall clock (``t_server``), and the send/receive
        stamps this side already takes bound the offset NTP-style
        (``tracing.ClockSync``) — the cross-rank timeline merge rides
        timestamps the liveness plane was already paying for."""
        if faults.fire(faults.HEARTBEAT_SEND):
            return False  # injected drop: silence, exactly like a hang
        from ... import tracing as _tracing

        clock = _tracing.clock_sync()
        body = {
            "steps": _counters.steps,
            "commits": _counters.commits,
            "rank": os.environ.get("HOROVOD_RANK", "0"),
            "time": clock.now(),
        }
        if os.environ.get("HOROVOD_METRICS_PIGGYBACK", "1") != "0":
            try:
                from ... import metrics as _metrics

                body["metrics"] = _metrics.snapshot()
            except Exception:  # noqa: BLE001 — liveness beats observability
                pass
        payload = json.dumps(body).encode()
        try:
            t_send = clock.now()
            reply = self._hb_client.put(HEARTBEAT_SCOPE, self.hostname,
                                        payload)
            t_recv = clock.now()
        except Exception as e:
            get_logger().debug("elastic: heartbeat send failed: %s", e)
            return False
        try:
            t_server = json.loads(reply or b"{}").get("t_server")
            if t_server is not None:
                clock.observe(t_send, t_recv, float(t_server))
        except Exception:  # noqa: BLE001 — alignment is best-effort
            pass
        try:
            # Eager host-plane workloads have no sampled step scope, so
            # their dispatch spans would never reach the merged timeline
            # or the straggler gauges: ship the tracer window on the
            # heartbeat cadence instead (throttled; no-op unless
            # HOROVOD_TRACE_SAMPLE enables shipping). A PARKED spare
            # never ships: it has no world rank, and its dummy launch-env
            # rank label would collide with a real rank's in the skew
            # attribution (heartbeats still flow — liveness needs them).
            if not self.parked:
                _tracing.maybe_ship_heartbeat()
        except Exception:  # noqa: BLE001 — shipping is best-effort
            pass
        return True

    def start_heartbeat(self, interval: float | None = None) -> None:
        if self._heartbeater is not None:
            return
        if interval is None:
            interval = get_float("HOROVOD_ELASTIC_HEARTBEAT_INTERVAL", 2.0)
        if interval <= 0:
            return  # explicitly disabled

        def loop():
            # First beat immediately: the driver's never-heartbeated grace
            # window should cover process startup, not the first interval.
            self.send_heartbeat()
            while not self._stop.wait(interval):
                self.send_heartbeat()

        self._heartbeater = threading.Thread(
            target=loop, name="hvd-elastic-heartbeat", daemon=True
        )
        self._heartbeater.start()

    def stop_polling(self) -> None:
        self._stop.set()
        if self._poller:
            self._poller.join(timeout=5)
            self._poller = None
        if self._heartbeater:
            self._heartbeater.join(timeout=5)
            self._heartbeater = None
        if self._abort_poller:
            self._abort_poller.join(timeout=5)
            self._abort_poller = None


_context: ElasticWorkerContext | None = None


def get_worker_context() -> ElasticWorkerContext:
    global _context
    if _context is None:
        _context = ElasticWorkerContext()
    return _context
