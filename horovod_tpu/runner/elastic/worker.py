"""Worker-side elastic plumbing: world-version polling + assignment fetch.

Parity with ``horovod/runner/elastic/worker.py`` (``WorkerNotificationClient``
/ ``WorkerNotificationService``), inverted for the KV-polling contract (see
``driver.py``): instead of the driver pushing to a TCP listener in every
worker, workers poll the rendezvous KV's world version — a bump arms
``notification_manager`` so the next ``state.commit()`` raises
``HostsUpdatedInterrupt`` (SURVEY.md §4.4 recovery loop).
"""

from __future__ import annotations

import json
import os
import threading

from ...elastic.runner import notification_manager
from ...utils.logging import get_logger
from ..http.kv_server import KVClient


def elastic_enabled() -> bool:
    return os.environ.get("HOROVOD_ELASTIC", "") == "1"


class ElasticWorkerContext:
    """This worker's view of the elastic world, refreshed per epoch."""

    def __init__(self):
        addr = os.environ["HOROVOD_RENDEZVOUS_ADDR"]
        port = int(os.environ["HOROVOD_RENDEZVOUS_PORT"])
        self.hostname = os.environ.get("HOROVOD_HOSTNAME", "localhost")
        self.client = KVClient(addr, port)
        self.version = int(os.environ.get("HOROVOD_WORLD_VERSION", "0"))
        self._poller: threading.Thread | None = None
        self._stop = threading.Event()

    def fetch_assignment(self, version: int | None = None) -> dict:
        """Read this host's assignment for a world version (JSON dict with
        process_id / num_processes / coordinator / slots / hosts).

        Raises ``RemovedFromWorldError`` when the epoch exists but this host
        is not in it, and ``HorovodInternalError`` for transient KV failures
        (driver restarting / network blip) so the elastic loop retries.
        """
        from ...exceptions import HorovodInternalError, RemovedFromWorldError

        # Always read the *latest* world: a worker re-initializing after an
        # interrupt must join the current epoch, not the one it started in.
        try:
            v = self.client.world_version() if version is None else version
            if v < self.version:
                v = self.version
            raw = self.client.get(f"world/{v}", self.hostname)
        except Exception as e:
            raise HorovodInternalError(f"rendezvous KV unreachable: {e}") from e
        if raw is None:
            raise RemovedFromWorldError(
                f"host {self.hostname!r} has no assignment in world v{v}"
            )
        self.version = v
        # Joining the latest epoch satisfies any pending hosts-updated
        # notification — clearing it avoids a spurious second teardown.
        notification_manager.clear()
        return json.loads(raw)

    def apply_to_env(self, assignment: dict) -> None:
        """Refresh the env contract so re-init picks up the new world."""
        # The version keys the coordinator-port KV scope; survivors and
        # newly spawned workers must agree on it.
        os.environ["HOROVOD_WORLD_VERSION"] = str(self.version)
        os.environ["HOROVOD_PROCESS_ID"] = str(assignment["process_id"])
        os.environ["HOROVOD_NUM_PROCESSES"] = str(assignment["num_processes"])
        os.environ["HOROVOD_COORDINATOR_ADDR"] = assignment["coordinator"]
        if assignment.get("native_port"):
            os.environ["HOROVOD_NATIVE_PORT"] = str(assignment["native_port"])
        os.environ["HOROVOD_RANK"] = str(assignment["process_id"])
        os.environ["HOROVOD_SIZE"] = str(assignment["num_processes"])
        os.environ["HOROVOD_CROSS_RANK"] = str(assignment["process_id"])
        os.environ["HOROVOD_CROSS_SIZE"] = str(assignment["num_processes"])

    def check_for_update(self) -> bool:
        """One poll: True (and notification armed) if the world moved on."""
        current = self.client.world_version()
        if current != self.version:
            self.version = current
            notification_manager.handle_hosts_updated()
            return True
        return False

    def start_polling(self, interval: float = 1.0) -> None:
        if self._poller is not None:
            return

        def loop():
            while not self._stop.wait(interval):
                try:
                    self.check_for_update()
                except Exception as e:  # KV unreachable: driver died/restarting
                    get_logger().debug("elastic poll failed: %s", e)

        self._poller = threading.Thread(
            target=loop, name="hvd-elastic-poll", daemon=True
        )
        self._poller.start()

    def stop_polling(self) -> None:
        self._stop.set()
        if self._poller:
            self._poller.join(timeout=5)
            self._poller = None


_context: ElasticWorkerContext | None = None


def get_worker_context() -> ElasticWorkerContext:
    global _context
    if _context is None:
        _context = ElasticWorkerContext()
    return _context
