"""Worker-side elastic plumbing: world-version polling, heartbeats,
assignment fetch.

Parity with ``horovod/runner/elastic/worker.py`` (``WorkerNotificationClient``
/ ``WorkerNotificationService``), inverted for the KV-polling contract (see
``driver.py``): instead of the driver pushing to a TCP listener in every
worker, workers poll the rendezvous KV's world version — a bump arms
``notification_manager`` so the next ``state.commit()`` raises
``HostsUpdatedInterrupt`` (SURVEY.md §4.4 recovery loop).

Liveness plane (the hung-host gap): alongside the poller, each worker
publishes a heartbeat to ``PUT /heartbeat/<host>`` every
``HOROVOD_ELASTIC_HEARTBEAT_INTERVAL`` seconds, piggybacking its step and
commit counters. The driver's monitor declares a host dead after
``HOROVOD_ELASTIC_HEARTBEAT_TIMEOUT`` of silence — a SIGSTOP'd process, a
wedged TPU VM, or a livelocked trainer all stop heartbeating (every thread
freezes), which ``popen.poll()`` alone can never see.

Driver-loss escalation: the poll loop counts consecutive KV failures,
raises its logging to ``warning`` after ``POLL_FAILURE_WARN_AFTER``, and —
once failures have been continuous for
``HOROVOD_ELASTIC_DRIVER_LOST_TIMEOUT`` seconds — exits the process with
``EXIT_DRIVER_LOST`` instead of polling a dead driver forever (the main
thread may be wedged in a collective precisely because the world died, so
the poller owns the exit).

Driver crash-restart rejoin: when the durable control-plane state plane
is armed (``HOROVOD_DRIVER_STATE_DIR``), an unreachable KV no longer
means the job is over — a supervisor may be relaunching the driver. The
poller then re-resolves the rendezvous endpoint from the shared-storage
discovery record (``driver_state.read_endpoint``) with jittered backoff
on every failed poll, and ONLY gives up (``EXIT_DRIVER_LOST``) after the
loss deadline plus ``HOROVOD_DRIVER_REJOIN_TIMEOUT`` of fruitless orphan
waiting. A record carrying a HIGHER driver epoch than this worker's is a
successor driver: the worker repoints every KV client at it (heartbeat,
abort, replication, tracing all follow), adopts the new epoch for the
split-brain fence, and the successor's g+1 world publish then surfaces
through the normal recovery machinery — no process restart.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time

from ... import abort, faults
from ... import metrics as _metrics
from ...elastic.runner import notification_manager
from ...utils.env import get_float
from ...utils.logging import get_logger
from ..http.kv_server import DONE_SCOPE, HEARTBEAT_SCOPE, KVClient
from .constants import EXIT_DRIVER_LOST, POLL_FAILURE_WARN_AFTER


def elastic_enabled() -> bool:
    return os.environ.get("HOROVOD_ELASTIC", "") == "1"


def spare_mode() -> bool:
    """True when this worker was launched as a WARM SPARE: discovered,
    heartbeating, framework-imported, but deliberately excluded from the
    world until the driver publishes an epoch that includes its host."""
    return os.environ.get("HOROVOD_SPARE", "") == "1"


class _HeartbeatCounters:
    """Process-wide progress counters piggybacked on every heartbeat, so
    the driver's liveness record doubles as a progress trace."""

    __slots__ = ("steps", "commits", "last_commit_pc")

    def __init__(self):
        self.steps = 0
        self.commits = 0
        # perf_counter stamp of the last landed commit: the goodput
        # ledger splits a failed attempt at this point — productive up to
        # the last commit, lost{failed_attempt} after it.
        self.last_commit_pc: float | None = None


_counters = _HeartbeatCounters()


def record_step() -> None:
    _counters.steps += 1


def record_commit() -> None:
    _counters.commits += 1
    _counters.last_commit_pc = time.perf_counter()


class ElasticWorkerContext:
    """This worker's view of the elastic world, refreshed per epoch."""

    def __init__(self, on_driver_lost=None):
        self.hostname = os.environ.get("HOROVOD_HOSTNAME", "localhost")
        # The serving driver's epoch (split-brain fence): writes carry it
        # as X-Hvd-Driver-Epoch so a worker still loyal to a superseded
        # driver bounces off the successor's 409 fence; the worker
        # follows the HIGHEST epoch it has seen (endpoint re-resolution
        # bumps it, never lowers it).
        self.driver_epoch = int(
            os.environ.get("HOROVOD_DRIVER_EPOCH", "0") or 0)
        self._build_clients(os.environ["HOROVOD_RENDEZVOUS_ADDR"],
                            int(os.environ["HOROVOD_RENDEZVOUS_PORT"]))
        self.version = int(os.environ.get("HOROVOD_WORLD_VERSION", "0"))
        # The generation this worker last actually JOINED (fetch_assignment)
        # — distinct from `version`, which the poll loop advances the
        # moment the driver bumps the epoch. The abort monitor must poll
        # the JOINED generation: a survivor wedged in world g's collectives
        # is still in world g even after its poller has seen g+1 announced.
        self.joined_version = self.version
        # True while a warm spare is parked on the assignment wait: it
        # has no world rank yet, so its tracer must not ship (its dummy
        # launch-env rank label would collide with a real rank's in the
        # skew attribution).
        self.parked = False
        self.consecutive_poll_failures = 0
        self._on_driver_lost = on_driver_lost or self._exit_driver_lost
        self._poller: threading.Thread | None = None
        self._heartbeater: threading.Thread | None = None
        self._abort_poller: threading.Thread | None = None
        self._stop = threading.Event()
        self._next_rejoin_probe = 0.0

    def _build_clients(self, addr: str, port: int) -> None:
        """(Re)build the three KV clients against one endpoint. Every
        client stamps writes with this worker's live generation view, so
        the server's fence can reject a zombie's replays (a
        SIGSTOP'd-through-recovery worker resumes with a stale version),
        and with the driver epoch (split-brain fence).

        The heartbeat and abort-poll clients are dedicated ONE-attempt /
        2s-timeout clients: the beat loop itself is the retry — a beat
        that inherited the full KV retry budget (3 × 10s timeout +
        backoff) could block the sender past the driver's heartbeat
        deadline and get a healthy worker killed for the very silence
        the budget was absorbing; the abort poll bounds wedged
        survivors' unblock latency and must never stretch it either."""
        gen_fn = lambda: self.version  # noqa: E731
        epoch_fn = lambda: (  # noqa: E731
            self.driver_epoch if self.driver_epoch > 0 else None)
        self.client = KVClient(addr, port, generation_fn=gen_fn,
                               epoch_fn=epoch_fn)
        self._hb_client = KVClient(addr, port, timeout=2.0, retries=1,
                                   generation_fn=gen_fn, epoch_fn=epoch_fn)
        self._abort_client = KVClient(addr, port, timeout=2.0, retries=1,
                                      generation_fn=gen_fn,
                                      epoch_fn=epoch_fn)

    def fetch_assignment(self, version: int | None = None) -> dict:
        """Read this host's assignment for a world version (JSON dict with
        process_id / num_processes / coordinator / slots / hosts).

        Raises ``RemovedFromWorldError`` when the epoch exists but this host
        is not in it, and ``HorovodInternalError`` for transient KV failures
        (driver restarting / network blip) so the elastic loop retries.
        """
        from ...exceptions import HorovodInternalError, RemovedFromWorldError

        # Always read the *latest* world: a worker re-initializing after an
        # interrupt must join the current epoch, not the one it started in.
        try:
            v = self.client.world_version() if version is None else version
            if v < self.version:
                v = self.version
            raw = self.client.get(f"world/{v}", self.hostname)
        except Exception as e:
            raise HorovodInternalError(f"rendezvous KV unreachable: {e}") from e
        if raw is None:
            raise RemovedFromWorldError(
                f"host {self.hostname!r} has no assignment in world v{v}"
            )
        self.version = v
        self.joined_version = v
        # Joining the latest epoch satisfies any pending hosts-updated
        # notification — clearing it avoids a spurious second teardown —
        # and moots any abort armed for the pre-recovery generation. An
        # abort record ALREADY posted for this generation (stall-only
        # recoveries rejoin the same generation; records are never
        # deleted) describes the failure we just recovered from, so it is
        # pre-consumed — only a record posted AFTER this join re-aborts.
        notification_manager.clear()
        try:
            from ..http.kv_server import ABORT_SCOPE

            stale = self.client.get(ABORT_SCOPE, str(v))
        except Exception:  # noqa: BLE001 — best-effort staleness marking
            stale = None
        abort.joined_generation(v, stale_record=stale)
        # Tracing plane: re-joining a world rebases the step counter so
        # every member of this generation counts steps from the same
        # point — cross-rank skew matching keys on (generation, step,
        # name), and a survivor's process-local count would otherwise
        # never line up with a replacement's.
        try:
            from ... import tracing

            tracing.get_tracer().rebase()
        except Exception:  # noqa: BLE001 — tracing is best-effort
            pass
        return json.loads(raw)

    def wait_for_assignment(self, poll_s: float | None = None) -> dict:
        """Spare-mode parking orbit: register as a warm spare, then poll
        until the driver publishes a world that includes this host.

        The caller must have started the poll loop (which advances
        ``self.version`` so KV writes stay inside the generation fence)
        and the heartbeat sender (the driver's liveness plane watches
        spares too) BEFORE parking here. A SIGTERM drain while waiting
        raises ``RemovedFromWorldError`` so the spare exits cleanly with
        ``EXIT_REMOVED``; transient KV failures propagate as
        ``HorovodInternalError`` and the elastic retry loop re-enters the
        wait (registration is idempotent).
        """
        if poll_s is None:
            poll_s = get_float("HOROVOD_SPARE_POLL_INTERVAL", 0.5)
        self.parked = True
        try:
            return self._wait_for_assignment_parked(poll_s)
        finally:
            self.parked = False

    def _wait_for_assignment_parked(self, poll_s: float) -> dict:
        from ...elastic.runner import drain_requested
        from ...exceptions import RemovedFromWorldError
        from ..http.kv_server import SPARE_SCOPE

        announced = False
        registered = False
        while True:
            try:
                assignment = self.fetch_assignment()
            except RemovedFromWorldError:
                if drain_requested():
                    raise RemovedFromWorldError(
                        "spare drained (SIGTERM) while waiting for an "
                        "assignment") from None
                if not announced:
                    # Park only after the first miss: a PROMOTED spare
                    # re-entering init() after a recovery fetches its
                    # assignment immediately and must not re-appear in
                    # the driver's spare roster.
                    announced = True
                    get_logger().info(
                        "elastic: warm spare on %s — framework ready, "
                        "waiting for a world assignment", self.hostname)
                    _metrics.event("spare_wait", generation=self.version,
                                   host=self.hostname)
                if not registered:
                    # Retried on every poll until it lands: a transient
                    # KV blip or a generation-fence 409 (the world
                    # reconfigured during this worker's long framework
                    # import) must not leave a warm, heartbeating spare
                    # permanently invisible to the policy's
                    # replacement-availability gate. Idempotent by
                    # construction.
                    try:
                        self.client.put(
                            SPARE_SCOPE, self.hostname, json.dumps({
                                "host": self.hostname,
                                "pid": os.getpid(),
                                "t": time.time(),
                            }).encode())
                        registered = True
                    except Exception as e:  # noqa: BLE001 — advisory
                        get_logger().debug(
                            "elastic: spare registration failed "
                            "(will retry): %s", e)
                time.sleep(poll_s)
                continue
            if announced:
                _metrics.event("spare_joined", generation=self.version,
                               host=self.hostname,
                               rank=assignment.get("process_id"))
                get_logger().info(
                    "elastic: spare on %s promoted into world v%d "
                    "(rank %s)", self.hostname, self.version,
                    assignment.get("process_id"))
            return assignment

    def apply_to_env(self, assignment: dict) -> None:
        """Refresh the env contract so re-init picks up the new world."""
        # The version keys the coordinator-port KV scope; survivors and
        # newly spawned workers must agree on it.
        os.environ["HOROVOD_WORLD_VERSION"] = str(self.version)
        os.environ["HOROVOD_PROCESS_ID"] = str(assignment["process_id"])
        os.environ["HOROVOD_NUM_PROCESSES"] = str(assignment["num_processes"])
        os.environ["HOROVOD_COORDINATOR_ADDR"] = assignment["coordinator"]
        if assignment.get("native_port"):
            os.environ["HOROVOD_NATIVE_PORT"] = str(assignment["native_port"])
        os.environ["HOROVOD_RANK"] = str(assignment["process_id"])
        os.environ["HOROVOD_SIZE"] = str(assignment["num_processes"])
        os.environ["HOROVOD_CROSS_RANK"] = str(assignment["process_id"])
        os.environ["HOROVOD_CROSS_SIZE"] = str(assignment["num_processes"])

    def check_for_update(self) -> bool:
        """One poll: True (and notification armed) if the world moved on."""
        current = self.client.world_version()
        if current != self.version:
            self.version = current
            notification_manager.handle_hosts_updated()
            return True
        return False

    # -- poll loop (with driver-loss escalation) -----------------------------

    def _exit_driver_lost(self, silent_s: float) -> None:
        get_logger().error(
            "elastic: rendezvous KV unreachable for %.0fs "
            "(%d consecutive poll failures) — driver lost; exiting %d",
            silent_s, self.consecutive_poll_failures, EXIT_DRIVER_LOST,
        )
        # os._exit, not sys.exit: this runs on the poller thread while the
        # main thread may be wedged in a collective whose peers died with
        # the driver — a SystemExit there would never be seen.
        os._exit(EXIT_DRIVER_LOST)

    def rejoin_timeout(self) -> float:
        """The bounded orphan window: how long past the driver-loss
        deadline a worker keeps re-resolving the rendezvous endpoint
        before giving up with ``EXIT_DRIVER_LOST``. Zero — the default
        whenever ``HOROVOD_DRIVER_STATE_DIR`` is unset — disables the
        orphan loop entirely: the 203 path is bit-for-bit the
        state-plane-free one."""
        from . import driver_state

        if driver_state.state_dir() is None:
            return 0.0
        return get_float("HOROVOD_DRIVER_REJOIN_TIMEOUT", 600.0)

    def _try_rejoin(self) -> bool:
        """One endpoint re-resolution attempt (jittered backoff between
        reads): follow the shared-storage discovery record to a SUCCESSOR
        driver — strictly higher epoch, answering probe — and repoint
        every client at it. Returns True on a completed repoint."""
        from . import driver_state

        now = time.monotonic()
        if now < self._next_rejoin_probe:
            return False
        base = get_float("HOROVOD_DRIVER_REJOIN_PROBE_INTERVAL", 1.0)
        self._next_rejoin_probe = now + base * (1.0 + random.random())
        record = driver_state.read_endpoint()
        if record is None or record["driver_epoch"] <= self.driver_epoch:
            return False  # the dead driver's own record (or none yet)
        probe = KVClient(record["addr"], record["port"], timeout=2.0,
                         retries=1)
        try:
            probe.world_version()
        except Exception:  # noqa: BLE001 — successor not up yet
            return False
        self._repoint(record["addr"], record["port"],
                      record["driver_epoch"])
        return True

    def _repoint(self, addr: str, port: int, epoch: int) -> None:
        """Adopt a successor driver's endpoint + epoch: rebuild the
        three owned clients, refresh the env contract (the trace
        shipper, ``abort.post``, and the peer replicator all resolve
        the endpoint from env), and reset the replicator's cached
        client so the next commit re-publishes its replica to the new
        KV — the peer rung re-arms with zero durable reads."""
        get_logger().warning(
            "elastic: rendezvous endpoint re-resolved to %s:%d (driver "
            "epoch %d > %d) — rejoining the restarted driver",
            addr, port, epoch, self.driver_epoch)
        self.driver_epoch = epoch
        os.environ["HOROVOD_RENDEZVOUS_ADDR"] = addr
        os.environ["HOROVOD_RENDEZVOUS_PORT"] = str(port)
        os.environ["HOROVOD_GLOO_RENDEZVOUS_ADDR"] = addr
        os.environ["HOROVOD_GLOO_RENDEZVOUS_PORT"] = str(port)
        os.environ["HOROVOD_DRIVER_EPOCH"] = str(epoch)
        self._build_clients(addr, port)
        try:
            from ... import peercheck

            rep = peercheck.active_replicator()
            if rep is not None:
                rep.repoint()
        except Exception:  # noqa: BLE001 — replication is best-effort
            pass
        _metrics.event("driver_rejoin", generation=self.version,
                       host=self.hostname, driver_epoch=epoch,
                       endpoint=f"{addr}:{port}")

    def start_polling(self, interval: float = 1.0) -> None:
        if self._poller is not None:
            return
        lost_timeout = get_float("HOROVOD_ELASTIC_DRIVER_LOST_TIMEOUT", 300.0)

        def loop():
            log = get_logger()
            first_failure: float | None = None
            orphaned = False
            while not self._stop.wait(interval):
                try:
                    self.check_for_update()
                except Exception as e:  # KV unreachable: driver died/restarting
                    now = time.monotonic()
                    if first_failure is None:
                        first_failure = now
                    self.consecutive_poll_failures += 1
                    n = self.consecutive_poll_failures
                    if n >= POLL_FAILURE_WARN_AFTER:
                        log.warning(
                            "elastic poll failed (%d consecutive, "
                            "driver silent %.0fs): %s",
                            n, now - first_failure, e,
                        )
                    else:
                        log.debug("elastic poll failed: %s", e)
                    rejoin_budget = self.rejoin_timeout()
                    if rejoin_budget > 0:
                        # Orphan loop: a supervisor may be relaunching
                        # the driver — keep re-resolving the endpoint
                        # (jittered) and only die at loss + rejoin.
                        try:
                            if self._try_rejoin():
                                first_failure = None
                                orphaned = False
                                self.consecutive_poll_failures = 0
                                continue
                        except Exception as re:  # noqa: BLE001
                            log.debug("elastic rejoin probe failed: %s",
                                      re)
                        if (lost_timeout > 0 and not orphaned
                                and now - first_failure >= lost_timeout):
                            orphaned = True
                            log.warning(
                                "elastic: driver lost for %.0fs — "
                                "entering the orphan wait (another "
                                "%.0fs of endpoint re-resolution "
                                "before exit %d)",
                                now - first_failure, rejoin_budget,
                                EXIT_DRIVER_LOST)
                            _metrics.event(
                                "driver_orphaned",
                                generation=self.version,
                                host=self.hostname,
                                silent_s=round(now - first_failure, 1))
                        if (lost_timeout > 0
                                and now - first_failure
                                >= lost_timeout + rejoin_budget):
                            self._on_driver_lost(now - first_failure)
                    elif (lost_timeout > 0
                            and now - first_failure >= lost_timeout):
                        self._on_driver_lost(now - first_failure)
                else:
                    if self.consecutive_poll_failures >= \
                            POLL_FAILURE_WARN_AFTER:
                        log.info(
                            "elastic: rendezvous KV reachable again after "
                            "%d failed polls", self.consecutive_poll_failures,
                        )
                    self.consecutive_poll_failures = 0
                    first_failure = None
                    orphaned = False

        self._poller = threading.Thread(
            target=loop, name="hvd-elastic-poll", daemon=True
        )
        self._poller.start()
        self.start_abort_monitor()

    # -- coordinated-abort monitor -------------------------------------------

    def start_abort_monitor(self, interval: float | None = None) -> None:
        """Mirror the KV's ``abort/<generation>`` flag into process-local
        state (``horovod_tpu.abort``) so every blocking site — native
        synchronize, stall.watch, fetch — can convert a wedge into
        ``HorovodInternalError`` within one poll interval. Started with
        the poll loop; rides a dedicated 1-attempt/2s client."""
        if self._abort_poller is not None:
            return
        if interval is None:
            interval = abort.poll_interval()
        if interval <= 0:
            return  # explicitly disabled

        def loop():
            log = get_logger()
            while not self._stop.wait(interval):
                try:
                    abort.poll_once(self._abort_client,
                                    generation=self.joined_version)
                except Exception as e:  # KV unreachable: the poll loop
                    log.debug("abort poll failed: %s", e)  # owns escalation

        self._abort_poller = threading.Thread(
            target=loop, name="hvd-elastic-abort", daemon=True
        )
        self._abort_poller.start()

    # -- heartbeat sender ----------------------------------------------------

    def send_heartbeat(self) -> bool:
        """Publish one heartbeat; returns False when dropped/failed.

        Failures are swallowed (the poll loop owns driver-loss escalation;
        a missed heartbeat only matters to the DRIVER's deadline).

        The heartbeat doubles as this worker's metrics publication: the
        full instrument snapshot rides the PUT (``"metrics"`` key) so the
        driver's ``GET /metrics`` serves a cluster-wide aggregate with
        per-rank labels — no extra connection, no extra poll loop.
        ``HOROVOD_METRICS_PIGGYBACK=0`` strips it (liveness-only beats).

        It also doubles as the clock-alignment exchange: the server's 200
        reply carries its wall clock (``t_server``), and the send/receive
        stamps this side already takes bound the offset NTP-style
        (``tracing.ClockSync``) — the cross-rank timeline merge rides
        timestamps the liveness plane was already paying for."""
        if faults.fire(faults.HEARTBEAT_SEND):
            return False  # injected drop: silence, exactly like a hang
        from ... import tracing as _tracing

        clock = _tracing.clock_sync()
        body = {
            "steps": _counters.steps,
            "commits": _counters.commits,
            "rank": os.environ.get("HOROVOD_RANK", "0"),
            "time": clock.now(),
        }
        if os.environ.get("HOROVOD_METRICS_PIGGYBACK", "1") != "0":
            try:
                from ... import metrics as _metrics

                body["metrics"] = _metrics.snapshot()
            except Exception:  # noqa: BLE001 — liveness beats observability
                pass
            try:
                # Communication observatory: the fitted alpha-beta model
                # rides the same beat (bounded: a handful of fits), so
                # the driver's GET /comms serves a cluster-merged view
                # and its policy plane sees per-host residuals. A PARKED
                # spare never ships one — like its trace window, its
                # dummy launch-env rank label would shadow a real rank's
                # model in the per-rank merge.
                if not self.parked:
                    from ... import comms_model as _comms_model

                    body["comms"] = _comms_model.get_model().payload()
            except Exception:  # noqa: BLE001 — observability only
                pass
            try:
                # Memory observatory: per-kind resident bytes and the
                # phase watermarks ride the same beat (bounded: a few
                # ints), so the driver's GET /memory serves a
                # cluster-merged per-rank breakdown. Same parked-spare
                # rule as the comms payload.
                if not self.parked:
                    from ... import memory as _memory

                    body["memory"] = _memory.get_observatory().payload()
            except Exception:  # noqa: BLE001 — observability only
                pass
        try:
            # Integrity defense plane: the latest state fingerprint
            # rides the beat (tiny — one digest + a few summaries) so
            # the driver's voting tick sees every rank's record without
            # a new route or poll loop. Armed by its own knob
            # (HOROVOD_INTEGRITY_INTERVAL), independent of the metrics
            # piggyback — corruption detection is correctness, not
            # telemetry. A PARKED spare has no world rank and ships
            # nothing (its launch-env rank label would collide with a
            # live rank's in the vote grouping).
            if not self.parked:
                from ... import integrity as _integrity

                rec = _integrity.heartbeat_payload()
                if rec is not None:
                    body["integrity"] = rec
        except Exception:  # noqa: BLE001 — liveness beats the defense
            pass
        payload = json.dumps(body).encode()
        try:
            t_send = clock.now()
            reply = self._hb_client.put(HEARTBEAT_SCOPE, self.hostname,
                                        payload)
            t_recv = clock.now()
        except Exception as e:
            get_logger().debug("elastic: heartbeat send failed: %s", e)
            return False
        try:
            t_server = json.loads(reply or b"{}").get("t_server")
            if t_server is not None:
                clock.observe(t_send, t_recv, float(t_server))
        except Exception:  # noqa: BLE001 — alignment is best-effort
            pass
        try:
            # Eager host-plane workloads have no sampled step scope, so
            # their dispatch spans would never reach the merged timeline
            # or the straggler gauges: ship the tracer window on the
            # heartbeat cadence instead (throttled; no-op unless
            # HOROVOD_TRACE_SAMPLE enables shipping). A PARKED spare
            # never ships: it has no world rank, and its dummy launch-env
            # rank label would collide with a real rank's in the skew
            # attribution (heartbeats still flow — liveness needs them).
            if not self.parked:
                _tracing.maybe_ship_heartbeat()
        except Exception:  # noqa: BLE001 — shipping is best-effort
            pass
        return True

    def start_heartbeat(self, interval: float | None = None) -> None:
        if self._heartbeater is not None:
            return
        if interval is None:
            interval = get_float("HOROVOD_ELASTIC_HEARTBEAT_INTERVAL", 2.0)
        if interval <= 0:
            return  # explicitly disabled

        def loop():
            # First beat immediately: the driver's never-heartbeated grace
            # window should cover process startup, not the first interval.
            self.send_heartbeat()
            while not self._stop.wait(interval):
                self.send_heartbeat()

        self._heartbeater = threading.Thread(
            target=loop, name="hvd-elastic-heartbeat", daemon=True
        )
        self._heartbeater.start()

    def stop_polling(self) -> None:
        self._stop.set()
        if self._poller:
            self._poller.join(timeout=5)
            self._poller = None
        if self._heartbeater:
            self._heartbeater.join(timeout=5)
            self._heartbeater = None
        if self._abort_poller:
            self._abort_poller.join(timeout=5)
            self._abort_poller = None


_context: ElasticWorkerContext | None = None


def get_worker_context() -> ElasticWorkerContext:
    global _context
    if _context is None:
        _context = ElasticWorkerContext()
    return _context


def announce_done() -> None:
    """Best-effort completion record (``PUT /done/<host>``), published
    when the elastic training function returns. The driver normally
    learns completion from the rc=0 it reaps — but a worker ADOPTED
    across a driver crash-restart is not the new driver's child, so this
    record is the only way its success survives the takeover. Failures
    are swallowed: a worker whose KV is gone still exits 0, and the
    pre-takeover reap path never needed the record anyway."""
    ctx = _context
    if ctx is None or not elastic_enabled():
        return
    try:
        # Deliberately NOT generation-fenced: a worker finishing while
        # the driver is mid-reconfigure (server already at g+1) must
        # still land its completion — a 409'd done record would read as
        # an unclean adopted exit and re-run the finished job. The
        # driver-epoch fence still applies (a superseded driver's
        # worker must not plant records in the successor's store).
        client = KVClient(
            os.environ["HOROVOD_RENDEZVOUS_ADDR"],
            int(os.environ["HOROVOD_RENDEZVOUS_PORT"]),
            timeout=5.0, retries=3,
            epoch_fn=(lambda: ctx.driver_epoch)
            if ctx.driver_epoch > 0 else None)
        client.put(DONE_SCOPE, ctx.hostname, json.dumps({
            "host": ctx.hostname,
            "rc": 0,
            "generation": ctx.joined_version,
            "t": time.time(),
        }).encode())
    except Exception as e:  # noqa: BLE001 — advisory record only
        get_logger().debug("elastic: completion announce failed: %s", e)
