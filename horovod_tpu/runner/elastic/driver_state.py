"""Durable control-plane state: the elastic driver's crash-restart story.

Every data-plane failure already has a recovery path (hung hosts, wedged
collectives, killed workers, stragglers) — but the driver process itself
was a single point of failure: its death orphaned the workers, which
timed out and exited ``EXIT_DRIVER_LOST``. This module closes that hole:

1. **Snapshot store** (:class:`DriverStateStore`): the driver journals
   its authoritative state — world membership and slots, generation,
   blacklist (with elapsed ages, so cooldowns survive a monotonic-clock
   restart), spare registry, policy EWMAs and the measured resize-cost
   estimate, per-host driver-lost counters, and the live worker PIDs —
   to ``$HOROVOD_DRIVER_STATE_DIR/driver_state.json`` on every mutation.
   Writes go through :func:`checkpoint.atomic_install` (hard-link
   rotation: the previous epoch's snapshot survives at ``.prev``, and no
   crash window ever leaves the path empty) with a sha256 integrity
   field; loads verify and fall back one snapshot on a torn write.
2. **Endpoint record** (:meth:`publish_endpoint` / :func:`read_endpoint`):
   the shared-storage discovery record orphaned workers re-resolve the
   rendezvous endpoint from — ``{addr, port, driver_epoch, generation}``
   — refreshed on every world publish.
3. **Driver-epoch fencing**: every snapshot and endpoint record is
   tagged with a monotonic **driver epoch**, bumped on every
   (re)start. A write whose epoch is LOWER than what the store already
   holds raises :class:`DriverFencedError` — a SIGSTOP'd-through-takeover
   stale driver can neither clobber its successor's snapshot nor
   recapture workers through the endpoint record. The same epoch rides
   driver-originated KV traffic as ``X-Hvd-Driver-Epoch`` and the KV
   server 409s lower-epoch writes (``runner/http/kv_server.py``).

Stdlib-only and jax-free by design: both the driver (pre-framework) and
the orphaned worker's poll thread import this.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Mapping

from ... import faults
from ...checkpoint import atomic_install, atomic_read, payload_digest
from ...utils.logging import get_logger

ENV_STATE_DIR = "HOROVOD_DRIVER_STATE_DIR"
ENV_DRIVER_EPOCH = "HOROVOD_DRIVER_EPOCH"

#: Snapshot + endpoint file names inside the state dir.
STATE_FILE = "driver_state.json"
ENDPOINT_FILE = "endpoint.json"


def state_dir() -> str | None:
    """The configured control-plane state directory, or None (the
    feature is then fully disabled — bit-for-bit the 203 path)."""
    d = os.environ.get(ENV_STATE_DIR, "").strip()
    return d or None


class DriverFencedError(RuntimeError):
    """A stale driver (lower epoch) tried to write control-plane state
    already owned by a higher-epoch successor. The correct reaction is
    to stand down, NOT to retry: the world has moved on."""


def _encode(record: Mapping[str, Any]) -> bytes:
    """One self-verifying JSON document: the record plus the sha256 of
    its canonical body, so a torn write fails verification instead of
    parsing as a plausible-but-partial state."""
    body = json.dumps(record, sort_keys=True)
    return json.dumps({"body": body,
                       "sha256": payload_digest(body.encode())}).encode()


def _decode(blob: bytes) -> dict:
    """Verify + parse; raises ``ValueError`` on any malformation."""
    outer = json.loads(blob)
    if not isinstance(outer, dict) or "body" not in outer:
        raise ValueError("driver-state record has no body")
    body = outer["body"]
    if payload_digest(str(body).encode()) != outer.get("sha256"):
        raise ValueError(
            "driver-state record failed its integrity check "
            "(torn/corrupted write)")
    record = json.loads(body)
    if not isinstance(record, dict):
        raise ValueError("driver-state body is not a mapping")
    return record


def _read_record(path: str) -> dict | None:
    """Newest verifiable record at ``path`` (falling back to ``.prev``
    on a torn current file), or None when neither slot is readable."""
    log = get_logger()
    for blob, which in atomic_read(path):
        try:
            return _decode(blob)
        except Exception as e:  # noqa: BLE001 — corrupt slot: keep looking
            log.error(
                "driver-state %s slot of %s is unreadable (%s); %s",
                which, path,
                e, "falling back to the previous snapshot"
                if which == "current" else "no snapshot recovered")
    return None


def _disk_epoch(path: str) -> int | None:
    rec = _read_record(path)
    if rec is None:
        return None
    try:
        return int(rec.get("driver_epoch", 0))
    except (TypeError, ValueError):
        return None


def proc_start_ticks(pid: int) -> int | None:
    """The kernel's process start time (clock ticks since boot, field 22
    of ``/proc/<pid>/stat``) — the PID-reuse guard for worker adoption:
    a snapshot PID whose start time no longer matches names a DIFFERENT
    process, which the takeover driver must never adopt (it would later
    SIGKILL an innocent process group). None where unreadable (non-proc
    platforms, vanished pid) — callers then fall back to PID-only."""
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            stat = f.read().decode(errors="replace")
        # comm can contain spaces/parens: field 22 counts from AFTER
        # the last ')' (fields 3..) — standard /proc/stat parsing.
        rest = stat.rsplit(")", 1)[1].split()
        return int(rest[19])  # field 22 overall = index 19 after comm
    except (OSError, IndexError, ValueError):
        return None


def read_endpoint(directory: str | None = None) -> dict | None:
    """The rendezvous-endpoint discovery record orphaned workers poll:
    ``{"addr", "port", "driver_epoch", "generation"}`` or None. Workers
    follow the HIGHEST driver epoch they have seen — a record at or
    below their current epoch is the dead driver's own and is ignored."""
    d = directory if directory is not None else state_dir()
    if not d:
        return None
    rec = _read_record(os.path.join(d, ENDPOINT_FILE))
    if rec is None:
        return None
    try:
        rec["driver_epoch"] = int(rec.get("driver_epoch", 0))
        rec["port"] = int(rec["port"])
        rec["addr"] = str(rec["addr"])
    except (KeyError, TypeError, ValueError):
        return None
    return rec


class DriverStateStore:
    """The driver-side handle: fenced snapshot saves, takeover loads,
    endpoint publication. One instance per driver process, constructed
    only when ``HOROVOD_DRIVER_STATE_DIR`` is set."""

    def __init__(self, directory: str, epoch: int = 0):
        self._dir = directory
        self.epoch = epoch
        os.makedirs(directory, exist_ok=True)
        try:
            # The snapshot carries the job's HMAC secret (the takeover
            # driver MUST resume it — a fresh key would 403 every
            # orphaned worker's rejoin), so the dir is operator-only.
            os.chmod(directory, 0o700)
        except OSError:
            pass
        self._log = get_logger()

    @property
    def directory(self) -> str:
        return self._dir

    @property
    def state_path(self) -> str:
        return os.path.join(self._dir, STATE_FILE)

    @property
    def endpoint_path(self) -> str:
        return os.path.join(self._dir, ENDPOINT_FILE)

    # -- fenced writes --------------------------------------------------------

    def _fenced_install(self, path: str, record: dict) -> None:
        """Install one record with the epoch fence: a higher epoch
        anywhere in the state dir — snapshot OR endpoint record, since a
        successor may have written either first — means THIS driver is
        the stale one: raise :class:`DriverFencedError`, touch nothing."""
        for probe in (self.state_path, self.endpoint_path):
            disk = _disk_epoch(probe)
            if disk is not None and disk > self.epoch:
                raise DriverFencedError(
                    f"driver epoch {self.epoch} superseded by epoch "
                    f"{disk} at {probe}; standing down")
        atomic_install(path, _encode(record))

    def save(self, snapshot: Mapping[str, Any]) -> None:
        """Persist one control-plane snapshot (fires the
        ``driver.snapshot`` fault point; ``raise`` simulates a storage
        blip, a SIGKILL mid-write is the torn-write chaos case the
        ``.prev`` fallback covers)."""
        if faults.fire(faults.DRIVER_SNAPSHOT):
            raise faults.InjectedFault("driver snapshot dropped")
        record = dict(snapshot)
        record["driver_epoch"] = self.epoch
        record["t_wall"] = time.time()
        self._fenced_install(self.state_path, record)

    def publish_endpoint(self, addr: str, port: int,
                         generation: int) -> None:
        """Refresh the shared-storage discovery record orphaned workers
        re-resolve the rendezvous endpoint from (same epoch fence). On a
        multi-tenant pod the record additionally carries the job id
        (``HOROVOD_JOB_ID``) — the scheduler resolves each job driver's
        live KV endpoint from exactly this record; absent outside a
        scheduled job so the single-job record stays byte-identical."""
        record = {
            "addr": addr,
            "port": int(port),
            "driver_epoch": self.epoch,
            "generation": int(generation),
        }
        job = os.environ.get("HOROVOD_JOB_ID")
        if job:
            record["job"] = job
        self._fenced_install(self.endpoint_path, record)

    # -- takeover loads -------------------------------------------------------

    def load(self) -> dict | None:
        """The newest verifiable snapshot (``.prev`` fallback on a torn
        current file), or None on a fresh state dir."""
        return _read_record(self.state_path)

    @classmethod
    def open(cls, directory: str) -> tuple["DriverStateStore", dict | None]:
        """Takeover entry: load the predecessor's snapshot (if any) and
        return a store whose epoch is one past the highest epoch the
        dir has seen — the restarted driver's fencing identity.

        The epoch is CLAIMED atomically (``O_EXCL`` marker file): two
        drivers relaunched concurrently by a flapping supervisor would
        otherwise both read epoch e and both serve as e+1 — equal
        epochs pass every fence, which is exactly the split brain this
        module exists to prevent. The loser of the claim race takes
        e+2 and immediately fences the winner out."""
        store = cls(directory)
        snap = store.load()
        prev = 0
        if snap is not None:
            try:
                prev = int(snap.get("driver_epoch", 0))
            except (TypeError, ValueError):
                prev = 0
        # The endpoint record can outlive a snapshot (or carry a higher
        # epoch after a crash between the two writes), and a claimed
        # epoch can predate both records (a driver that crashed before
        # its first save): the new epoch must clear ALL of them.
        ep = read_endpoint(directory)
        if ep is not None:
            prev = max(prev, ep["driver_epoch"])
        for name in os.listdir(directory):
            if name.startswith("epoch.") and name.endswith(".claim"):
                try:
                    prev = max(prev, int(name.split(".")[1]))
                except (IndexError, ValueError):
                    continue
        while True:
            epoch = prev + 1
            try:
                fd = os.open(
                    os.path.join(directory, f"epoch.{epoch}.claim"),
                    os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o600)
                os.close(fd)
                break
            except FileExistsError:
                prev = epoch  # raced: a peer claimed it — go higher
        store.epoch = epoch
        return store, snap
