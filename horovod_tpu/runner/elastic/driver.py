"""The elastic driver: keeps min_np ≤ world ≤ max_np across host churn.

Parity with ``horovod/runner/elastic/driver.py — ElasticDriver`` +
``registration.py`` + ``worker.py``: polls host discovery, launches and
monitors workers, blacklists failing hosts, re-forms the world on change,
and notifies surviving workers.

TPU-native notification contract (replacing the reference's per-worker
``WorkerNotificationService`` TCP push): the driver publishes each world
epoch to the rendezvous KV server —

- ``GET /_version``                      → current world version (bumped on
  every reconfiguration; workers poll this cheaply)
- ``GET /world/<version>``  (key = hostname) → JSON assignment for that host:
  ``{"process_id", "num_processes", "coordinator", "slots", "hosts"}``

Workers poll the version between commits (``worker.py — ElasticWorkerLoop``);
a bump surfaces as ``HostsUpdatedInterrupt`` and the worker re-reads its
assignment for the new version. A host absent from the new epoch exits
cleanly.
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time

from ... import faults
from ... import metrics as _metrics
from ...elastic.policy import PolicyController
from ...exceptions import HostDiscoveryFailedError
from ...utils.env import get_float
from ...utils.logging import get_logger
from ..exec_utils import (
    WorkerProc,
    build_worker_env,
    drain_worker,
    launch_worker,
    terminate_worker,
    terminate_workers,
)
from ..hosts import HostInfo, ProcessAssignment, get_host_assignments
from ..http.kv_server import RendezvousServer
from ..network import coordinator_addr, driver_addr, free_port
from .discovery import FixedHostDiscovery, HostDiscoveryScript, HostManager

from .constants import (  # noqa: E402  (EXIT_REMOVED re-exported for users)
    EXIT_DRIVER_LOST,
    EXIT_REMOVED,
)

WORLD_SCOPE = "world"


class ElasticDriver:
    def __init__(
        self,
        settings,  # runner.launch.Settings
        discovery=None,
        sink=None,
        poll_interval: float = 1.0,
    ):
        self._settings = settings
        self._log = get_logger()
        self._sink = sink
        self._poll_interval = poll_interval
        if discovery is None:
            if settings.discovery_script:
                discovery = HostDiscoveryScript(settings.discovery_script)
            else:
                discovery = FixedHostDiscovery(settings.hosts)
        self._manager = HostManager(discovery)
        # Secret before server construction: the server snapshots its HMAC
        # key at __init__ (a later setdefault would leave it open-mode).
        from .. import secret as _secret

        os.environ.setdefault(_secret.ENV_KEY, _secret.make_secret_key())
        self._server = RendezvousServer()
        self._workers: dict[str, WorkerProc] = {}
        self._launched_at: dict[str, float] = {}  # host -> monotonic launch
        self._driver_lost_counts: dict[str, int] = {}  # consecutive rc=203
        self._world_hosts: list[HostInfo] = []
        self._coord_port: int = 0
        self._native_port: int = 0
        self._shutdown = False
        self._min_np = settings.min_np or 1
        self._max_np = settings.max_np
        # Liveness plane: a host silent for hb_timeout seconds is declared
        # dead (hung, not crashed — popen.poll() cannot see it) and is
        # killed/blacklisted like a failure. 0 disables enforcement (a
        # worker that never heartbeats — plain scripts — stays safe by
        # default). A host that has NEVER heartbeated gets hb_grace from
        # its launch instead, covering interpreter/framework startup.
        self._hb_timeout = get_float("HOROVOD_ELASTIC_HEARTBEAT_TIMEOUT", 0.0)
        self._hb_grace = get_float(
            "HOROVOD_ELASTIC_HEARTBEAT_GRACE",
            max(10.0 * self._hb_timeout, 60.0),
        )
        # Self-healing policy plane (ROADMAP item 3): the controller that
        # turns the straggler/goodput sensors into proactive drains. Inert
        # unless HOROVOD_TARGET_GOODPUT is set; the warm-spare tier is
        # governed independently by HOROVOD_WARM_SPARES (via HostManager).
        self._policy = PolicyController(min_np=self._min_np)
        self._spare_procs: dict[str, WorkerProc] = {}
        self._rate_state: dict[str, tuple[float, float]] = {}
        self._last_policy_tick = 0.0
        self._draining = False

    # -- world formation -----------------------------------------------------

    def _wait_for_available_slots(self, min_np: int, timeout: float) -> list[HostInfo]:
        """Block until discovery yields ≥ min_np usable hosts (parity:
        ``ElasticDriver.wait_for_available_slots``)."""
        deadline = time.time() + timeout
        while True:
            try:
                self._manager.update_available_hosts()
            except HostDiscoveryFailedError:
                raise  # sustained streak: the driver is blind — fail loudly
            except Exception as e:  # discovery script hiccup: retry
                self._log.warning("elastic: discovery failed (%s); retrying", e)
            hosts = self._manager.pick_world(
                [h.hostname for h in self._world_hosts], self._max_np
            )
            if len(hosts) >= min_np:
                return hosts
            if time.time() >= deadline:
                raise TimeoutError(
                    f"elastic: {len(hosts)} host(s) available after "
                    f"{timeout:.0f}s; need min_np={min_np}"
                )
            time.sleep(self._poll_interval)

    def _publish_world(self, hosts: list[HostInfo]) -> int:
        """Publish the new epoch's assignments, then bump the version (the
        scope is written before the bump so in-flight workers of the
        previous epoch never read a hole)."""
        assignments = get_host_assignments(hosts)
        coord = coordinator_addr([h.hostname for h in hosts])
        self._coord_port = free_port()
        self._native_port = free_port()
        data = {
            a.hostname: json.dumps(
                {
                    "process_id": a.rank,
                    "num_processes": a.size,
                    "coordinator": f"{coord}:{self._coord_port}",
                    "native_port": self._native_port,
                    "slots": a.slots,
                    "hosts": [[h.hostname, h.slots] for h in hosts],
                }
            ).encode()
            for a in assignments
        }
        version = self._server.publish_epoch(WORLD_SCOPE, data)
        self._world_hosts = hosts
        # Scrape gauges + lifecycle journal: one record per world epoch,
        # stamped with the generation the epoch IS.
        self._server.set_cluster_info(
            world_np=len(hosts),
            blacklisted=self._manager.blacklist_count())
        _metrics.event(
            "world_published", generation=version, np=len(hosts),
            hosts=[h.hostname for h in hosts])
        return version

    def _launch_missing_workers(self, version: int) -> None:
        assignments = get_host_assignments(self._world_hosts)
        kv_addr = driver_addr([a.hostname for a in assignments])
        coord_addr = coordinator_addr([a.hostname for a in assignments])
        for a in assignments:
            w = self._workers.get(a.hostname)
            if w is not None and w.popen.poll() is None:
                continue  # alive: keep it
            if w is not None:
                if w.popen.returncode == 0:
                    # Completed job racing a reconfiguration: leave the
                    # corpse for the monitor, which surfaces rc=0 as job
                    # completion — relaunching would silently restart a
                    # finished job.
                    continue
                # Failed/removed but not yet reaped (a whole GENERATION
                # crashing lands here: the first reap triggers
                # reconfiguration while peers' corpses still occupy the
                # table) — sweep it so the host gets its new-generation
                # worker now instead of after another monitor round. A
                # re-crash gets reaped (and blacklisted) by the monitor
                # normally.
                del self._workers[a.hostname]
            sp = self._spare_procs.pop(a.hostname, None)
            if sp is not None and sp.popen.poll() is None:
                # Warm-spare promotion: the host already runs a launched,
                # heartbeating, framework-imported worker parked on the
                # assignment wait — move it into the world instead of
                # cold-launching. Its poll loop sees this version bump and
                # fetches the assignment; the join costs one
                # re-rendezvous. Heartbeat record deliberately kept (it
                # is live — clearing it would reset liveness to the
                # never-heartbeated grace).
                try:
                    if faults.fire(faults.SPARE_PROMOTE):
                        raise faults.InjectedFault(
                            "spare promotion dropped")
                    self._workers[a.hostname] = sp
                    self._server.clear_spare(a.hostname)
                    self._server.record_policy_action("promote")
                    _metrics.POLICY_DECISIONS.inc(action="promote")
                    _metrics.event("spare_promoted", generation=version,
                                   host=a.hostname, rank=a.rank)
                    self._log.info(
                        "elastic: promoting warm spare on %s into the "
                        "world (rank %d/%d, v%d)",
                        a.hostname, a.rank, a.size, version,
                    )
                    continue
                except Exception as e:  # noqa: BLE001 — chaos/injection
                    self._log.warning(
                        "elastic: spare promotion on %s failed (%s); "
                        "falling back to a cold launch", a.hostname, e,
                    )
                    terminate_worker(sp)
                    self._server.clear_spare(a.hostname)
            env = build_worker_env(
                a,
                base_env=dict(os.environ),
                rendezvous_addr=kv_addr,
                rendezvous_port=self._server.port,
                coordinator_addr=coord_addr,
                coordinator_port=self._coord_port,
                native_port=self._native_port,
                cpu_mode=self._settings.cpu_mode,
                extra_env={
                    **self._settings.env,
                    "HOROVOD_ELASTIC": "1",
                    "HOROVOD_WORLD_VERSION": str(version),
                    "HOROVOD_HOSTNAME": a.hostname,
                },
            )
            self._log.info(
                "elastic: launching worker on %s (process %d/%d, v%d)",
                a.hostname, a.rank, a.size, version,
            )
            # Fresh liveness record per launch: a relaunched host must
            # neither inherit its predecessor's recent heartbeat (masking
            # a hung start) nor its silence (instant condemnation) — it
            # gets the never-heartbeated grace window from launch instead.
            self._server.clear_heartbeat(a.hostname)
            self._launched_at[a.hostname] = time.monotonic()
            self._workers[a.hostname] = launch_worker(
                a, self._settings.command, env,
                ssh_port=self._settings.ssh_port, sink=self._sink,
            )

    def _reconfigure(self) -> None:
        t0 = time.monotonic()
        hosts = self._manager.pick_world(
            [h.hostname for h in self._world_hosts], self._max_np
        )
        if len(hosts) < self._min_np:
            hosts = self._wait_for_available_slots(
                self._min_np, self._settings.elastic_timeout
            )
        if (self._manager.warm_spares_target > 0
                and [(h.hostname, h.slots) for h in hosts]
                == [(h.hostname, h.slots) for h in self._world_hosts]
                and all(h.hostname in self._workers
                        and self._workers[h.hostname].popen.poll() is None
                        for h in hosts)):
            # Spare-tier-only change (a cooldown-returned host routed to
            # standby, a surplus host discovered): the WORLD is unchanged
            # AND every world host still runs a live worker — a host
            # reaped without blacklisting (EXIT_DRIVER_LOST) keeps its
            # world slot and MUST fall through to the relaunch below.
            # Publishing a new epoch here would only churn every worker
            # through a re-sync; refresh the spare fleet instead.
            self._ensure_spares(self._server.version)
            return
        keep = {h.hostname for h in hosts}
        # Kill workers on hosts that left the world.
        leaving = [n for n in self._workers if n not in keep]
        for name in leaving:
            self._log.info("elastic: removing worker on %s", name)
            self._server.clear_heartbeat(name)
            self._launched_at.pop(name, None)
        terminate_workers([self._workers.pop(n) for n in leaving])
        version = self._publish_world(hosts)
        self._launch_missing_workers(version)
        self._ensure_spares(version)
        # The SLO gate weighs a voluntary drain against the MEASURED
        # price of a re-rendezvous, not an assumed one.
        self._policy.note_resize_cost(time.monotonic() - t0)

    # -- main loop -----------------------------------------------------------

    def run(self) -> int:
        _metrics.event("driver_start", generation=0,
                       min_np=self._min_np, max_np=self._max_np)
        hosts = self._wait_for_available_slots(
            self._min_np, self._settings.elastic_timeout
        )
        self._server.start()
        version = self._publish_world(hosts)
        self._launch_missing_workers(version)
        self._ensure_spares(version)
        prev_sigterm = self._install_sigterm_forwarder()
        try:
            return self._monitor()
        finally:
            terminate_workers(list(self._workers.values())
                              + list(self._spare_procs.values()))
            try:
                # A decision whose realization window the job outlived
                # still gets its policy_decision record (partial window).
                self._policy.flush()
            except Exception:  # noqa: BLE001 — shutdown must finish
                pass
            if prev_sigterm is not None:
                try:
                    signal.signal(signal.SIGTERM, prev_sigterm)
                except (ValueError, OSError):
                    pass
            self._server.stop()

    def _dead_by_heartbeat(
            self, procs: dict[str, WorkerProc] | None = None,
    ) -> list[tuple[str, str]]:
        """Hosts the liveness plane has declared dead: (host, why) pairs.

        A host is dead when its last heartbeat is older than hb_timeout,
        or — if it has NEVER heartbeated — when hb_grace has elapsed since
        its launch (interpreter startup, framework import). popen.poll()
        cannot see either case: a SIGSTOP'd process, a wedged TPU VM, or a
        livelocked trainer is still "running" to the OS. ``procs``
        defaults to the world workers; the spare fleet is checked with the
        same rule (a hung spare is a replacement that would not replace).
        """
        if self._hb_timeout <= 0:
            return []
        if procs is None:
            procs = self._workers
        dead: list[tuple[str, str]] = []
        now = time.monotonic()
        for name, w in procs.items():
            if w.popen.poll() is not None:
                continue  # exited: the reap path owns it
            age = self._server.heartbeat_age(name)
            if age is None:
                launched = self._launched_at.get(name)
                if launched is not None and now - launched >= self._hb_grace:
                    dead.append((name, (
                        f"no heartbeat within {self._hb_grace:.0f}s "
                        "grace of launch")))
            elif age >= self._hb_timeout:
                dead.append((name, (
                    f"heartbeat silent for {age:.0f}s "
                    f"(timeout {self._hb_timeout:.0f}s)")))
        return dead

    def _post_abort(self, reason: str) -> None:
        """Post the coordinated-abort record for the CURRENT generation
        (the dying world) before `_reconfigure` bumps it: survivors wedged
        in a collective with the dead peer poll the flag and convert the
        wedge into HorovodInternalError → elastic recovery, instead of
        blocking forever inside a native allreduce no one will complete."""
        gen = self._server.post_abort(reason)
        _metrics.event("abort_posted", generation=gen, reason=reason,
                       source="driver")
        self._log.warning(
            "elastic: posting coordinated abort for world generation %d "
            "(%s)", gen, reason,
        )

    def _blacklist(self, name: str, why: str) -> None:
        """Blacklist + journal + refresh the scrape gauge in one place."""
        self._manager.blacklist(name)
        self._server.set_cluster_info(
            blacklisted=self._manager.blacklist_count())
        _metrics.event("blacklist", generation=self._server.generation,
                       host=name, reason=why)

    # -- warm spares ---------------------------------------------------------

    def _launch_spare(self, host: HostInfo, version: int) -> None:
        """Launch a WARM SPARE worker on ``host``: same command, same env
        contract, plus ``HOROVOD_SPARE=1`` — the worker imports its
        frameworks, heartbeats, registers at ``PUT /spare/<host>``, and
        parks on the assignment wait until a world includes it."""
        assignment = ProcessAssignment(
            hostname=host.hostname, rank=0, size=1, local_rank=0,
            local_size=1, cross_rank=0, cross_size=1, slots=host.slots,
            first_device_rank=0)
        world_names = [h.hostname for h in self._world_hosts]
        env = build_worker_env(
            assignment,
            base_env=dict(os.environ),
            rendezvous_addr=driver_addr(world_names + [host.hostname]),
            rendezvous_port=self._server.port,
            coordinator_addr=coordinator_addr(world_names or
                                              [host.hostname]),
            coordinator_port=self._coord_port,
            native_port=self._native_port,
            cpu_mode=self._settings.cpu_mode,
            extra_env={
                **self._settings.env,
                "HOROVOD_ELASTIC": "1",
                "HOROVOD_SPARE": "1",
                "HOROVOD_WORLD_VERSION": str(version),
                "HOROVOD_HOSTNAME": host.hostname,
            },
        )
        self._log.info("elastic: launching warm spare on %s (v%d)",
                       host.hostname, version)
        self._server.clear_heartbeat(host.hostname)
        self._launched_at[host.hostname] = time.monotonic()
        self._spare_procs[host.hostname] = launch_worker(
            assignment, self._settings.command, env,
            ssh_port=self._settings.ssh_port, sink=self._sink,
        )
        _metrics.event("spare_launched", generation=version,
                       host=host.hostname)

    def _retire_spare(self, name: str, why: str, version: int) -> None:
        w = self._spare_procs.pop(name, None)
        if w is None:
            return
        self._log.info("elastic: retiring spare on %s (%s)", name, why)
        terminate_worker(w)
        self._launched_at.pop(name, None)
        self._server.clear_heartbeat(name)
        self._server.clear_spare(name)
        _metrics.event("spare_retired", generation=version, host=name,
                       reason=why)

    def _ensure_spares(self, version: int) -> None:
        """Reconcile the spare fleet with the HostManager's spare tier:
        reap exits, kill hung spares (same liveness rule as the world —
        but no abort, no reconfigure: spares are not in anyone's
        collectives), retire tier-leavers, launch tier-joiners."""
        if self._manager.warm_spares_target <= 0 and not self._spare_procs:
            return
        for name in [n for n, w in self._spare_procs.items()
                     if w.popen.poll() is not None]:
            w = self._spare_procs.pop(name)
            self._launched_at.pop(name, None)
            self._server.clear_heartbeat(name)
            self._server.clear_spare(name)
            _metrics.event("spare_exit", generation=version, host=name,
                           rc=w.popen.returncode)
            self._log.warning(
                "elastic: spare on %s exited rc=%d; the tier will "
                "relaunch it while the host stays discovered",
                name, w.popen.returncode)
        for name, why in self._dead_by_heartbeat(self._spare_procs):
            self._log.warning(
                "elastic: spare on %s is hung (%s); killing", name, why)
            _metrics.event("spare_hung", generation=version, host=name,
                           reason=why)
            self._retire_spare(name, f"hung: {why}", version)
        tier = {h.hostname: h for h in self._manager.spare_hosts()}
        for name in [n for n in self._spare_procs if n not in tier]:
            self._retire_spare(name, "left the spare tier", version)
        for name, h in tier.items():
            if name not in self._spare_procs and name not in self._workers:
                self._launch_spare(h, version)
        self._server.set_cluster_info(spares=len(self._spare_procs))
        _metrics.POLICY_SPARES.set(len(self._spare_procs))

    def _warm_spare_count(self) -> int:
        """Spares that are launched, registered (framework-imported), and
        fresh on the liveness plane — the replacements a drain may count
        on joining at the next generation fence."""
        registered = self._server.spare_records()
        warm = 0
        for name, w in self._spare_procs.items():
            if w.popen.poll() is not None or name not in registered:
                continue
            age = self._server.heartbeat_age(name)
            if age is None:
                continue
            if self._hb_timeout > 0 and age >= self._hb_timeout:
                continue
            warm += 1
        return warm

    # -- proactive drain (policy + preemption notices) ------------------------

    def _drain_host(self, name: str, why: str, decision=None,
                    action: str = "drain") -> None:
        """Proactively drain one world host through the existing
        SIGTERM→final-commit path, then re-form the world without it.

        SIGTERM first: the worker's drain handler finishes its current
        step, lands a final commit at the STILL-CURRENT generation (the
        fence would 409 it after the bump), and exits ``EXIT_REMOVED``.
        Only after the exit (or the drain grace) does the driver post the
        coordinated abort — unwedging survivors blocked with the departed
        peer — blacklist the host, and reconfigure; a warm spare then
        joins at the new generation fence."""
        w = self._workers.get(name)
        if w is None:
            return
        gen = self._server.generation
        # Post-hoc "why did you replace that host": the driver-side
        # flight record carries the host's last shipped trace window and
        # the evidence that condemned it.
        payload = self._server.trace_payload(name) or {}
        _metrics.FLIGHT_DUMPS.inc(reason="policy_drain")
        _metrics.event(
            "flight_record", generation=gen, reason="policy_drain",
            host=name,
            steps=(payload.get("steps") or [])[-2:],
            clock_offset_s=payload.get("clock_offset_s"),
            evidence=(decision.evidence if decision is not None else None))
        self._log.warning(
            "elastic: proactively draining worker on %s (%s)", name, why)
        # Remote-aware TERM delivery: a raw local killpg cannot reach an
        # ssh-launched worker's remote tree (pty teardown is SIGHUP, not
        # SIGTERM — the drain handler would never run).
        drain_worker(w)
        grace = get_float("HOROVOD_POLICY_DRAIN_GRACE", 20.0)
        deadline = time.monotonic() + grace
        while time.monotonic() < deadline and w.popen.poll() is None:
            time.sleep(0.05)
        rc = w.popen.poll()
        if rc is None:
            self._log.warning(
                "elastic: drained worker on %s still alive after %.0fs "
                "grace; escalating to SIGKILL", name, grace)
        _metrics.event("policy_drain", generation=gen, host=name,
                       action=action, reason=why, rc=rc)
        self._post_abort(f"proactive drain of {name} ({why})")
        terminate_worker(self._workers.pop(name))
        self._launched_at.pop(name, None)
        self._server.clear_heartbeat(name)
        self._blacklist(name, f"{action}: {why}")
        self._server.record_policy_action(action)
        if decision is not None:
            # record_drain counts the action into POLICY_DECISIONS.
            self._policy.record_drain(decision, generation=gen)
        else:
            _metrics.POLICY_DECISIONS.inc(action=action)
        self._reconfigure()

    def _handle_preempt_notices(self, version: int) -> None:
        """External preemption notices (``PUT /preempt/<host>``) become
        drain signals end to end: the DRIVER forwards the SIGTERM to that
        host's worker — the notice works even when the cloud cannot
        signal the worker process directly. Consumed once handled."""
        for name in self._server.preempt_notices():
            self._server.consume_preempt(name)
            _metrics.event("preempt_notice", generation=version, host=name)
            if name in self._workers:
                self._log.warning(
                    "elastic: preemption notice for %s — draining via "
                    "SIGTERM forward", name)
                self._drain_host(name, "external preemption notice",
                                 action="preempt")
            elif name in self._spare_procs:
                self._retire_spare(name, "external preemption notice",
                                   version)
                self._blacklist(name, "external preemption notice")
            else:
                # Not running anything of ours, but about to vanish:
                # keep pick_world from choosing it (cooldown re-admits).
                self._blacklist(name, "external preemption notice")

    def _install_sigterm_forwarder(self):
        """Driver-level preemption: SIGTERM on the DRIVER forwards the
        drain to every worker and spare per host, so a launcher-level
        notice drains the whole job through final commits instead of
        dying with uncommitted epochs. Returns the previous handler (to
        restore on exit) or None when not installable (non-main thread,
        exotic hosts)."""
        if threading.current_thread() is not threading.main_thread():
            return None

        def _on_sigterm(signum, frame):
            if self._draining:
                return
            self._draining = True
            _metrics.event("driver_drain",
                           generation=self._server.generation)
            self._log.warning(
                "elastic: driver received SIGTERM (preemption notice) — "
                "forwarding the drain to %d worker(s) and %d spare(s)",
                len(self._workers), len(self._spare_procs))
            for w in (list(self._workers.values())
                      + list(self._spare_procs.values())):
                drain_worker(w)

        try:
            return signal.signal(signal.SIGTERM, _on_sigterm)
        except (ValueError, OSError):
            return None

    # -- policy tick ---------------------------------------------------------

    def _update_world_rate(self) -> None:
        """Feed the policy's throughput signal: per-host commit rates
        from successive heartbeat payload counters, averaged over the
        world (counter resets across relaunches reseed, never go
        negative)."""
        now = time.monotonic()
        world = {h.hostname for h in self._world_hosts}
        for name in [n for n in self._rate_state if n not in world]:
            del self._rate_state[name]
        rates = []
        for name in world:
            raw = self._server.heartbeat_payload(name)
            if raw is None:
                continue
            try:
                commits = json.loads(raw).get("commits")
            except (ValueError, UnicodeDecodeError):
                continue
            if not isinstance(commits, (int, float)):
                continue
            prev = self._rate_state.get(name)
            self._rate_state[name] = (float(commits), now)
            if prev is None:
                continue
            prev_commits, prev_t = prev
            dt = now - prev_t
            delta = float(commits) - prev_commits
            if dt <= 0 or delta < 0:
                continue
            rates.append(delta / dt)
        if rates:
            self._policy.note_rate(sum(rates) / len(rates))

    def _policy_tick(self) -> None:
        """One self-healing evaluation (throttled to the policy
        interval): reconcile spares, consume preemption notices, and —
        when the SLO knob arms the controller — fold the skew/heartbeat
        evidence, decide, and drain. A policy failure must never take the
        driver down; the monitor wraps this call."""
        now = time.monotonic()
        if now - self._last_policy_tick < max(
                min(self._policy.interval_s, 30.0), 0.25):
            return
        self._last_policy_tick = now
        version = self._server.generation
        self._ensure_spares(version)
        self._handle_preempt_notices(version)
        if not self._policy.enabled:
            return  # inert: no evidence gathering, no decisions
        self._update_world_rate()
        try:
            skew = self._server.straggler_summary()
        except Exception as e:  # noqa: BLE001 — evidence is best-effort
            self._log.debug("elastic: straggler summary failed: %s", e)
            skew = {}
        world_names = [h.hostname for h in self._world_hosts]
        self._policy.observe(skew, self._server.heartbeat_ages(),
                             world_names)
        decision = self._policy.decide(world_names,
                                       self._warm_spare_count())
        if decision is not None and decision.host in self._workers:
            self._drain_host(decision.host, decision.reason,
                             decision=decision, action=decision.action)
        realized = self._policy.realize_tick()
        if realized is not None:
            self._log.info(
                "elastic: policy decision on %s realized: %s",
                realized.host, realized.predicted.get("realized"))

    def _monitor(self) -> int:
        last_poll = 0.0
        while True:
            # 1. Reap exited workers.
            finished = {
                n: w for n, w in self._workers.items()
                if w.popen.poll() is not None
            }
            need_reconfigure = False
            for name, w in finished.items():
                rc = w.popen.returncode
                del self._workers[name]
                self._launched_at.pop(name, None)
                self._server.clear_heartbeat(name)
                _metrics.event("worker_exit",
                               generation=self._server.generation,
                               host=name, rc=rc)
                if rc == 0:
                    # Success on any worker ⇒ the job completed (reference
                    # semantics: the training function returned).
                    self._log.info("elastic: worker on %s finished ok", name)
                    _metrics.event("job_complete",
                                   generation=self._server.generation,
                                   host=name)
                    return 0
                if rc == EXIT_REMOVED:
                    # Clean self-exit of a worker dropped from the world —
                    # not a failure, not job completion.
                    self._log.info("elastic: removed worker on %s exited", name)
                    continue
                if rc == EXIT_DRIVER_LOST:
                    # The worker gave up on an unreachable rendezvous KV.
                    # If we are here to see it, the driver process is alive
                    # — a partition or KV fault, i.e. a CONTROL-PLANE
                    # problem, not a host problem: relaunch the worker but
                    # do not poison the blacklist with a healthy host.
                    # Capped: a PERSISTENT per-host KV fault (firewalled
                    # port) must not churn the whole fleet through a
                    # reconfiguration every driver-loss deadline forever —
                    # after 3 consecutive 203s the host is blacklisted
                    # like any failure.
                    n = self._driver_lost_counts.get(name, 0) + 1
                    self._driver_lost_counts[name] = n
                    if n <= 3:
                        self._log.error(
                            "elastic: worker on %s lost the rendezvous KV "
                            "(rc=%d, %d consecutive) — control-plane "
                            "fault, not a host fault; relaunching without "
                            "blacklisting", name, rc, n,
                        )
                        self._post_abort(
                            f"worker on {name} exited EXIT_DRIVER_LOST")
                        need_reconfigure = True
                        continue
                    self._log.error(
                        "elastic: worker on %s lost the rendezvous KV %d "
                        "consecutive times — persistent; blacklisting",
                        name, n,
                    )
                    del self._driver_lost_counts[name]
                    self._post_abort(
                        f"worker on {name} lost the rendezvous KV "
                        f"{n} consecutive times; blacklisted")
                    self._blacklist(
                        name, f"{n} consecutive EXIT_DRIVER_LOST exits")
                    need_reconfigure = True
                    continue
                self._driver_lost_counts.pop(name, None)
                self._log.warning(
                    "elastic: worker on %s failed (rc=%d); blacklisting",
                    name, rc,
                )
                self._post_abort(
                    f"worker on {name} failed with rc={rc}; blacklisted")
                self._blacklist(name, f"worker failed with rc={rc}")
                need_reconfigure = True
            # 1b. Liveness plane: kill + blacklist hosts the heartbeat
            # deadline has condemned (hung, not crashed — invisible to the
            # reap above). terminate_worker escalates SIGTERM→SIGKILL, and
            # SIGKILL lands even on a SIGSTOP'd process.
            for name, why in self._dead_by_heartbeat():
                self._log.warning(
                    "elastic: worker on %s is hung (%s); killing and "
                    "blacklisting", name, why,
                )
                # Abort FIRST, kill second: survivors wedged with the hung
                # peer should already be polling the flag when the SIGKILL
                # lands, whichever unblocks them first.
                self._post_abort(f"worker on {name} is hung ({why}); killed")
                _metrics.event("worker_hung",
                               generation=self._server.generation,
                               host=name, reason=why)
                terminate_worker(self._workers.pop(name))
                self._launched_at.pop(name, None)
                self._server.clear_heartbeat(name)
                self._blacklist(name, f"hung: {why}")
                need_reconfigure = True
            # Driver-level drain: once every worker has exited (final
            # commits landed, EXIT_REMOVED reaped above), the job is
            # drained — don't re-form a world we were told to vacate.
            if self._draining:
                if not self._workers:
                    self._log.info("elastic: drain complete; exiting")
                    _metrics.event("driver_drained",
                                   generation=self._server.generation)
                    return 0
                time.sleep(0.05)
                continue
            if need_reconfigure:
                self._reconfigure()
                continue
            # 1c. Self-healing policy plane: warm-spare reconciliation,
            # preemption notices, and (when HOROVOD_TARGET_GOODPUT arms
            # it) straggler-drain decisions. Policy failures are logged,
            # never fatal — a broken brain must not kill the body.
            try:
                self._policy_tick()
            except Exception as e:  # noqa: BLE001
                self._log.warning("elastic: policy tick failed: %s", e)
            # 2. Poll discovery.
            if time.time() - last_poll >= self._poll_interval:
                last_poll = time.time()
                try:
                    changed = self._manager.update_available_hosts()
                except HostDiscoveryFailedError:
                    raise  # sustained streak: fail the job loudly
                except Exception as e:
                    self._log.warning("elastic: discovery failed: %s", e)
                    changed = False
                if changed:
                    self._log.info("elastic: host set changed; reconfiguring")
                    self._reconfigure()
            time.sleep(0.05)


def run_elastic(settings, sink=None, discovery=None) -> int:
    """Entry used by ``hvdrun --host-discovery-script ...``."""
    driver = ElasticDriver(settings, discovery=discovery, sink=sink)
    return driver.run()
