"""The elastic driver: keeps min_np ≤ world ≤ max_np across host churn.

Parity with ``horovod/runner/elastic/driver.py — ElasticDriver`` +
``registration.py`` + ``worker.py``: polls host discovery, launches and
monitors workers, blacklists failing hosts, re-forms the world on change,
and notifies surviving workers.

TPU-native notification contract (replacing the reference's per-worker
``WorkerNotificationService`` TCP push): the driver publishes each world
epoch to the rendezvous KV server —

- ``GET /_version``                      → current world version (bumped on
  every reconfiguration; workers poll this cheaply)
- ``GET /world/<version>``  (key = hostname) → JSON assignment for that host:
  ``{"process_id", "num_processes", "coordinator", "slots", "hosts"}``

Workers poll the version between commits (``worker.py — ElasticWorkerLoop``);
a bump surfaces as ``HostsUpdatedInterrupt`` and the worker re-reads its
assignment for the new version. A host absent from the new epoch exits
cleanly.
"""

from __future__ import annotations

import json
import os
import time

from ... import metrics as _metrics
from ...exceptions import HostDiscoveryFailedError
from ...utils.env import get_float
from ...utils.logging import get_logger
from ..exec_utils import (
    WorkerProc,
    build_worker_env,
    launch_worker,
    terminate_worker,
    terminate_workers,
)
from ..hosts import HostInfo, get_host_assignments
from ..http.kv_server import RendezvousServer
from ..network import coordinator_addr, driver_addr, free_port
from .discovery import FixedHostDiscovery, HostDiscoveryScript, HostManager

from .constants import (  # noqa: E402  (EXIT_REMOVED re-exported for users)
    EXIT_DRIVER_LOST,
    EXIT_REMOVED,
)

WORLD_SCOPE = "world"


class ElasticDriver:
    def __init__(
        self,
        settings,  # runner.launch.Settings
        discovery=None,
        sink=None,
        poll_interval: float = 1.0,
    ):
        self._settings = settings
        self._log = get_logger()
        self._sink = sink
        self._poll_interval = poll_interval
        if discovery is None:
            if settings.discovery_script:
                discovery = HostDiscoveryScript(settings.discovery_script)
            else:
                discovery = FixedHostDiscovery(settings.hosts)
        self._manager = HostManager(discovery)
        # Secret before server construction: the server snapshots its HMAC
        # key at __init__ (a later setdefault would leave it open-mode).
        from .. import secret as _secret

        os.environ.setdefault(_secret.ENV_KEY, _secret.make_secret_key())
        self._server = RendezvousServer()
        self._workers: dict[str, WorkerProc] = {}
        self._launched_at: dict[str, float] = {}  # host -> monotonic launch
        self._driver_lost_counts: dict[str, int] = {}  # consecutive rc=203
        self._world_hosts: list[HostInfo] = []
        self._coord_port: int = 0
        self._native_port: int = 0
        self._shutdown = False
        self._min_np = settings.min_np or 1
        self._max_np = settings.max_np
        # Liveness plane: a host silent for hb_timeout seconds is declared
        # dead (hung, not crashed — popen.poll() cannot see it) and is
        # killed/blacklisted like a failure. 0 disables enforcement (a
        # worker that never heartbeats — plain scripts — stays safe by
        # default). A host that has NEVER heartbeated gets hb_grace from
        # its launch instead, covering interpreter/framework startup.
        self._hb_timeout = get_float("HOROVOD_ELASTIC_HEARTBEAT_TIMEOUT", 0.0)
        self._hb_grace = get_float(
            "HOROVOD_ELASTIC_HEARTBEAT_GRACE",
            max(10.0 * self._hb_timeout, 60.0),
        )

    # -- world formation -----------------------------------------------------

    def _wait_for_available_slots(self, min_np: int, timeout: float) -> list[HostInfo]:
        """Block until discovery yields ≥ min_np usable hosts (parity:
        ``ElasticDriver.wait_for_available_slots``)."""
        deadline = time.time() + timeout
        while True:
            try:
                self._manager.update_available_hosts()
            except HostDiscoveryFailedError:
                raise  # sustained streak: the driver is blind — fail loudly
            except Exception as e:  # discovery script hiccup: retry
                self._log.warning("elastic: discovery failed (%s); retrying", e)
            hosts = self._manager.pick_world(
                [h.hostname for h in self._world_hosts], self._max_np
            )
            if len(hosts) >= min_np:
                return hosts
            if time.time() >= deadline:
                raise TimeoutError(
                    f"elastic: {len(hosts)} host(s) available after "
                    f"{timeout:.0f}s; need min_np={min_np}"
                )
            time.sleep(self._poll_interval)

    def _publish_world(self, hosts: list[HostInfo]) -> int:
        """Publish the new epoch's assignments, then bump the version (the
        scope is written before the bump so in-flight workers of the
        previous epoch never read a hole)."""
        assignments = get_host_assignments(hosts)
        coord = coordinator_addr([h.hostname for h in hosts])
        self._coord_port = free_port()
        self._native_port = free_port()
        data = {
            a.hostname: json.dumps(
                {
                    "process_id": a.rank,
                    "num_processes": a.size,
                    "coordinator": f"{coord}:{self._coord_port}",
                    "native_port": self._native_port,
                    "slots": a.slots,
                    "hosts": [[h.hostname, h.slots] for h in hosts],
                }
            ).encode()
            for a in assignments
        }
        version = self._server.publish_epoch(WORLD_SCOPE, data)
        self._world_hosts = hosts
        # Scrape gauges + lifecycle journal: one record per world epoch,
        # stamped with the generation the epoch IS.
        self._server.set_cluster_info(
            world_np=len(hosts),
            blacklisted=self._manager.blacklist_count())
        _metrics.event(
            "world_published", generation=version, np=len(hosts),
            hosts=[h.hostname for h in hosts])
        return version

    def _launch_missing_workers(self, version: int) -> None:
        assignments = get_host_assignments(self._world_hosts)
        kv_addr = driver_addr([a.hostname for a in assignments])
        coord_addr = coordinator_addr([a.hostname for a in assignments])
        for a in assignments:
            w = self._workers.get(a.hostname)
            if w is not None and w.popen.poll() is None:
                continue  # alive: keep it
            if w is not None:
                if w.popen.returncode == 0:
                    # Completed job racing a reconfiguration: leave the
                    # corpse for the monitor, which surfaces rc=0 as job
                    # completion — relaunching would silently restart a
                    # finished job.
                    continue
                # Failed/removed but not yet reaped (a whole GENERATION
                # crashing lands here: the first reap triggers
                # reconfiguration while peers' corpses still occupy the
                # table) — sweep it so the host gets its new-generation
                # worker now instead of after another monitor round. A
                # re-crash gets reaped (and blacklisted) by the monitor
                # normally.
                del self._workers[a.hostname]
            env = build_worker_env(
                a,
                base_env=dict(os.environ),
                rendezvous_addr=kv_addr,
                rendezvous_port=self._server.port,
                coordinator_addr=coord_addr,
                coordinator_port=self._coord_port,
                native_port=self._native_port,
                cpu_mode=self._settings.cpu_mode,
                extra_env={
                    **self._settings.env,
                    "HOROVOD_ELASTIC": "1",
                    "HOROVOD_WORLD_VERSION": str(version),
                    "HOROVOD_HOSTNAME": a.hostname,
                },
            )
            self._log.info(
                "elastic: launching worker on %s (process %d/%d, v%d)",
                a.hostname, a.rank, a.size, version,
            )
            # Fresh liveness record per launch: a relaunched host must
            # neither inherit its predecessor's recent heartbeat (masking
            # a hung start) nor its silence (instant condemnation) — it
            # gets the never-heartbeated grace window from launch instead.
            self._server.clear_heartbeat(a.hostname)
            self._launched_at[a.hostname] = time.monotonic()
            self._workers[a.hostname] = launch_worker(
                a, self._settings.command, env,
                ssh_port=self._settings.ssh_port, sink=self._sink,
            )

    def _reconfigure(self) -> None:
        hosts = self._manager.pick_world(
            [h.hostname for h in self._world_hosts], self._max_np
        )
        if len(hosts) < self._min_np:
            hosts = self._wait_for_available_slots(
                self._min_np, self._settings.elastic_timeout
            )
        keep = {h.hostname for h in hosts}
        # Kill workers on hosts that left the world.
        leaving = [n for n in self._workers if n not in keep]
        for name in leaving:
            self._log.info("elastic: removing worker on %s", name)
            self._server.clear_heartbeat(name)
            self._launched_at.pop(name, None)
        terminate_workers([self._workers.pop(n) for n in leaving])
        version = self._publish_world(hosts)
        self._launch_missing_workers(version)

    # -- main loop -----------------------------------------------------------

    def run(self) -> int:
        _metrics.event("driver_start", generation=0,
                       min_np=self._min_np, max_np=self._max_np)
        hosts = self._wait_for_available_slots(
            self._min_np, self._settings.elastic_timeout
        )
        self._server.start()
        version = self._publish_world(hosts)
        self._launch_missing_workers(version)
        try:
            return self._monitor()
        finally:
            terminate_workers(list(self._workers.values()))
            self._server.stop()

    def _dead_by_heartbeat(self) -> list[tuple[str, str]]:
        """Hosts the liveness plane has declared dead: (host, why) pairs.

        A host is dead when its last heartbeat is older than hb_timeout,
        or — if it has NEVER heartbeated — when hb_grace has elapsed since
        its launch (interpreter startup, framework import). popen.poll()
        cannot see either case: a SIGSTOP'd process, a wedged TPU VM, or a
        livelocked trainer is still "running" to the OS.
        """
        if self._hb_timeout <= 0:
            return []
        dead: list[tuple[str, str]] = []
        now = time.monotonic()
        for name, w in self._workers.items():
            if w.popen.poll() is not None:
                continue  # exited: the reap path owns it
            age = self._server.heartbeat_age(name)
            if age is None:
                launched = self._launched_at.get(name)
                if launched is not None and now - launched >= self._hb_grace:
                    dead.append((name, (
                        f"no heartbeat within {self._hb_grace:.0f}s "
                        "grace of launch")))
            elif age >= self._hb_timeout:
                dead.append((name, (
                    f"heartbeat silent for {age:.0f}s "
                    f"(timeout {self._hb_timeout:.0f}s)")))
        return dead

    def _post_abort(self, reason: str) -> None:
        """Post the coordinated-abort record for the CURRENT generation
        (the dying world) before `_reconfigure` bumps it: survivors wedged
        in a collective with the dead peer poll the flag and convert the
        wedge into HorovodInternalError → elastic recovery, instead of
        blocking forever inside a native allreduce no one will complete."""
        gen = self._server.post_abort(reason)
        _metrics.event("abort_posted", generation=gen, reason=reason,
                       source="driver")
        self._log.warning(
            "elastic: posting coordinated abort for world generation %d "
            "(%s)", gen, reason,
        )

    def _blacklist(self, name: str, why: str) -> None:
        """Blacklist + journal + refresh the scrape gauge in one place."""
        self._manager.blacklist(name)
        self._server.set_cluster_info(
            blacklisted=self._manager.blacklist_count())
        _metrics.event("blacklist", generation=self._server.generation,
                       host=name, reason=why)

    def _monitor(self) -> int:
        last_poll = 0.0
        while True:
            # 1. Reap exited workers.
            finished = {
                n: w for n, w in self._workers.items()
                if w.popen.poll() is not None
            }
            need_reconfigure = False
            for name, w in finished.items():
                rc = w.popen.returncode
                del self._workers[name]
                self._launched_at.pop(name, None)
                self._server.clear_heartbeat(name)
                _metrics.event("worker_exit",
                               generation=self._server.generation,
                               host=name, rc=rc)
                if rc == 0:
                    # Success on any worker ⇒ the job completed (reference
                    # semantics: the training function returned).
                    self._log.info("elastic: worker on %s finished ok", name)
                    _metrics.event("job_complete",
                                   generation=self._server.generation,
                                   host=name)
                    return 0
                if rc == EXIT_REMOVED:
                    # Clean self-exit of a worker dropped from the world —
                    # not a failure, not job completion.
                    self._log.info("elastic: removed worker on %s exited", name)
                    continue
                if rc == EXIT_DRIVER_LOST:
                    # The worker gave up on an unreachable rendezvous KV.
                    # If we are here to see it, the driver process is alive
                    # — a partition or KV fault, i.e. a CONTROL-PLANE
                    # problem, not a host problem: relaunch the worker but
                    # do not poison the blacklist with a healthy host.
                    # Capped: a PERSISTENT per-host KV fault (firewalled
                    # port) must not churn the whole fleet through a
                    # reconfiguration every driver-loss deadline forever —
                    # after 3 consecutive 203s the host is blacklisted
                    # like any failure.
                    n = self._driver_lost_counts.get(name, 0) + 1
                    self._driver_lost_counts[name] = n
                    if n <= 3:
                        self._log.error(
                            "elastic: worker on %s lost the rendezvous KV "
                            "(rc=%d, %d consecutive) — control-plane "
                            "fault, not a host fault; relaunching without "
                            "blacklisting", name, rc, n,
                        )
                        self._post_abort(
                            f"worker on {name} exited EXIT_DRIVER_LOST")
                        need_reconfigure = True
                        continue
                    self._log.error(
                        "elastic: worker on %s lost the rendezvous KV %d "
                        "consecutive times — persistent; blacklisting",
                        name, n,
                    )
                    del self._driver_lost_counts[name]
                    self._post_abort(
                        f"worker on {name} lost the rendezvous KV "
                        f"{n} consecutive times; blacklisted")
                    self._blacklist(
                        name, f"{n} consecutive EXIT_DRIVER_LOST exits")
                    need_reconfigure = True
                    continue
                self._driver_lost_counts.pop(name, None)
                self._log.warning(
                    "elastic: worker on %s failed (rc=%d); blacklisting",
                    name, rc,
                )
                self._post_abort(
                    f"worker on {name} failed with rc={rc}; blacklisted")
                self._blacklist(name, f"worker failed with rc={rc}")
                need_reconfigure = True
            # 1b. Liveness plane: kill + blacklist hosts the heartbeat
            # deadline has condemned (hung, not crashed — invisible to the
            # reap above). terminate_worker escalates SIGTERM→SIGKILL, and
            # SIGKILL lands even on a SIGSTOP'd process.
            for name, why in self._dead_by_heartbeat():
                self._log.warning(
                    "elastic: worker on %s is hung (%s); killing and "
                    "blacklisting", name, why,
                )
                # Abort FIRST, kill second: survivors wedged with the hung
                # peer should already be polling the flag when the SIGKILL
                # lands, whichever unblocks them first.
                self._post_abort(f"worker on {name} is hung ({why}); killed")
                _metrics.event("worker_hung",
                               generation=self._server.generation,
                               host=name, reason=why)
                terminate_worker(self._workers.pop(name))
                self._launched_at.pop(name, None)
                self._server.clear_heartbeat(name)
                self._blacklist(name, f"hung: {why}")
                need_reconfigure = True
            if need_reconfigure:
                self._reconfigure()
                continue
            # 2. Poll discovery.
            if time.time() - last_poll >= self._poll_interval:
                last_poll = time.time()
                try:
                    changed = self._manager.update_available_hosts()
                except HostDiscoveryFailedError:
                    raise  # sustained streak: fail the job loudly
                except Exception as e:
                    self._log.warning("elastic: discovery failed: %s", e)
                    changed = False
                if changed:
                    self._log.info("elastic: host set changed; reconfiguring")
                    self._reconfigure()
            time.sleep(0.05)


def run_elastic(settings, sink=None, discovery=None) -> int:
    """Entry used by ``hvdrun --host-discovery-script ...``."""
    driver = ElasticDriver(settings, discovery=discovery, sink=sink)
    return driver.run()
