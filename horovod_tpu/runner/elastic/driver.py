"""The elastic driver: keeps min_np ≤ world ≤ max_np across host churn.

Parity with ``horovod/runner/elastic/driver.py — ElasticDriver`` +
``registration.py`` + ``worker.py``: polls host discovery, launches and
monitors workers, blacklists failing hosts, re-forms the world on change,
and notifies surviving workers.

TPU-native notification contract (replacing the reference's per-worker
``WorkerNotificationService`` TCP push): the driver publishes each world
epoch to the rendezvous KV server —

- ``GET /_version``                      → current world version (bumped on
  every reconfiguration; workers poll this cheaply)
- ``GET /world/<version>``  (key = hostname) → JSON assignment for that host:
  ``{"process_id", "num_processes", "coordinator", "slots", "hosts"}``

Workers poll the version between commits (``worker.py — ElasticWorkerLoop``);
a bump surfaces as ``HostsUpdatedInterrupt`` and the worker re-reads its
assignment for the new version. A host absent from the new epoch exits
cleanly.
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time

from ... import faults
from ... import metrics as _metrics
from ...elastic.policy import PolicyController
from ...exceptions import HostDiscoveryFailedError
from ...utils.env import get_float
from ...utils.logging import get_logger
from ..exec_utils import (
    WorkerProc,
    build_worker_env,
    drain_worker,
    launch_worker,
    terminate_worker,
    terminate_workers,
)
from ..hosts import HostInfo, ProcessAssignment, get_host_assignments
from ..http.kv_server import RendezvousServer
from ..network import coordinator_addr, driver_addr, free_port
from . import driver_state
from .discovery import FixedHostDiscovery, HostDiscoveryScript, HostManager

from .constants import (  # noqa: E402  (EXIT_REMOVED re-exported for users)
    EXIT_DRIVER_LOST,
    EXIT_DRIVER_SUPERSEDED,
    EXIT_REMOVED,
)

WORLD_SCOPE = "world"


class _AdoptedPopen:
    """Liveness-only stand-in for a worker Popen the driver did not
    spawn: a crash-restarted driver ADOPTS the predecessor's still-live
    workers by PID (they survived the crash — ``start_new_session`` —
    and rejoin at the next generation fence without a process restart).
    ``poll()`` answers via signal 0; the exit CODE of a non-child is
    unreadable, so the monitor special-cases adopted exits (completion
    comes from the worker's ``PUT /done/<host>`` record instead)."""

    def __init__(self, pid: int):
        self.pid = pid
        self.returncode: int | None = None

    def poll(self) -> int | None:
        if self.returncode is not None:
            return self.returncode
        try:
            os.kill(self.pid, 0)
        except ProcessLookupError:
            self.returncode = 0  # sentinel; the monitor checks the type
            return self.returncode
        except PermissionError:
            pass  # alive, different uid (shouldn't happen; treat alive)
        return None


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        pass
    return True


class ElasticDriver:
    def __init__(
        self,
        settings,  # runner.launch.Settings
        discovery=None,
        sink=None,
        poll_interval: float = 1.0,
    ):
        self._settings = settings
        self._log = get_logger()
        self._sink = sink
        self._poll_interval = poll_interval
        if discovery is None:
            if settings.discovery_script:
                discovery = HostDiscoveryScript(settings.discovery_script)
            else:
                discovery = FixedHostDiscovery(settings.hosts)
        self._manager = HostManager(discovery)
        # Durable control-plane state (crash-restart takeover) is opened
        # FIRST: the predecessor's snapshot carries the job's HMAC
        # secret, which must be resumed before the server snapshots its
        # key below — a takeover driver minting a fresh secret would 403
        # every orphaned worker's rejoin forever. Entirely inert with
        # HOROVOD_DRIVER_STATE_DIR unset: no store, epoch 0, no snapshot
        # writes, no endpoint record — bit-for-bit the
        # driver-loss-is-fatal (203) behavior.
        from .. import secret as _secret

        self._store: driver_state.DriverStateStore | None = None
        self._snapshot: dict | None = None
        sdir = driver_state.state_dir()
        if sdir is not None:
            self._store, self._snapshot = driver_state.DriverStateStore.open(
                sdir)
            if self._snapshot is not None:
                prev_secret = self._snapshot.get("secret_key")
                if prev_secret:
                    os.environ[_secret.ENV_KEY] = str(prev_secret)
        # Secret before server construction: the server snapshots its HMAC
        # key at __init__ (a later setdefault would leave it open-mode).
        os.environ.setdefault(_secret.ENV_KEY, _secret.make_secret_key())
        self._server = RendezvousServer()
        self._workers: dict[str, WorkerProc] = {}
        self._launched_at: dict[str, float] = {}  # host -> monotonic launch
        self._driver_lost_counts: dict[str, int] = {}  # consecutive rc=203
        self._world_hosts: list[HostInfo] = []
        self._coord_port: int = 0
        self._native_port: int = 0
        self._shutdown = False
        self._min_np = settings.min_np or 1
        self._max_np = settings.max_np
        # Liveness plane: a host silent for hb_timeout seconds is declared
        # dead (hung, not crashed — popen.poll() cannot see it) and is
        # killed/blacklisted like a failure. 0 disables enforcement (a
        # worker that never heartbeats — plain scripts — stays safe by
        # default). A host that has NEVER heartbeated gets hb_grace from
        # its launch instead, covering interpreter/framework startup.
        self._hb_timeout = get_float("HOROVOD_ELASTIC_HEARTBEAT_TIMEOUT", 0.0)
        self._hb_grace = get_float(
            "HOROVOD_ELASTIC_HEARTBEAT_GRACE",
            max(10.0 * self._hb_timeout, 60.0),
        )
        # Self-healing policy plane (ROADMAP item 3): the controller that
        # turns the straggler/goodput sensors into proactive drains. Inert
        # unless HOROVOD_TARGET_GOODPUT is set; the warm-spare tier is
        # governed independently by HOROVOD_WARM_SPARES (via HostManager).
        self._policy = PolicyController(min_np=self._min_np)
        self._spare_procs: dict[str, WorkerProc] = {}
        self._rate_state: dict[str, tuple[float, float]] = {}
        self._last_policy_tick = 0.0
        # Integrity defense plane (horovod_tpu/integrity.py): the driver
        # is the voter — armed by HOROVOD_INTEGRITY_INTERVAL in the
        # shared env, independent of the goodput-policy SLO knob
        # (corruption is a correctness problem, not a throughput one).
        self._integrity_strikes: dict[str, int] = {}
        self._last_integrity_tick = 0.0
        self._last_integrity_hb_version = -1
        self._integrity_acted_group: tuple[int, int] = (-1, -1)
        # (rank, condemned digest) of the last NAMED divergent vote —
        # the 2-voter continuity resolution's memory.
        self._last_outlier: tuple[int, str] | None = None
        self._draining = False
        self._superseded = False
        self._last_state_save = 0.0
        self._state_refresh_s = get_float("HOROVOD_DRIVER_STATE_REFRESH",
                                          10.0)

    # -- durable control-plane state ------------------------------------------

    @property
    def driver_epoch(self) -> int:
        return self._store.epoch if self._store is not None else 0

    def _proc_record(self, w: WorkerProc) -> dict:
        return {
            "pid": int(w.popen.pid),
            "local": w.remote_host is None,
            "slots": int(getattr(w.assignment, "slots", 1) or 1),
            # PID-reuse guard: adoption re-checks the kernel start time,
            # so a recycled PID can never get an unrelated process
            # adopted (and later SIGKILLed) as a worker.
            "start_ticks": driver_state.proc_start_ticks(
                int(w.popen.pid)),
        }

    def _snapshot_record(self) -> dict:
        """The driver's authoritative state, as one JSON-able record:
        what a successor needs to re-form the world at g+1 without
        losing membership, fencing, blacklist cooldowns, policy
        evidence, or the live workers themselves (adopted by PID)."""
        return {
            "generation": self._server.generation,
            "min_np": self._min_np,
            "max_np": self._max_np,
            "world": [[h.hostname, h.slots] for h in self._world_hosts],
            "workers": {n: self._proc_record(w)
                        for n, w in self._workers.items()},
            "spares": {n: self._proc_record(w)
                       for n, w in self._spare_procs.items()},
            "blacklist": self._manager.export_blacklist(),
            "driver_lost_counts": dict(self._driver_lost_counts),
            "integrity_strikes": dict(self._integrity_strikes),
            # The last-voted group rides along too: workers keep staging
            # the same fingerprint on every heartbeat, so a takeover
            # driver re-voting the identical (generation, step) group
            # would double-count the strike — one real divergence event
            # must cost exactly one confirmation.
            "integrity_acted_group": list(self._integrity_acted_group),
            "integrity_last_outlier": (list(self._last_outlier)
                                       if self._last_outlier else None),
            # The KV quarantine rides along: the acted-group watermark
            # above stops the takeover driver from RE-voting the group,
            # so without this a condemned rank's proven-corrupt replicas
            # would be assembly-eligible on the successor's fresh server
            # — permanently, if the corrupt host died with the old
            # driver and never fingerprints again.
            "integrity_quarantine": self._server.quarantine_export(),
            "policy": self._policy.export_state(),
            # The job HMAC secret: the takeover driver must serve (and
            # sign) with the SAME key the orphaned workers hold, or
            # their rejoin probes 403 forever. The state dir is 0700.
            "secret_key": os.environ.get("HOROVOD_SECRET_KEY", ""),
        }

    def _save_state(self) -> None:
        """Journal the control-plane snapshot (every mutating path calls
        this; the monitor additionally refreshes it every
        HOROVOD_DRIVER_STATE_REFRESH seconds to capture PID/EWMA drift).
        A fencing rejection means a SUCCESSOR owns the state — this
        driver stands down instead of corrupting it; any other failure
        is logged and survived (a storage blip must not kill the job the
        snapshot exists to protect)."""
        if self._store is None or self._superseded:
            return
        try:
            self._store.save(self._snapshot_record())
            self._last_state_save = time.monotonic()
        except driver_state.DriverFencedError as e:
            self._log.error("elastic: %s", e)
            self._superseded = True
        except Exception as e:  # noqa: BLE001 — snapshot is best-effort
            self._log.warning(
                "elastic: control-plane snapshot failed (%s); takeover "
                "would resume from the previous snapshot", e)

    def _publish_endpoint(self) -> None:
        """Refresh the shared-storage discovery record orphaned workers
        re-resolve the rendezvous endpoint from (fenced like the
        snapshot)."""
        if self._store is None or self._superseded:
            return
        addr = driver_addr([h.hostname for h in self._world_hosts]
                           or ["localhost"])
        try:
            self._store.publish_endpoint(addr, self._server.port,
                                         self._server.generation)
        except driver_state.DriverFencedError as e:
            self._log.error("elastic: %s", e)
            self._superseded = True
        except Exception as e:  # noqa: BLE001
            self._log.warning(
                "elastic: endpoint record publish failed (%s); orphaned "
                "workers cannot rejoin until it lands", e)

    def _state_env(self) -> dict[str, str]:
        """Worker-env additions for the durable-control-plane contract:
        the state dir (so orphans can re-resolve the endpoint record)
        and the serving driver epoch (the split-brain fence identity
        workers stamp their writes with). Empty when the plane is off —
        the worker env stays bit-for-bit the HEAD contract."""
        if self._store is None:
            return {}
        return {
            driver_state.ENV_STATE_DIR: self._store.directory,
            driver_state.ENV_DRIVER_EPOCH: str(self._store.epoch),
        }

    def _adopt_from_snapshot(self, snap: dict) -> list[str]:
        """Adopt the predecessor's still-live LOCAL workers and spares
        by PID: they keep training through the takeover and rejoin at
        the g+1 fence without a process restart. Remote (ssh-launched)
        workers cannot be adopted — their local ssh client died with the
        predecessor — and are relaunched cold by the normal path."""
        adopted: list[str] = []
        for table, target in (("workers", self._workers),
                              ("spares", self._spare_procs)):
            for host, info in (snap.get(table) or {}).items():
                if not isinstance(info, dict) or not info.get("local"):
                    continue
                try:
                    pid = int(info.get("pid"))
                except (TypeError, ValueError):
                    continue
                if not _pid_alive(pid):
                    continue
                recorded = info.get("start_ticks")
                if recorded is not None:
                    ticks = driver_state.proc_start_ticks(pid)
                    if ticks is not None and ticks != recorded:
                        self._log.warning(
                            "elastic: pid %d on %s was recycled (start "
                            "ticks %s != recorded %s); not adopting",
                            pid, host, ticks, recorded)
                        continue
                assignment = ProcessAssignment(
                    hostname=host, rank=0, size=1, local_rank=0,
                    local_size=1, cross_rank=0, cross_size=1,
                    slots=int(info.get("slots", 1) or 1),
                    first_device_rank=0)
                target[host] = WorkerProc(assignment, _AdoptedPopen(pid),
                                          None)
                self._launched_at[host] = time.monotonic()
                adopted.append(host)
                self._log.info(
                    "elastic: adopted orphaned %s on %s (pid %d)",
                    "worker" if table == "workers" else "spare", host,
                    pid)
        return adopted

    # -- world formation -----------------------------------------------------

    def _wait_for_available_slots(self, min_np: int, timeout: float) -> list[HostInfo]:
        """Block until discovery yields ≥ min_np usable hosts (parity:
        ``ElasticDriver.wait_for_available_slots``)."""
        deadline = time.time() + timeout
        while True:
            try:
                self._manager.update_available_hosts()
            except HostDiscoveryFailedError:
                raise  # sustained streak: the driver is blind — fail loudly
            except Exception as e:  # discovery script hiccup: retry
                self._log.warning("elastic: discovery failed (%s); retrying", e)
            hosts = self._manager.pick_world(
                [h.hostname for h in self._world_hosts], self._max_np
            )
            if len(hosts) >= min_np:
                return hosts
            if time.time() >= deadline:
                raise TimeoutError(
                    f"elastic: {len(hosts)} host(s) available after "
                    f"{timeout:.0f}s; need min_np={min_np}"
                )
            time.sleep(self._poll_interval)

    def _publish_world(self, hosts: list[HostInfo]) -> int:
        """Publish the new epoch's assignments, then bump the version (the
        scope is written before the bump so in-flight workers of the
        previous epoch never read a hole)."""
        assignments = get_host_assignments(hosts)
        coord = coordinator_addr([h.hostname for h in hosts])
        self._coord_port = free_port()
        self._native_port = free_port()
        data = {
            a.hostname: json.dumps(
                {
                    "process_id": a.rank,
                    "num_processes": a.size,
                    "coordinator": f"{coord}:{self._coord_port}",
                    "native_port": self._native_port,
                    "slots": a.slots,
                    "hosts": [[h.hostname, h.slots] for h in hosts],
                }
            ).encode()
            for a in assignments
        }
        version = self._server.publish_epoch(WORLD_SCOPE, data)
        self._world_hosts = hosts
        # Scrape gauges + lifecycle journal: one record per world epoch,
        # stamped with the generation the epoch IS.
        self._server.set_cluster_info(
            world_np=len(hosts),
            blacklisted=self._manager.blacklist_count())
        _metrics.event(
            "world_published", generation=version, np=len(hosts),
            hosts=[h.hostname for h in hosts],
            driver_epoch=self.driver_epoch)
        # Durable control plane: every world publish refreshes the
        # endpoint discovery record (orphan rejoin target) and the
        # snapshot (membership + generation are the takeover's core).
        self._publish_endpoint()
        self._save_state()
        return version

    def _launch_missing_workers(self, version: int) -> None:
        assignments = get_host_assignments(self._world_hosts)
        kv_addr = driver_addr([a.hostname for a in assignments])
        coord_addr = coordinator_addr([a.hostname for a in assignments])
        for a in assignments:
            w = self._workers.get(a.hostname)
            if w is not None and w.popen.poll() is None:
                continue  # alive: keep it
            if w is not None:
                if w.popen.returncode == 0:
                    # Completed job racing a reconfiguration: leave the
                    # corpse for the monitor, which surfaces rc=0 as job
                    # completion — relaunching would silently restart a
                    # finished job.
                    continue
                # Failed/removed but not yet reaped (a whole GENERATION
                # crashing lands here: the first reap triggers
                # reconfiguration while peers' corpses still occupy the
                # table) — sweep it so the host gets its new-generation
                # worker now instead of after another monitor round. A
                # re-crash gets reaped (and blacklisted) by the monitor
                # normally.
                del self._workers[a.hostname]
            sp = self._spare_procs.pop(a.hostname, None)
            if sp is not None and sp.popen.poll() is None:
                # Warm-spare promotion: the host already runs a launched,
                # heartbeating, framework-imported worker parked on the
                # assignment wait — move it into the world instead of
                # cold-launching. Its poll loop sees this version bump and
                # fetches the assignment; the join costs one
                # re-rendezvous. Heartbeat record deliberately kept (it
                # is live — clearing it would reset liveness to the
                # never-heartbeated grace).
                try:
                    if faults.fire(faults.SPARE_PROMOTE):
                        raise faults.InjectedFault(
                            "spare promotion dropped")
                    self._workers[a.hostname] = sp
                    self._server.clear_spare(a.hostname)
                    self._server.record_policy_action("promote")
                    _metrics.POLICY_DECISIONS.inc(action="promote")
                    _metrics.event("spare_promoted", generation=version,
                                   host=a.hostname, rank=a.rank)
                    self._log.info(
                        "elastic: promoting warm spare on %s into the "
                        "world (rank %d/%d, v%d)",
                        a.hostname, a.rank, a.size, version,
                    )
                    continue
                except Exception as e:  # noqa: BLE001 — chaos/injection
                    self._log.warning(
                        "elastic: spare promotion on %s failed (%s); "
                        "falling back to a cold launch", a.hostname, e,
                    )
                    terminate_worker(sp)
                    self._server.clear_spare(a.hostname)
            env = build_worker_env(
                a,
                base_env=dict(os.environ),
                rendezvous_addr=kv_addr,
                rendezvous_port=self._server.port,
                coordinator_addr=coord_addr,
                coordinator_port=self._coord_port,
                native_port=self._native_port,
                cpu_mode=self._settings.cpu_mode,
                extra_env={
                    **self._settings.env,
                    "HOROVOD_ELASTIC": "1",
                    "HOROVOD_WORLD_VERSION": str(version),
                    "HOROVOD_HOSTNAME": a.hostname,
                    **self._state_env(),
                },
            )
            self._log.info(
                "elastic: launching worker on %s (process %d/%d, v%d)",
                a.hostname, a.rank, a.size, version,
            )
            # Fresh liveness record per launch: a relaunched host must
            # neither inherit its predecessor's recent heartbeat (masking
            # a hung start) nor its silence (instant condemnation) — it
            # gets the never-heartbeated grace window from launch instead.
            self._server.clear_heartbeat(a.hostname)
            self._launched_at[a.hostname] = time.monotonic()
            self._workers[a.hostname] = launch_worker(
                a, self._settings.command, env,
                ssh_port=self._settings.ssh_port, sink=self._sink,
            )
        # Fresh PIDs land in the durable snapshot immediately — a driver
        # crash right after a launch wave must still let the successor
        # adopt the new workers instead of double-launching their hosts.
        self._save_state()

    def _reconfigure(self) -> None:
        t0 = time.monotonic()
        hosts = self._manager.pick_world(
            [h.hostname for h in self._world_hosts], self._max_np
        )
        if len(hosts) < self._min_np:
            hosts = self._wait_for_available_slots(
                self._min_np, self._settings.elastic_timeout
            )
        if (self._manager.warm_spares_target > 0
                and [(h.hostname, h.slots) for h in hosts]
                == [(h.hostname, h.slots) for h in self._world_hosts]
                and all(h.hostname in self._workers
                        and self._workers[h.hostname].popen.poll() is None
                        for h in hosts)):
            # Spare-tier-only change (a cooldown-returned host routed to
            # standby, a surplus host discovered): the WORLD is unchanged
            # AND every world host still runs a live worker — a host
            # reaped without blacklisting (EXIT_DRIVER_LOST) keeps its
            # world slot and MUST fall through to the relaunch below.
            # Publishing a new epoch here would only churn every worker
            # through a re-sync; refresh the spare fleet instead.
            self._ensure_spares(self._server.version)
            return
        keep = {h.hostname for h in hosts}
        # Kill workers on hosts that left the world.
        leaving = [n for n in self._workers if n not in keep]
        for name in leaving:
            self._log.info("elastic: removing worker on %s", name)
            self._server.clear_heartbeat(name)
            self._launched_at.pop(name, None)
        terminate_workers([self._workers.pop(n) for n in leaving])
        version = self._publish_world(hosts)
        self._launch_missing_workers(version)
        self._ensure_spares(version)
        # The SLO gate weighs a voluntary drain against the MEASURED
        # price of a re-rendezvous, not an assumed one.
        self._policy.note_resize_cost(time.monotonic() - t0)

    # -- main loop -----------------------------------------------------------

    def _prepare_takeover(self) -> bool:
        """Resume the predecessor's control-plane state (fires the
        ``driver.takeover`` fault point): seed the fresh KV server with
        the snapshot's generation (so the takeover world publishes at
        g+1 and the generation fence stays monotonic across the crash)
        and this driver's bumped epoch (arming the split-brain fence),
        then restore the blacklist cooldowns, policy evidence, and
        driver-lost counters. Returns True when a snapshot was resumed."""
        snap = self._snapshot
        if self._store is None or snap is None:
            if self._store is not None:
                self._server.seed(driver_epoch=self._store.epoch)
                _metrics.DRIVER_EPOCH.set(self._store.epoch)
            return False
        if faults.fire(faults.DRIVER_TAKEOVER):
            raise faults.InjectedFault(
                "driver takeover dropped (injected)")
        try:
            generation = int(snap.get("generation", 0))
        except (TypeError, ValueError):
            generation = 0
        self._server.seed(generation=generation,
                          driver_epoch=self._store.epoch)
        _metrics.DRIVER_EPOCH.set(self._store.epoch)
        self._manager.restore_blacklist(snap.get("blacklist"))
        self._policy.restore_state(snap.get("policy"))
        acted = snap.get("integrity_acted_group")
        if (isinstance(acted, (list, tuple)) and len(acted) == 2):
            try:
                self._integrity_acted_group = (int(acted[0]), int(acted[1]))
            except (TypeError, ValueError):
                pass
        outlier = snap.get("integrity_last_outlier")
        if (isinstance(outlier, (list, tuple)) and len(outlier) == 2):
            try:
                self._last_outlier = (int(outlier[0]), str(outlier[1]))
            except (TypeError, ValueError):
                pass
        self._server.restore_quarantine(snap.get("integrity_quarantine"))
        strikes = snap.get("integrity_strikes")
        if isinstance(strikes, dict):
            # A persistently-corrupting host must not get a clean record
            # just because the control plane flapped.
            for host, n in strikes.items():
                try:
                    self._integrity_strikes[str(host)] = int(n)
                except (TypeError, ValueError):
                    continue
        counts = snap.get("driver_lost_counts")
        if isinstance(counts, dict):
            for host, n in counts.items():
                try:
                    self._driver_lost_counts[str(host)] = int(n)
                except (TypeError, ValueError):
                    continue
            # The scrape counter resumes too: the cap continuing from
            # restored counts while hvd_driver_lost_total read 0 would
            # hide exactly the flap trail the metric exists to show.
            self._server.seed_driver_lost(self._driver_lost_counts)
        # Prefer the snapshot's membership for rank stability: pick_world
        # keeps `preferred` (the previous world) first.
        world = []
        for entry in snap.get("world") or []:
            try:
                world.append(HostInfo(str(entry[0]), int(entry[1])))
            except (TypeError, ValueError, IndexError):
                continue
        self._world_hosts = world
        _metrics.DRIVER_TAKEOVERS.inc()
        self._log.warning(
            "elastic: taking over from driver epoch %d at generation %d "
            "(world %s, %d blacklisted)", self._store.epoch - 1,
            generation, [h.hostname for h in world],
            self._manager.blacklist_count())
        return True

    def run(self) -> int:
        takeover = self._prepare_takeover()
        job = os.environ.get("HOROVOD_JOB_ID")
        if job:
            # Multi-tenant pod: this driver serves ONE job of a shared
            # pool (the gang scheduler launched it with a per-job
            # discovery lease, state dir, and journal); every journal
            # record it emits is stamped job=<id> by the env contract.
            self._log.warning(
                "elastic: driver serving job %r of a multi-tenant pool",
                job)
        _metrics.event("driver_start",
                       generation=self._server.generation,
                       min_np=self._min_np, max_np=self._max_np,
                       driver_epoch=self.driver_epoch, takeover=takeover)
        hosts = self._wait_for_available_slots(
            self._min_np, self._settings.elastic_timeout
        )
        self._server.start()
        adopted: list[str] = []
        if takeover:
            # Adopt BEFORE the first snapshot save: the save below
            # persists THIS driver's worker table, and an empty one
            # would clobber the predecessor's PID record — a crash in
            # the takeover window would then leave the next successor
            # nothing to adopt (double-launched hosts).
            adopted = self._adopt_from_snapshot(self._snapshot or {})
        # Persist the bumped epoch before anything else mutates: from
        # this instant a resurrected predecessor's snapshot/endpoint
        # writes raise DriverFencedError and it stands down. (The epoch
        # itself was already claimed O_EXCL at store open.)
        self._save_state()
        if takeover:
            _metrics.event(
                "driver_takeover", generation=self._server.generation,
                driver_epoch=self.driver_epoch, adopted=adopted,
                world=[h.hostname for h in self._world_hosts])
            # The old world's liveness is unknowable (a worker may be
            # wedged in a collective with a peer that died alongside the
            # driver): post the coordinated abort for the restored
            # generation so every survivor — wedged or training — enters
            # the recovery ladder and re-rendezvouses at g+1. With the
            # peer replica plane armed this lands on the peer rung: zero
            # durable reads, and each rank re-publishes its replica to
            # this server on its next commit.
            self._post_abort(
                f"driver takeover (epoch {self.driver_epoch}): "
                f"re-forming the world at generation "
                f"{self._server.generation + 1}")
        version = self._publish_world(hosts)
        self._launch_missing_workers(version)
        self._ensure_spares(version)
        prev_sigterm = self._install_sigterm_forwarder()
        try:
            return self._monitor()
        finally:
            if self._superseded:
                # A successor owns the world AND the workers (it adopted
                # them); terminating "our" processes would kill ITS
                # world. Stand down touching nothing.
                self._log.warning(
                    "elastic: superseded driver standing down without "
                    "touching %d worker(s)", len(self._workers))
            else:
                terminate_workers(list(self._workers.values())
                                  + list(self._spare_procs.values()))
            try:
                # A decision whose realization window the job outlived
                # still gets its policy_decision record (partial window).
                self._policy.flush()
            except Exception:  # noqa: BLE001 — shutdown must finish
                pass
            if prev_sigterm is not None:
                try:
                    signal.signal(signal.SIGTERM, prev_sigterm)
                except (ValueError, OSError):
                    pass
            self._server.stop()

    def _dead_by_heartbeat(
            self, procs: dict[str, WorkerProc] | None = None,
    ) -> list[tuple[str, str]]:
        """Hosts the liveness plane has declared dead: (host, why) pairs.

        A host is dead when its last heartbeat is older than hb_timeout,
        or — if it has NEVER heartbeated — when hb_grace has elapsed since
        its launch (interpreter startup, framework import). popen.poll()
        cannot see either case: a SIGSTOP'd process, a wedged TPU VM, or a
        livelocked trainer is still "running" to the OS. ``procs``
        defaults to the world workers; the spare fleet is checked with the
        same rule (a hung spare is a replacement that would not replace).
        """
        if self._hb_timeout <= 0:
            return []
        if procs is None:
            procs = self._workers
        dead: list[tuple[str, str]] = []
        now = time.monotonic()
        for name, w in procs.items():
            if w.popen.poll() is not None:
                continue  # exited: the reap path owns it
            age = self._server.heartbeat_age(name)
            if age is None:
                launched = self._launched_at.get(name)
                if launched is not None and now - launched >= self._hb_grace:
                    dead.append((name, (
                        f"no heartbeat within {self._hb_grace:.0f}s "
                        "grace of launch")))
            elif age >= self._hb_timeout:
                dead.append((name, (
                    f"heartbeat silent for {age:.0f}s "
                    f"(timeout {self._hb_timeout:.0f}s)")))
        return dead

    def _post_abort(self, reason: str) -> None:
        """Post the coordinated-abort record for the CURRENT generation
        (the dying world) before `_reconfigure` bumps it: survivors wedged
        in a collective with the dead peer poll the flag and convert the
        wedge into HorovodInternalError → elastic recovery, instead of
        blocking forever inside a native allreduce no one will complete."""
        gen = self._server.post_abort(reason)
        _metrics.event("abort_posted", generation=gen, reason=reason,
                       source="driver")
        self._log.warning(
            "elastic: posting coordinated abort for world generation %d "
            "(%s)", gen, reason,
        )

    def _blacklist(self, name: str, why: str) -> None:
        """Blacklist + journal + refresh the scrape gauge in one place."""
        self._manager.blacklist(name)
        self._server.set_cluster_info(
            blacklisted=self._manager.blacklist_count())
        _metrics.event("blacklist", generation=self._server.generation,
                       host=name, reason=why)
        self._save_state()

    # -- warm spares ---------------------------------------------------------

    def _launch_spare(self, host: HostInfo, version: int) -> None:
        """Launch a WARM SPARE worker on ``host``: same command, same env
        contract, plus ``HOROVOD_SPARE=1`` — the worker imports its
        frameworks, heartbeats, registers at ``PUT /spare/<host>``, and
        parks on the assignment wait until a world includes it."""
        assignment = ProcessAssignment(
            hostname=host.hostname, rank=0, size=1, local_rank=0,
            local_size=1, cross_rank=0, cross_size=1, slots=host.slots,
            first_device_rank=0)
        world_names = [h.hostname for h in self._world_hosts]
        env = build_worker_env(
            assignment,
            base_env=dict(os.environ),
            rendezvous_addr=driver_addr(world_names + [host.hostname]),
            rendezvous_port=self._server.port,
            coordinator_addr=coordinator_addr(world_names or
                                              [host.hostname]),
            coordinator_port=self._coord_port,
            native_port=self._native_port,
            cpu_mode=self._settings.cpu_mode,
            extra_env={
                **self._settings.env,
                "HOROVOD_ELASTIC": "1",
                "HOROVOD_SPARE": "1",
                "HOROVOD_WORLD_VERSION": str(version),
                "HOROVOD_HOSTNAME": host.hostname,
                **self._state_env(),
            },
        )
        self._log.info("elastic: launching warm spare on %s (v%d)",
                       host.hostname, version)
        self._server.clear_heartbeat(host.hostname)
        self._launched_at[host.hostname] = time.monotonic()
        self._spare_procs[host.hostname] = launch_worker(
            assignment, self._settings.command, env,
            ssh_port=self._settings.ssh_port, sink=self._sink,
        )
        _metrics.event("spare_launched", generation=version,
                       host=host.hostname)

    def _retire_spare(self, name: str, why: str, version: int) -> None:
        w = self._spare_procs.pop(name, None)
        if w is None:
            return
        self._log.info("elastic: retiring spare on %s (%s)", name, why)
        terminate_worker(w)
        self._launched_at.pop(name, None)
        self._server.clear_heartbeat(name)
        self._server.clear_spare(name)
        _metrics.event("spare_retired", generation=version, host=name,
                       reason=why)

    def _ensure_spares(self, version: int) -> None:
        """Reconcile the spare fleet with the HostManager's spare tier:
        reap exits, kill hung spares (same liveness rule as the world —
        but no abort, no reconfigure: spares are not in anyone's
        collectives), retire tier-leavers, launch tier-joiners."""
        if self._manager.warm_spares_target <= 0 and not self._spare_procs:
            return
        for name in [n for n, w in self._spare_procs.items()
                     if w.popen.poll() is not None]:
            w = self._spare_procs.pop(name)
            self._launched_at.pop(name, None)
            self._server.clear_heartbeat(name)
            self._server.clear_spare(name)
            _metrics.event("spare_exit", generation=version, host=name,
                           rc=w.popen.returncode)
            self._log.warning(
                "elastic: spare on %s exited rc=%d; the tier will "
                "relaunch it while the host stays discovered",
                name, w.popen.returncode)
        for name, why in self._dead_by_heartbeat(self._spare_procs):
            self._log.warning(
                "elastic: spare on %s is hung (%s); killing", name, why)
            _metrics.event("spare_hung", generation=version, host=name,
                           reason=why)
            self._retire_spare(name, f"hung: {why}", version)
        tier = {h.hostname: h for h in self._manager.spare_hosts()}
        for name in [n for n in self._spare_procs if n not in tier]:
            self._retire_spare(name, "left the spare tier", version)
        for name, h in tier.items():
            if name not in self._spare_procs and name not in self._workers:
                self._launch_spare(h, version)
        self._server.set_cluster_info(spares=len(self._spare_procs))
        _metrics.POLICY_SPARES.set(len(self._spare_procs))

    def _warm_spare_count(self) -> int:
        """Spares that are launched, registered (framework-imported), and
        fresh on the liveness plane — the replacements a drain may count
        on joining at the next generation fence."""
        registered = self._server.spare_records()
        warm = 0
        for name, w in self._spare_procs.items():
            if w.popen.poll() is not None or name not in registered:
                continue
            age = self._server.heartbeat_age(name)
            if age is None:
                continue
            if self._hb_timeout > 0 and age >= self._hb_timeout:
                continue
            warm += 1
        return warm

    # -- proactive drain (policy + preemption notices) ------------------------

    def _drain_host(self, name: str, why: str, decision=None,
                    action: str = "drain",
                    abort_posted: bool = False) -> None:
        """Proactively drain one world host through the existing
        SIGTERM→final-commit path, then re-form the world without it.

        SIGTERM first: the worker's drain handler finishes its current
        step, lands a final commit at the STILL-CURRENT generation (the
        fence would 409 it after the bump), and exits ``EXIT_REMOVED``.
        Only after the exit (or the drain grace) does the driver post the
        coordinated abort — unwedging survivors blocked with the departed
        peer — blacklist the host, and reconfigure; a warm spare then
        joins at the new generation fence."""
        w = self._workers.get(name)
        if w is None:
            return
        gen = self._server.generation
        # Post-hoc "why did you replace that host": the driver-side
        # flight record carries the host's last shipped trace window and
        # the evidence that condemned it.
        payload = self._server.trace_payload(name) or {}
        _metrics.FLIGHT_DUMPS.inc(reason="policy_drain")
        _metrics.event(
            "flight_record", generation=gen, reason="policy_drain",
            host=name,
            steps=(payload.get("steps") or [])[-2:],
            clock_offset_s=payload.get("clock_offset_s"),
            evidence=(decision.evidence if decision is not None else None))
        self._log.warning(
            "elastic: proactively draining worker on %s (%s)", name, why)
        # Remote-aware TERM delivery: a raw local killpg cannot reach an
        # ssh-launched worker's remote tree (pty teardown is SIGHUP, not
        # SIGTERM — the drain handler would never run).
        drain_worker(w)
        grace = get_float("HOROVOD_POLICY_DRAIN_GRACE", 20.0)
        deadline = time.monotonic() + grace
        while time.monotonic() < deadline and w.popen.poll() is None:
            time.sleep(0.05)
        rc = w.popen.poll()
        if rc is None:
            self._log.warning(
                "elastic: drained worker on %s still alive after %.0fs "
                "grace; escalating to SIGKILL", name, grace)
        _metrics.event("policy_drain", generation=gen, host=name,
                       action=action, reason=why, rc=rc)
        if not abort_posted:
            self._post_abort(f"proactive drain of {name} ({why})")
        terminate_worker(self._workers.pop(name))
        self._launched_at.pop(name, None)
        self._server.clear_heartbeat(name)
        self._blacklist(name, f"{action}: {why}")
        self._server.record_policy_action(action)
        if decision is not None:
            # record_drain counts the action into POLICY_DECISIONS.
            self._policy.record_drain(decision, generation=gen)
        else:
            _metrics.POLICY_DECISIONS.inc(action=action)
        self._reconfigure()

    def _handle_preempt_notices(self, version: int) -> None:
        """External preemption notices (``PUT /preempt/<host>``) become
        drain signals end to end: the DRIVER forwards the SIGTERM to that
        host's worker — the notice works even when the cloud cannot
        signal the worker process directly. Consumed once handled."""
        for name in self._server.preempt_notices():
            self._server.consume_preempt(name)
            _metrics.event("preempt_notice", generation=version, host=name)
            if name in self._workers:
                self._log.warning(
                    "elastic: preemption notice for %s — draining via "
                    "SIGTERM forward", name)
                self._drain_host(name, "external preemption notice",
                                 action="preempt")
            elif name in self._spare_procs:
                self._retire_spare(name, "external preemption notice",
                                   version)
                self._blacklist(name, "external preemption notice")
            else:
                # Not running anything of ours, but about to vanish:
                # keep pick_world from choosing it (cooldown re-admits).
                self._blacklist(name, "external preemption notice")

    def _install_sigterm_forwarder(self):
        """Driver-level preemption: SIGTERM on the DRIVER forwards the
        drain to every worker and spare per host, so a launcher-level
        notice drains the whole job through final commits instead of
        dying with uncommitted epochs. Returns the previous handler (to
        restore on exit) or None when not installable (non-main thread,
        exotic hosts)."""
        if threading.current_thread() is not threading.main_thread():
            return None

        def _on_sigterm(signum, frame):
            if self._draining:
                return
            self._draining = True
            _metrics.event("driver_drain",
                           generation=self._server.generation)
            self._log.warning(
                "elastic: driver received SIGTERM (preemption notice) — "
                "forwarding the drain to %d worker(s) and %d spare(s)",
                len(self._workers), len(self._spare_procs))
            for w in (list(self._workers.values())
                      + list(self._spare_procs.values())):
                drain_worker(w)

        try:
            return signal.signal(signal.SIGTERM, _on_sigterm)
        except (ValueError, OSError):
            return None

    # -- integrity tick (the cross-rank vote) ---------------------------------

    def _integrity_tick(self) -> None:
        """One voting pass over the fingerprints piggybacked on the
        worker heartbeats (armed by ``HOROVOD_INTEGRITY_INTERVAL`` —
        independent of the goodput policy: corruption is correctness).
        The newest COMPLETE (generation, step) group is voted once; a
        named outlier is journaled, counted, its replica PUTs fenced on
        the KV (the corrupt record evicted, ``.prev`` retained), its
        strike fed to the policy controller, and — under
        ``HOROVOD_INTEGRITY_ACTION=drain`` — its host drained through
        the existing actuators with the coordinated abort posted FIRST
        (survivors must stop rotating replica slots before the drain
        grace lets them advance past the last good group)."""
        from ... import integrity

        if not integrity.enabled():
            return
        now = time.monotonic()
        if now - self._last_integrity_tick < 0.25:
            return
        self._last_integrity_tick = now
        # Idle ticks are one integer compare: the heartbeat store's
        # mutation counter gates the JSON parse of every rank's
        # (metrics/comms-fattened) heartbeat body.
        hbv = self._server.heartbeat_version()
        if hbv == self._last_integrity_hb_version:
            return
        self._last_integrity_hb_version = hbv
        if not self._world_hosts:
            return
        # (records, vote) through the server's hb_version-keyed cache —
        # shared with the live-vote fence and GET /integrity, so one
        # heartbeat mutation costs one parse+vote process-wide. The
        # cache votes with the server's world_np, which the driver set
        # to len(world hosts) at publish (one worker per host).
        records, voted = self._server.integrity_vote_cached()
        if not records or voted is None:
            return
        group, verdict = voted
        if group <= self._integrity_acted_group:
            return
        self._integrity_acted_group = group
        if not verdict.get("divergent"):
            # A clean complete vote resets the strike counters:
            # HOROVOD_INTEGRITY_CONFIRMATIONS means CONSECUTIVE
            # divergent votes (the knob exists to tolerate transient
            # wire corruption), so two unrelated one-off events with
            # clean votes between them must not accumulate into a
            # drain. The policy channel's strikes (note_integrity) stay
            # cumulative by design — that knob is membership-lifetime.
            if self._integrity_strikes or self._last_outlier is not None:
                self._integrity_strikes.clear()
                self._last_outlier = None
                self._save_state()
            return
        gen = self._server.generation
        if (verdict.get("ambiguous") and verdict.get("voters") == 2
                and self._last_outlier is not None):
            # Continuity resolution: with 2 voters a PERSISTENT
            # corruption makes every vote after the first ambiguous
            # (the outlier's prev digest — its own condemned record —
            # disagrees with the peer's), so confirmations >= 2 could
            # never accumulate. But if the previously named rank's prev
            # IS the exact digest the last vote condemned, the
            # ambiguity is that same corruption persisting across
            # intervals — attribute it to the same rank.
            lrank, ldigest = self._last_outlier
            rec = records.get(int(lrank)) or {}
            prev = rec.get("prev")
            prev_digest = (prev.get("digest")
                           if isinstance(prev, dict) else None)
            if prev_digest and prev_digest == ldigest:
                verdict = dict(verdict, ambiguous=False,
                               method="continuity",
                               outlier_rank=rec.get("rank", lrank),
                               outlier_host=rec.get("host"))
        host = verdict.get("outlier_host")
        rank = verdict.get("outlier_rank")
        if verdict.get("ambiguous") or not host:
            self._log.error(
                "elastic: integrity vote at group %s is DIVERGENT but "
                "ambiguous (%d voters, digests %s) — no host named, no "
                "action taken", group, verdict.get("voters"),
                verdict.get("digests"))
            _metrics.event(
                "integrity_divergence", generation=gen, host=None,
                rank=None, ambiguous=True, step=group[1],
                group_generation=group[0], voters=verdict.get("voters"),
                digests=verdict.get("digests"))
            # The watermark advanced: persist it, or a takeover driver
            # re-votes this still-staged group and journals a duplicate
            # ambiguous event (the named/clean branches already save).
            self._save_state()
            return
        out_rec = records.get(int(rank)) or {}
        if out_rec.get("digest"):
            # Remembered for the 2-voter continuity resolution above.
            self._last_outlier = (int(rank), str(out_rec["digest"]))
        # Confirmations are per MEMBERSHIP, like the policy channel's
        # strikes: a departed host's count must not survive into its
        # re-entry through the spare tier (the clean-vote clear alone
        # cannot guarantee it — another host's persistent divergence
        # can keep clean complete votes from ever landing).
        world = {h.hostname for h in self._world_hosts}
        for h in [h for h in self._integrity_strikes if h not in world]:
            del self._integrity_strikes[h]
        self._integrity_strikes[host] = (
            self._integrity_strikes.get(host, 0) + 1)
        strikes = self._integrity_strikes[host]
        self._log.error(
            "elastic: integrity vote named %s (rank %s) DIVERGENT at "
            "generation %d step %d (method=%s, strike %d) — silent data "
            "corruption evidence", host, rank, group[0], group[1],
            verdict.get("method"), strikes)
        _metrics.INTEGRITY_DIVERGENCE.inc(host=host)
        self._server.record_integrity_divergence(host)
        _metrics.event(
            "integrity_divergence", generation=gen, host=host, rank=rank,
            ambiguous=False, step=group[1], group_generation=group[0],
            method=verdict.get("method"), voters=verdict.get("voters"),
            digests=verdict.get("digests"), strikes=strikes)
        # Post-hoc evidence, like the policy drain's: the condemned
        # host's last shipped trace window rides a driver-side flight
        # record.
        payload = self._server.trace_payload(host) or {}
        _metrics.FLIGHT_DUMPS.inc(reason="integrity_divergence")
        _metrics.event(
            "flight_record", generation=gen,
            reason="integrity_divergence", host=host,
            steps=(payload.get("steps") or [])[-2:],
            digests=verdict.get("digests"))
        # Fence + evict BEFORE anything else: the corrupt shard must be
        # out of the assembly set before any recovery can read it. If
        # the outlier's own PREVIOUS fingerprint already disagreed with
        # its peers' (every record ships its prior digest inline), the
        # corruption predates this vote — condemn from that step, so a
        # detection that lagged one interval cannot leave a known-bad
        # replica eligible for peer-rung assembly (the ladder then
        # falls through to durable: correctness over storage-freeness).
        qgen, qstep = group
        try:
            outlier_prev = (records.get(int(rank)) or {}).get("prev") or {}
            peer_prevs = {
                ((rec.get("prev") or {}).get("digest"))
                for r2, rec in records.items() if int(r2) != int(rank)}
            if (outlier_prev.get("digest") and len(peer_prevs) == 1
                    and None not in peer_prevs
                    and outlier_prev["digest"] not in peer_prevs):
                qstep = int(outlier_prev.get("step", qstep))
                # The prev may belong to a PRIOR world generation (a
                # re-form landed between the two intervals): condemn
                # from its own generation, not the vote's, or the
                # known-bad prior-generation replica stays eligible.
                qgen = int(outlier_prev.get("generation", qgen))
        except (TypeError, ValueError):
            pass
        self._server.quarantine_rank(rank, host, generation=group[0],
                                     step=group[1],
                                     from_generation=qgen, from_step=qstep)
        self._policy.note_integrity(host)
        self._save_state()
        if (integrity.integrity_action() == "drain"
                and strikes >= integrity.confirmations()
                and host in self._workers):
            # Abort FIRST: survivors stop committing (and rotating the
            # last good replica group away) within one abort-poll
            # interval; the condemned host's final commit is fenced by
            # the quarantine anyway, so the graceful-drain ordering
            # buys nothing here.
            self._post_abort(
                f"integrity divergence on {host} (rank {rank}, "
                f"generation {group[0]} step {group[1]})")
            self._drain_host(
                host,
                f"integrity divergence (strike {strikes}, method "
                f"{verdict.get('method')})",
                action="drain", abort_posted=True)

    # -- policy tick ---------------------------------------------------------

    def _update_world_rate(self) -> None:
        """Feed the policy's throughput signal: per-host commit rates
        from successive heartbeat payload counters, averaged over the
        world (counter resets across relaunches reseed, never go
        negative)."""
        now = time.monotonic()
        world = {h.hostname for h in self._world_hosts}
        for name in [n for n in self._rate_state if n not in world]:
            del self._rate_state[name]
        rates = []
        for name in world:
            raw = self._server.heartbeat_payload(name)
            if raw is None:
                continue
            try:
                commits = json.loads(raw).get("commits")
            except (ValueError, UnicodeDecodeError):
                continue
            if not isinstance(commits, (int, float)):
                continue
            prev = self._rate_state.get(name)
            self._rate_state[name] = (float(commits), now)
            if prev is None:
                continue
            prev_commits, prev_t = prev
            dt = now - prev_t
            delta = float(commits) - prev_commits
            if dt <= 0 or delta < 0:
                continue
            rates.append(delta / dt)
        if rates:
            self._policy.note_rate(sum(rates) / len(rates))

    def _policy_tick(self) -> None:
        """One self-healing evaluation (throttled to the policy
        interval): reconcile spares, consume preemption notices, and —
        when the SLO knob arms the controller — fold the skew/heartbeat
        evidence, decide, and drain. A policy failure must never take the
        driver down; the monitor wraps this call."""
        now = time.monotonic()
        if now - self._last_policy_tick < max(
                min(self._policy.interval_s, 30.0), 0.25):
            return
        self._last_policy_tick = now
        version = self._server.generation
        self._ensure_spares(version)
        self._handle_preempt_notices(version)
        if not self._policy.armed:
            return  # inert: no evidence gathering, no decisions
        world_names = [h.hostname for h in self._world_hosts]
        if self._policy.enabled:
            # Goodput-evidence intake only serves the SLO channel; the
            # integrity-strikes channel (armed without a target) decides
            # on the vote tick's strike counts alone.
            self._update_world_rate()
            try:
                skew = self._server.straggler_summary()
            except Exception as e:  # noqa: BLE001 — evidence best-effort
                self._log.debug("elastic: straggler summary failed: %s", e)
                skew = {}
            # Comms-residual channel: per-host predicted-vs-observed
            # residual seconds from the cluster-merged alpha-beta model —
            # the link-degradation evidence that leads the skew signal.
            # Gated on the channel knob: the merge JSON-parses every
            # worker's heartbeat body on the single-threaded server, work
            # the controller would never read with the channel off.
            residuals: dict = {}
            if self._policy.comms_residual_s > 0:
                try:
                    residuals = (self._server.comms_summary()
                                 .get("residuals") or {})
                except Exception as e:  # noqa: BLE001 — best-effort
                    self._log.debug("elastic: comms summary failed: %s", e)
            # Step-regression channel: the attribution sentinel's
            # {suspect host: excess seconds} map. Every world host gets
            # an explicit 0.0 when the channel is fed (measured
            # healthy), so a cleared alarm RESETS the condemnation
            # clock instead of freezing it; knob-gated like the
            # comms channel (the analysis runs on the server either
            # way — this only gates the controller's intake).
            regression: dict | None = None
            if self._policy.step_regression_s > 0:
                try:
                    regression = {h: 0.0 for h in world_names}
                    regression.update(self._server.regression_suspects())
                except Exception as e:  # noqa: BLE001 — best-effort
                    self._log.debug(
                        "elastic: regression suspects failed: %s", e)
                    regression = None
            self._policy.observe(skew, self._server.heartbeat_ages(),
                                 world_names, comms_residuals=residuals,
                                 regression_excess=regression)
        decision = self._policy.decide(world_names,
                                       self._warm_spare_count())
        if decision is not None and decision.host in self._workers:
            self._drain_host(decision.host, decision.reason,
                             decision=decision, action=decision.action)
        realized = self._policy.realize_tick()
        if realized is not None:
            self._log.info(
                "elastic: policy decision on %s realized: %s",
                realized.host, realized.predicted.get("realized"))

    def _monitor(self) -> int:
        last_poll = 0.0
        while True:
            if self._superseded:
                # A snapshot/endpoint write was fenced: a higher-epoch
                # driver owns the world (this one was SIGSTOP'd or
                # partitioned through its own relaunch). Stand down
                # WITHOUT touching the workers — the successor adopted
                # them (run()'s finally skips termination on this flag).
                _metrics.event("driver_superseded",
                               generation=self._server.generation,
                               driver_epoch=self.driver_epoch)
                return EXIT_DRIVER_SUPERSEDED
            # 1. Reap exited workers.
            finished = {
                n: w for n, w in self._workers.items()
                if w.popen.poll() is not None
            }
            need_reconfigure = False
            for name, w in finished.items():
                rc = w.popen.returncode
                del self._workers[name]
                self._launched_at.pop(name, None)
                self._server.clear_heartbeat(name)
                _metrics.event("worker_exit",
                               generation=self._server.generation,
                               host=name, rc=rc,
                               adopted=isinstance(w.popen, _AdoptedPopen))
                if isinstance(w.popen, _AdoptedPopen):
                    # An adopted (non-child) worker's exit code is
                    # unreadable. Completion is learned from the done
                    # record the elastic loop publishes on return;
                    # anything else is treated as an unclean exit — but
                    # WITHOUT blacklisting (we cannot distinguish a
                    # crash from a clean EXIT_REMOVED, and a takeover
                    # must not poison the blacklist with guesses).
                    if name in self._server.done_records():
                        self._log.info(
                            "elastic: adopted worker on %s finished ok "
                            "(done record)", name)
                        _metrics.event("job_complete",
                                       generation=self._server.generation,
                                       host=name)
                        return 0
                    self._log.warning(
                        "elastic: adopted worker on %s exited with an "
                        "unreadable code and no done record; relaunching "
                        "without blacklisting", name)
                    self._post_abort(
                        f"adopted worker on {name} exited uncleanly")
                    need_reconfigure = True
                    continue
                if rc == 0:
                    # Success on any worker ⇒ the job completed (reference
                    # semantics: the training function returned).
                    self._log.info("elastic: worker on %s finished ok", name)
                    _metrics.event("job_complete",
                                   generation=self._server.generation,
                                   host=name)
                    return 0
                if rc == EXIT_REMOVED:
                    # Clean self-exit of a worker dropped from the world —
                    # not a failure, not job completion.
                    self._log.info("elastic: removed worker on %s exited", name)
                    continue
                if rc == EXIT_DRIVER_LOST:
                    # The worker gave up on an unreachable rendezvous KV.
                    # If we are here to see it, the driver process is alive
                    # — a partition or KV fault, i.e. a CONTROL-PLANE
                    # problem, not a host problem: relaunch the worker but
                    # do not poison the blacklist with a healthy host.
                    # Capped: a PERSISTENT per-host KV fault (firewalled
                    # port) must not churn the whole fleet through a
                    # reconfiguration every driver-loss deadline forever —
                    # after 3 consecutive 203s the host is blacklisted
                    # like any failure.
                    n = self._driver_lost_counts.get(name, 0) + 1
                    self._driver_lost_counts[name] = n
                    # Control-plane flap observability (the cap below was
                    # invisible before): hvd_driver_lost_total{host} on
                    # the scrape + a driver_lost journal event per reap,
                    # so operators see flaps building toward the
                    # blacklist long before it fires.
                    self._server.record_driver_lost(name)
                    _metrics.DRIVER_LOST.inc(host=name)
                    _metrics.event(
                        "driver_lost", generation=self._server.generation,
                        host=name, consecutive=n, capped=n > 3)
                    if n <= 3:
                        self._log.error(
                            "elastic: worker on %s lost the rendezvous KV "
                            "(rc=%d, %d consecutive) — control-plane "
                            "fault, not a host fault; relaunching without "
                            "blacklisting", name, rc, n,
                        )
                        self._post_abort(
                            f"worker on {name} exited EXIT_DRIVER_LOST")
                        need_reconfigure = True
                        continue
                    self._log.error(
                        "elastic: worker on %s lost the rendezvous KV %d "
                        "consecutive times — persistent; blacklisting",
                        name, n,
                    )
                    del self._driver_lost_counts[name]
                    self._post_abort(
                        f"worker on {name} lost the rendezvous KV "
                        f"{n} consecutive times; blacklisted")
                    self._blacklist(
                        name, f"{n} consecutive EXIT_DRIVER_LOST exits")
                    need_reconfigure = True
                    continue
                self._driver_lost_counts.pop(name, None)
                self._log.warning(
                    "elastic: worker on %s failed (rc=%d); blacklisting",
                    name, rc,
                )
                self._post_abort(
                    f"worker on {name} failed with rc={rc}; blacklisted")
                self._blacklist(name, f"worker failed with rc={rc}")
                need_reconfigure = True
            # 1b. Liveness plane: kill + blacklist hosts the heartbeat
            # deadline has condemned (hung, not crashed — invisible to the
            # reap above). terminate_worker escalates SIGTERM→SIGKILL, and
            # SIGKILL lands even on a SIGSTOP'd process.
            for name, why in self._dead_by_heartbeat():
                self._log.warning(
                    "elastic: worker on %s is hung (%s); killing and "
                    "blacklisting", name, why,
                )
                # Abort FIRST, kill second: survivors wedged with the hung
                # peer should already be polling the flag when the SIGKILL
                # lands, whichever unblocks them first.
                self._post_abort(f"worker on {name} is hung ({why}); killed")
                _metrics.event("worker_hung",
                               generation=self._server.generation,
                               host=name, reason=why)
                terminate_worker(self._workers.pop(name))
                self._launched_at.pop(name, None)
                self._server.clear_heartbeat(name)
                self._blacklist(name, f"hung: {why}")
                need_reconfigure = True
            # Driver-level drain: once every worker has exited (final
            # commits landed, EXIT_REMOVED reaped above), the job is
            # drained — don't re-form a world we were told to vacate.
            if self._draining:
                if not self._workers:
                    self._log.info("elastic: drain complete; exiting")
                    _metrics.event("driver_drained",
                                   generation=self._server.generation)
                    return 0
                time.sleep(0.05)
                continue
            if need_reconfigure:
                self._reconfigure()
                continue
            # 1c. Integrity defense plane: vote the piggybacked
            # fingerprints, fence/drain a corrupting host. Failures are
            # logged, never fatal — same contract as the policy brain.
            try:
                self._integrity_tick()
            except Exception as e:  # noqa: BLE001
                self._log.warning("elastic: integrity tick failed: %s", e)
            # 1d. Self-healing policy plane: warm-spare reconciliation,
            # preemption notices, and (when HOROVOD_TARGET_GOODPUT arms
            # it) straggler-drain decisions. Policy failures are logged,
            # never fatal — a broken brain must not kill the body.
            try:
                self._policy_tick()
            except Exception as e:  # noqa: BLE001
                self._log.warning("elastic: policy tick failed: %s", e)
            # 1e. Durable control plane: periodic snapshot refresh — the
            # mutation paths save eagerly, but worker PIDs and policy
            # EWMAs drift between mutations and a takeover should resume
            # the freshest view (also the stale-driver tripwire: a
            # resumed predecessor's first refresh hits the fence and it
            # stands down).
            if (self._store is not None
                    and self._state_refresh_s > 0
                    and time.monotonic() - self._last_state_save
                    >= self._state_refresh_s):
                self._save_state()
            # 2. Poll discovery.
            if time.time() - last_poll >= self._poll_interval:
                last_poll = time.time()
                try:
                    changed = self._manager.update_available_hosts()
                except HostDiscoveryFailedError:
                    raise  # sustained streak: fail the job loudly
                except Exception as e:
                    self._log.warning("elastic: discovery failed: %s", e)
                    changed = False
                if changed:
                    self._log.info("elastic: host set changed; reconfiguring")
                    self._reconfigure()
            time.sleep(0.05)


def run_elastic(settings, sink=None, discovery=None) -> int:
    """Entry used by ``hvdrun --host-discovery-script ...``."""
    driver = ElasticDriver(settings, discovery=discovery, sink=sink)
    return driver.run()
