from .discovery import (  # noqa: F401
    FixedHostDiscovery,
    HostDiscovery,
    HostDiscoveryScript,
    HostManager,
)
from .driver import ElasticDriver, run_elastic  # noqa: F401
