"""Elastic host discovery.

Parity with ``horovod/runner/elastic/discovery.py`` (``HostDiscovery``,
``HostDiscoveryScript``, ``HostManager``): the driver periodically asks a
user-provided source which hosts exist; the manager diffs successive views,
maintains the failure blacklist, and answers "which hosts may run workers
right now".

TPU divergence (SURVEY.md §4.4): a discovered host is a TPU VM worker; host
removal ≙ preemption. The manager additionally snaps the usable host count to
a topology-valid world size (``valid_sizes``) — ICI slices cannot shrink by
arbitrary chip counts, so the driver only forms worlds whose host count is in
the valid set (default: any count — DCN data-parallel groups have no such
constraint).
"""

from __future__ import annotations

import subprocess
import threading
from typing import Callable, Sequence

from ..hosts import HostInfo


class HostDiscovery:
    """Interface: return the current world as {hostname: slots}."""

    def find_available_hosts_and_slots(self) -> dict[str, int]:
        raise NotImplementedError


class HostDiscoveryScript(HostDiscovery):
    """Runs a user script that prints ``host:slots`` (or ``host``) per line.

    The reference's fault-injection test pattern drives this: tests edit the
    file the script reads, and the driver picks up the change on the next
    poll. Keep that contract — it is the cheapest chaos harness there is.
    """

    def __init__(self, script_path: str, timeout: float = 10.0):
        self._script = script_path
        self._timeout = timeout

    def find_available_hosts_and_slots(self) -> dict[str, int]:
        out = subprocess.run(
            [self._script],
            capture_output=True,
            timeout=self._timeout,
            check=True,
            text=True,
            shell=False,
        ).stdout
        hosts: dict[str, int] = {}
        for line in out.splitlines():
            line = line.strip()
            if not line:
                continue
            info = HostInfo.from_string(line)
            hosts[info.hostname] = info.slots
        return hosts


class FixedHostDiscovery(HostDiscovery):
    """Static host set (used when elastic runs with a fixed -H list)."""

    def __init__(self, hosts: Sequence[HostInfo]):
        self._hosts = {h.hostname: h.slots for h in hosts}

    def find_available_hosts_and_slots(self) -> dict[str, int]:
        return dict(self._hosts)


class HostManager:
    """Tracks discovered hosts, the blacklist, and world-size validity."""

    def __init__(
        self,
        discovery: HostDiscovery,
        valid_sizes: Callable[[int], bool] | None = None,
    ):
        self._discovery = discovery
        self._lock = threading.Lock()
        self._current: dict[str, int] = {}
        self._blacklist: set[str] = set()
        self._valid = valid_sizes or (lambda n: n >= 1)

    def update_available_hosts(self) -> bool:
        """Poll discovery; returns True if the usable host set changed."""
        found = self._discovery.find_available_hosts_and_slots()
        with self._lock:
            before = self._usable_locked()
            self._current = found
            after = self._usable_locked()
            return before != after

    def blacklist(self, hostname: str) -> None:
        with self._lock:
            self._blacklist.add(hostname)

    def is_blacklisted(self, hostname: str) -> bool:
        with self._lock:
            return hostname in self._blacklist

    def _usable_locked(self) -> dict[str, int]:
        return {
            h: s for h, s in self._current.items() if h not in self._blacklist
        }

    def usable_hosts(self) -> list[HostInfo]:
        with self._lock:
            return [HostInfo(h, s) for h, s in sorted(self._usable_locked().items())]

    def pick_world(
        self, preferred: Sequence[str], max_np: int | None
    ) -> list[HostInfo]:
        """Choose the next world's hosts: keep `preferred` (current workers)
        first for rank stability, append new hosts, cap at max_np, then snap
        down to the largest topology-valid count."""
        with self._lock:
            usable = self._usable_locked()
        ordered: list[HostInfo] = []
        for h in preferred:
            if h in usable:
                ordered.append(HostInfo(h, usable[h]))
        for h, s in sorted(usable.items()):
            if all(o.hostname != h for o in ordered):
                ordered.append(HostInfo(h, s))
        if max_np is not None:
            ordered = ordered[:max_np]
        while ordered and not self._valid(len(ordered)):
            ordered.pop()
        return ordered
