"""Elastic host discovery.

Parity with ``horovod/runner/elastic/discovery.py`` (``HostDiscovery``,
``HostDiscoveryScript``, ``HostManager``): the driver periodically asks a
user-provided source which hosts exist; the manager diffs successive views,
maintains the failure blacklist, and answers "which hosts may run workers
right now".

TPU divergence (SURVEY.md §4.4): a discovered host is a TPU VM worker; host
removal ≙ preemption. The manager additionally snaps the usable host count to
a topology-valid world size (``valid_sizes``) — ICI slices cannot shrink by
arbitrary chip counts, so the driver only forms worlds whose host count is in
the valid set (default: any count — DCN data-parallel groups have no such
constraint).
"""

from __future__ import annotations

import subprocess
import threading
import time
from typing import Callable, Sequence

from ... import faults
from ...exceptions import HostDiscoveryFailedError
from ..hosts import HostInfo


class HostDiscovery:
    """Interface: return the current world as {hostname: slots}."""

    def find_available_hosts_and_slots(self) -> dict[str, int]:
        raise NotImplementedError


class HostDiscoveryScript(HostDiscovery):
    """Runs a user script that prints ``host:slots`` (or ``host``) per line.

    The reference's fault-injection test pattern drives this: tests edit the
    file the script reads, and the driver picks up the change on the next
    poll. Keep that contract — it is the cheapest chaos harness there is.
    """

    def __init__(self, script_path: str, timeout: float = 10.0):
        self._script = script_path
        self._timeout = timeout

    def find_available_hosts_and_slots(self) -> dict[str, int]:
        out = subprocess.run(
            [self._script],
            capture_output=True,
            timeout=self._timeout,
            check=True,
            text=True,
            shell=False,
        ).stdout
        hosts: dict[str, int] = {}
        for line in out.splitlines():
            line = line.strip()
            if not line:
                continue
            info = HostInfo.from_string(line)
            hosts[info.hostname] = info.slots
        return hosts


class FixedHostDiscovery(HostDiscovery):
    """Static host set (used when elastic runs with a fixed -H list)."""

    def __init__(self, hosts: Sequence[HostInfo]):
        self._hosts = {h.hostname: h.slots for h in hosts}

    def find_available_hosts_and_slots(self) -> dict[str, int]:
        return dict(self._hosts)


def snap_to_topology(
    hosts: Sequence[HostInfo],
    max_hosts: int | None = None,
) -> list[HostInfo]:
    """Snap a candidate host set to a TOPOLOGY-VALID world (SURVEY §8 hard
    part 3: ICI slices cannot shrink by arbitrary chip counts).

    Validity rules, in order:

    - **host granularity**: whole hosts only — a TPU VM's chips leave or
      join together (preemption takes the VM, not a chip);
    - **homogeneous local size**: every chosen host contributes the SAME
      slot count L. The hierarchical (cross, local) mesh needs equal rows
      — a ragged world would push full-payload legs onto DCN
      (``parallel/hierarchical.py``) — and an ICI sub-slice is uniform by
      construction.

    The chosen L maximizes total ranks ``count(slots >= L) * L`` over the
    candidate L values present in the set; ties prefer the LARGER L (a
    wider ICI leg beats more DCN rows at equal rank count). Hosts are
    returned in the input order (rank stability) with slots clamped to L.
    """
    ordered = list(hosts)
    if max_hosts is not None:
        ordered = ordered[:max_hosts]
    if not ordered:
        return []
    candidates = sorted({h.slots for h in ordered}, reverse=True)
    best_l, best_total = 0, -1
    for L in candidates:
        total = sum(1 for h in ordered if h.slots >= L) * L
        if total > best_total:  # ties keep the earlier (larger) L
            best_l, best_total = L, total
    return [HostInfo(h.hostname, best_l)
            for h in ordered if h.slots >= best_l]


class HostManager:
    """Tracks discovered hosts, the blacklist, and world-size validity."""

    def __init__(
        self,
        discovery: HostDiscovery,
        valid_sizes: Callable[[int], bool] | None = None,
        cooldown_s: float | None = None,
        max_discovery_failures: int | None = None,
        warm_spares: int | None = None,
    ):
        from ...utils.env import get_float, get_int

        self._discovery = discovery
        # Warm-spare tier (HOROVOD_WARM_SPARES): up to this many usable
        # hosts are held OUT of the world — discovered, launchable,
        # heartbeating — so a replacement costs one re-rendezvous at the
        # next generation fence instead of a cold launch. 0 (default)
        # disables the tier entirely (HEAD behavior, bit for bit).
        self._warm_spares = (
            get_int("HOROVOD_WARM_SPARES", 0)
            if warm_spares is None else warm_spares)
        self._spares: set[str] = set()
        # Hosts whose blacklist cooldown expired while still discovered:
        # with the spare tier enabled they must RE-ENTER AS SPARES, not
        # swap straight back into a healthy world — a host that was just
        # condemned proves itself warm first. The flag clears when the
        # world actually NEEDS the host (a shrink below target), which is
        # exactly the promotion path.
        self._cooldown_returned: set[str] = set()
        # A single discovery blip is routine (script timeout, cloud API
        # hiccup) and the driver retries it; a STREAK of
        # HOROVOD_ELASTIC_DISCOVERY_FAILURES consecutive failures means
        # the driver is blind to the fleet and must fail loudly instead
        # of freezing the elastic world forever. 0 disables escalation.
        self._max_discovery_failures = (
            get_int("HOROVOD_ELASTIC_DISCOVERY_FAILURES", 10)
            if max_discovery_failures is None else max_discovery_failures)
        self._discovery_failures = 0
        self._lock = threading.Lock()
        self._current: dict[str, int] = {}
        # host -> blacklist timestamp. With a cooldown
        # (HOROVOD_BLACKLIST_COOLDOWN seconds, reference:
        # cooldown_range in horovod/runner/elastic/discovery.py) entries
        # EXPIRE — the recovery path for whole-generation failures
        # (preempted slice, host reboot) where the same hosts come back;
        # 0 keeps the permanent blacklist.
        self._blacklist: dict[str, float] = {}
        self._cooldown_s = (
            get_float("HOROVOD_BLACKLIST_COOLDOWN", 0.0)
            if cooldown_s is None else cooldown_s)
        self._expired_pending = False  # expiry happened since last poll
        self._valid = valid_sizes or (lambda n: n >= 1)

    def update_available_hosts(self) -> bool:
        """Poll discovery; returns True if the usable host set changed.

        Raises :class:`HostDiscoveryFailedError` after
        ``max_discovery_failures`` CONSECUTIVE poll failures (one success
        resets the streak); below that the underlying exception propagates
        so the caller can log-and-retry as before.
        """
        try:
            if faults.fire(faults.DISCOVERY_POLL):
                return False  # injected drop: this poll never happened
            found = self._discovery.find_available_hosts_and_slots()
        except HostDiscoveryFailedError:
            raise
        except Exception as e:
            self._discovery_failures += 1
            if (self._max_discovery_failures > 0
                    and self._discovery_failures
                    >= self._max_discovery_failures):
                raise HostDiscoveryFailedError(
                    f"host discovery failed {self._discovery_failures} "
                    f"consecutive times (last: {e}); the elastic driver "
                    "cannot see the fleet — giving up"
                ) from e
            raise
        self._discovery_failures = 0
        with self._lock:
            # 'before' is the PRE-PRUNE view — the world the caller last
            # acted on. A cooldown expiry must read as a change whether
            # it happens during this poll or was absorbed by an earlier
            # lazy-pruning read (_expired_pending records those): an
            # expired host that never reads as a change would never
            # trigger the reconfiguration that re-admits it.
            before = {h: s for h, s in self._current.items()
                      if h not in self._blacklist}
            self._current = found
            after = self._usable_locked()
            changed = before != after or self._expired_pending
            self._expired_pending = False
            return changed

    def blacklist(self, hostname: str) -> None:
        with self._lock:
            # monotonic: a wall-clock step (NTP after VM resume — this
            # code's exact environment) must not stretch or collapse the
            # cooldown window.
            self._blacklist[hostname] = time.monotonic()

    def export_blacklist(self) -> dict[str, float]:
        """Blacklist as {host: age-in-seconds} — RELATIVE ages, because
        monotonic stamps do not survive a driver restart. Feeds the
        durable control-plane snapshot (driver_state.py)."""
        now = time.monotonic()
        with self._lock:
            self._prune_blacklist_locked()
            return {h: now - t for h, t in self._blacklist.items()}

    def restore_blacklist(self, ages) -> None:
        """Takeover resume: re-enter blacklist entries with their
        exported ages re-based onto THIS process's monotonic clock —
        cooldown windows keep counting across the crash instead of
        restarting (a condemned host must not be re-admitted early just
        because the control plane flapped)."""
        if not isinstance(ages, dict):
            return
        now = time.monotonic()
        with self._lock:
            for host, age in ages.items():
                try:
                    self._blacklist[str(host)] = now - max(float(age), 0.0)
                except (TypeError, ValueError):
                    continue

    def is_blacklisted(self, hostname: str) -> bool:
        with self._lock:
            self._prune_blacklist_locked()
            return hostname in self._blacklist

    def blacklist_count(self) -> int:
        """Hosts currently blacklisted (cooldown-pruned) — the driver's
        ``hvd_blacklisted_hosts`` scrape gauge."""
        with self._lock:
            self._prune_blacklist_locked()
            return len(self._blacklist)

    def _prune_blacklist_locked(self) -> None:
        if self._cooldown_s <= 0:
            return
        now = time.monotonic()
        for h in [h for h, t in self._blacklist.items()
                  if now - t >= self._cooldown_s]:
            del self._blacklist[h]
            # Only a host discovery STILL lists is a usable-set change;
            # flagging a departed host's expiry would trigger a no-op
            # whole-world reconfiguration (new epoch, re-formed world)
            # that re-admits nothing.
            if h in self._current:
                self._expired_pending = True
                if self._warm_spares > 0:
                    self._cooldown_returned.add(h)

    def _usable_locked(self) -> dict[str, int]:
        self._prune_blacklist_locked()
        return {
            h: s for h, s in self._current.items() if h not in self._blacklist
        }

    def usable_hosts(self) -> list[HostInfo]:
        with self._lock:
            return [HostInfo(h, s) for h, s in sorted(self._usable_locked().items())]

    def pick_world(
        self, preferred: Sequence[str], max_np: int | None
    ) -> list[HostInfo]:
        """Choose the next world's hosts: keep `preferred` (current workers)
        first for rank stability, append new hosts, cap at max_np, snap to
        a topology-valid shape (host granularity + homogeneous local size,
        :func:`snap_to_topology`), then snap down to the largest valid
        host count.

        With the warm-spare tier enabled (``warm_spares > 0``) the pick
        additionally: (a) holds up to ``warm_spares`` surplus usable hosts
        OUT of the world (``spare_hosts()`` reports them — the driver
        keeps warm worker processes on them); (b) keeps cooldown-returned
        hosts in the spare tier until the world actually needs them to
        reach its target size — a just-condemned host proves itself warm
        before it re-enters; a blacklisted host is never usable at all,
        so it can appear in neither the world nor the spare tier.
        """
        with self._lock:
            usable = self._usable_locked()
            # A returned host that left discovery (or was re-blacklisted)
            # sheds the flag — stale entries must not leak.
            self._cooldown_returned &= set(usable)
            returned = set(self._cooldown_returned)
        ordered: list[HostInfo] = []
        for h in preferred:
            if h in usable:
                ordered.append(HostInfo(h, usable[h]))
        for h, s in sorted(usable.items()):
            if all(o.hostname != h for o in ordered):
                ordered.append(HostInfo(h, s))
        if self._warm_spares <= 0:
            ordered = snap_to_topology(ordered, max_hosts=max_np)
            while ordered and not self._valid(len(ordered)):
                ordered.pop()
            with self._lock:
                self._spares = set()
            return ordered
        # Spare-aware pick: fill the world from hosts NOT gated behind the
        # cooldown-return rule first; promote returned hosts only when the
        # world would otherwise fall short of its budget.
        budget = max_np if max_np is not None else max(
            len(ordered) - self._warm_spares, 1)
        world = [h for h in ordered if h.hostname not in returned][:budget]
        promoted: set[str] = set()
        if len(world) < budget:
            for h in ordered:
                if len(world) >= budget:
                    break
                if h.hostname in returned and all(
                        o.hostname != h.hostname for o in world):
                    world.append(h)
                    promoted.add(h.hostname)
        # Re-impose preferred-first order (rank stability) after the fill.
        order_index = {h.hostname: i for i, h in enumerate(ordered)}
        world.sort(key=lambda h: order_index[h.hostname])
        world = snap_to_topology(world, max_hosts=budget)
        while world and not self._valid(len(world)):
            world.pop()
        world_names = {h.hostname for h in world}
        spares = [h for h in ordered
                  if h.hostname not in world_names][: self._warm_spares]
        with self._lock:
            self._cooldown_returned -= promoted & world_names
            self._spares = {h.hostname for h in spares}
        return world

    def spare_hosts(self) -> list[HostInfo]:
        """The current spare tier: usable hosts the last ``pick_world``
        held out of the world for warm standby (empty when the tier is
        disabled)."""
        with self._lock:
            usable = self._usable_locked()
            return [HostInfo(h, usable[h])
                    for h in sorted(self._spares) if h in usable]

    @property
    def warm_spares_target(self) -> int:
        return self._warm_spares
