"""Cluster-scheduler host discovery for ``hvdrun``.

Parity: ``horovod/runner/util/lsf.py`` (``LSFUtils.using_lsf`` /
``get_compute_hosts``) and the reference's Slurm support (upstream rides
``mpirun`` inside an allocation; we parse the allocation directly since
there is no MPI here). When ``hvdrun`` runs inside an LSF or Slurm job and
the user gave no ``-H``/``--hostfile``, the allocation's hosts are used
automatically — same UX as the reference's LSF auto-detection.

Slots follow this launcher's meaning (one controller process per host;
slots = devices the host contributes — see :mod:`.hosts`), so scheduler
task/cpu counts are carried through as the per-host slot count.
"""

from __future__ import annotations

import os
import re

from .hosts import HostInfo, HostParseError


def in_lsf(environ=os.environ) -> bool:
    """True inside an LSF job (parity: LSFUtils.using_lsf)."""
    return "LSB_JOBID" in environ and (
        "LSB_MCPU_HOSTS" in environ or "LSB_HOSTS" in environ
    )


def lsf_hosts(environ=os.environ) -> list[HostInfo]:
    """Hosts of the current LSF allocation, first-seen order.

    ``LSB_MCPU_HOSTS`` is "host1 n1 host2 n2 ..."; ``LSB_HOSTS`` repeats
    each hostname once per slot. The batch/launch host LSF prepends is
    kept — the reference also trains on it.
    """
    mcpu = environ.get("LSB_MCPU_HOSTS")
    counts: dict[str, int] = {}
    if mcpu:
        toks = mcpu.split()
        if len(toks) % 2:
            raise HostParseError(f"malformed LSB_MCPU_HOSTS: {mcpu!r}")
        for host, n in zip(toks[::2], toks[1::2]):
            if not n.isdigit() or int(n) < 1:
                raise HostParseError(
                    f"malformed LSB_MCPU_HOSTS count for {host}: {n!r}"
                )
            counts[host] = counts.get(host, 0) + int(n)
    else:
        for host in environ.get("LSB_HOSTS", "").split():
            counts[host] = counts.get(host, 0) + 1
    if not counts:
        raise HostParseError("no LSF hosts found in LSB_MCPU_HOSTS/LSB_HOSTS")
    return [HostInfo(h, n) for h, n in counts.items()]


def in_slurm(environ=os.environ) -> bool:
    """True inside a Slurm allocation."""
    return "SLURM_JOB_ID" in environ and (
        "SLURM_JOB_NODELIST" in environ or "SLURM_NODELIST" in environ
    )


def expand_nodelist(nodelist: str) -> list[str]:
    """Expand Slurm's compressed nodelist syntax:
    ``"tpu[001-004,007],login1"`` -> tpu001..tpu004, tpu007, login1.
    Zero-padding of range endpoints is preserved.
    """
    hosts: list[str] = []
    i = 0
    n = len(nodelist)
    while i < n:
        j = i
        # scan one comma-separated element, tracking bracket depth
        depth = 0
        while j < n and (nodelist[j] != "," or depth > 0):
            if nodelist[j] == "[":
                depth += 1
            elif nodelist[j] == "]":
                depth -= 1
            j += 1
        elem = nodelist[i:j].strip()
        i = j + 1
        if not elem:
            continue
        m = re.fullmatch(r"([^\[\]]*)\[([^\]]+)\]([^\[\]]*)", elem)
        if not m:
            if "[" in elem or "]" in elem:
                raise HostParseError(f"bad Slurm nodelist element {elem!r}")
            hosts.append(elem)
            continue
        prefix, body, suffix = m.groups()
        for part in body.split(","):
            part = part.strip()
            if "-" in part:
                lo, hi = part.split("-", 1)
                width = len(lo) if lo.startswith("0") else 0
                if not (lo.isdigit() and hi.isdigit() and int(lo) <= int(hi)):
                    raise HostParseError(
                        f"bad Slurm range {part!r} in {elem!r}"
                    )
                for v in range(int(lo), int(hi) + 1):
                    hosts.append(f"{prefix}{v:0{width}d}{suffix}")
            else:
                if not part.isdigit():
                    raise HostParseError(
                        f"bad Slurm range element {part!r} in {elem!r}"
                    )
                hosts.append(f"{prefix}{part}{suffix}")
    if not hosts:
        raise HostParseError(f"empty Slurm nodelist {nodelist!r}")
    return hosts


def _expand_tasks_per_node(spec: str, n_hosts: int) -> list[int]:
    """Expand SLURM_TASKS_PER_NODE, e.g. ``"2(x3),1"`` -> [2,2,2,1];
    pads/truncates defensively to n_hosts (1 slot default)."""
    out: list[int] = []
    for part in spec.split(","):
        part = part.strip()
        m = re.fullmatch(r"(\d+)(?:\(x(\d+)\))?", part)
        if not m:
            raise HostParseError(f"bad SLURM_TASKS_PER_NODE element {part!r}")
        count = int(m.group(2)) if m.group(2) else 1
        out.extend([int(m.group(1))] * count)
    out = out[:n_hosts]
    out.extend([1] * (n_hosts - len(out)))
    return out


def slurm_hosts(environ=os.environ) -> list[HostInfo]:
    """Hosts of the current Slurm allocation with per-node task counts as
    slots."""
    nodelist = environ.get("SLURM_JOB_NODELIST") or environ.get(
        "SLURM_NODELIST"
    )
    if not nodelist:
        raise HostParseError("no SLURM_JOB_NODELIST/SLURM_NODELIST set")
    names = expand_nodelist(nodelist)
    tasks = environ.get("SLURM_TASKS_PER_NODE")
    slots = (
        _expand_tasks_per_node(tasks, len(names))
        if tasks
        else [1] * len(names)
    )
    return [HostInfo(h, s) for h, s in zip(names, slots)]


def detect_scheduler_hosts(environ=os.environ) -> list[HostInfo] | None:
    """Hosts from the surrounding scheduler allocation, or None when not
    running under a recognized scheduler. LSF is checked first (the
    reference's only auto-detected scheduler), then Slurm."""
    if in_lsf(environ):
        return lsf_hosts(environ)
    if in_slurm(environ):
        return slurm_hosts(environ)
    return None
