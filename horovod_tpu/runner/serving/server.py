"""The serving fleet's HTTP front: health + inference off the
RCU-swapped model.

Stdlib only (ThreadingHTTPServer — the same serving substrate as the
rendezvous KV), no framework init on the request path. The request
handler reads the model pointer ONCE (:meth:`ModelServer.current`) and
uses that snapshot for the whole request: a concurrent hot-swap is
invisible to in-flight requests, and a request can never observe two
models (the swap-atomicity contract tests/test_serving.py hammers).

Routes:

- ``GET /model`` — health/identity/age JSON (``ModelServer.health``);
  200 with ``status: no_model`` before the first install — readiness
  probes poll this, they must never see a connection error.
- ``POST /infer`` — run ``infer_fn(model, body)`` on the snapshot.
  With no model yet: 503 (the ONLY 5xx this server emits — once a model
  has been served, degradation serves last-good, never an error).

``infer_fn`` is injectable: the default echoes the model identity
(generation/step/digest), which is exactly what the chaos tests need to
prove which model served a request; real deployments pass a jax apply.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable

from ... import metrics as _metrics
from ... import serving as _serving


def _default_infer(model: _serving.ServedModel, body: bytes) -> dict:
    """Identity probe: which complete model served this request."""
    return {"generation": model.generation, "step": model.step,
            "digest": model.digest}


class _ServeHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, *args):  # noqa: D102 — quiet by default
        pass

    def _reply(self, code: int, payload: dict):
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802
        if self.path != "/model":
            return self._reply(404, {"error": "unknown route"})
        self._reply(200, self.server.model_server.health())  # type: ignore[attr-defined]

    def do_POST(self):  # noqa: N802
        if self.path != "/infer":
            return self._reply(404, {"error": "unknown route"})
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        # THE read: one reference fetch, then this request lives on that
        # snapshot no matter how many swaps land meanwhile.
        model = self.server.model_server.current()  # type: ignore[attr-defined]
        try:
            _metrics.SERVE_REQUESTS.inc()
        except Exception:  # noqa: BLE001
            pass
        if model is None:
            return self._reply(503, {"error": "no model installed yet"})
        try:
            out = self.server.infer_fn(model, body)  # type: ignore[attr-defined]
        except Exception as e:  # noqa: BLE001 — one bad request ≠ dark fleet
            return self._reply(400, {"error": str(e)})
        self._reply(200, out)


class InferenceServer:
    """The serving process: subscriber thread + HTTP front."""

    def __init__(self, model_server: _serving.ModelServer | None = None,
                 infer_fn: Callable | None = None,
                 host: str = "0.0.0.0", port: int = 0):
        self.model_server = model_server or _serving.ModelServer()
        self.subscriber = _serving.ModelSubscriber(self.model_server)
        self._httpd = ThreadingHTTPServer((host, port), _ServeHandler)
        self._httpd.model_server = self.model_server  # type: ignore[attr-defined]
        self._httpd.infer_fn = infer_fn or _default_infer  # type: ignore[attr-defined]
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> None:
        self.subscriber.start()
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="hvd-serve-http",
            daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self.subscriber.stop()
        self._httpd.shutdown()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self._httpd.server_close()


def serve(host: str = "0.0.0.0", port: int = 8500) -> None:
    """Blocking entry point (``python -m horovod_tpu.runner.serving``)."""
    server = InferenceServer(host=host, port=port)
    server.start()
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
