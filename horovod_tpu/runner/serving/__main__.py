"""CLI: ``python -m horovod_tpu.runner.serving [--host H] [--port P]``.

Reads the rendezvous endpoint from the launcher env contract
(HOROVOD_RENDEZVOUS_ADDR / HOROVOD_RENDEZVOUS_PORT) and serves until
interrupted.
"""

from __future__ import annotations

import argparse

from .server import serve


def main() -> None:
    parser = argparse.ArgumentParser(
        prog="horovod_tpu.runner.serving",
        description="Read-only serving tier: subscribe to the KV "
                    "modelstate scope and hot-swap inference weights.")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=8500)
    args = parser.parse_args()
    serve(host=args.host, port=args.port)


if __name__ == "__main__":
    main()
