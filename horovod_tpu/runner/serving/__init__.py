"""The serving tier's process surface: a stdlib HTTP inference server
wired to the training→serving bridge (:mod:`horovod_tpu.serving`).

``python -m horovod_tpu.runner.serving`` starts a subscriber polling the
rendezvous KV's ``modelstate`` scope and an HTTP front that serves
health (``GET /model``) and inference (``POST /infer``) off the
RCU-swapped model — see :mod:`.server`.
"""

from .server import InferenceServer, serve  # noqa: F401
