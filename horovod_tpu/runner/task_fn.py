"""Per-host task-service entry point (parity: ``horovod/runner/task_fn.py``).

The driver launches ``python -m horovod_tpu.runner.task_fn`` on every host
during the pre-flight probe; the process prints its service port (the
driver reads it from the muxed output), serves NIC queries, and exits when
the driver is done (or after ``--ttl`` seconds as a safety net).
"""

from __future__ import annotations

import argparse
import signal
import threading

from .driver_service import TaskService


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--ttl", type=float, default=120.0,
                   help="exit after this many seconds (orphan safety net)")
    args = p.parse_args()
    svc = TaskService(port=args.port)
    port = svc.start()
    print(f"HVD_TASK_SERVICE_PORT={port}", flush=True)
    # SIGTERM (driver teardown / preemption notice) ends the TTL wait
    # immediately and exits 0 — a probe service has nothing to drain, so
    # an interruptible wait is the whole graceful-shutdown story (the old
    # time.sleep forced the driver to wait out SIGKILL escalation).
    done = threading.Event()
    try:
        signal.signal(signal.SIGTERM, lambda *_: done.set())
    except ValueError:  # not the main thread (embedded use): TTL only
        pass
    done.wait(args.ttl)
    svc.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
