"""Per-host task-service entry point (parity: ``horovod/runner/task_fn.py``).

The driver launches ``python -m horovod_tpu.runner.task_fn`` on every host
during the pre-flight probe; the process prints its service port (the
driver reads it from the muxed output), serves NIC queries, and exits when
the driver is done (or after ``--ttl`` seconds as a safety net).
"""

from __future__ import annotations

import argparse
import time

from .driver_service import TaskService


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--ttl", type=float, default=120.0,
                   help="exit after this many seconds (orphan safety net)")
    args = p.parse_args()
    svc = TaskService(port=args.port)
    port = svc.start()
    print(f"HVD_TASK_SERVICE_PORT={port}", flush=True)
    time.sleep(args.ttl)
    svc.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
