"""Worker process execution: env plumbing, spawn, output multiplexing.

TPU-native analog of the reference's Gloo launch path
(``horovod/runner/gloo_run.py — launch_gloo``): per-rank env construction,
exec on each host (local fork or ssh), stdout/stderr multiplexed with rank
prefixes, first failure propagated by terminating the rest.

Divergences, by design: workers are one controller process per host; the env
block carries both the reference's world facts (``HOROVOD_RANK/SIZE/...``)
and the JAX multi-host bootstrap (``HOROVOD_COORDINATOR_ADDR`` →
``jax.distributed.initialize``). CPU dev-mode fabricates virtual devices per
host via ``XLA_FLAGS=--xla_force_host_platform_device_count``.
"""

from __future__ import annotations

import dataclasses
import os
import shlex
import signal
import subprocess
import sys
import threading
import time
from typing import Callable, Sequence

from .hosts import ProcessAssignment
from .network import is_local


def build_worker_env(
    assignment: ProcessAssignment,
    base_env: dict[str, str],
    rendezvous_addr: str,
    rendezvous_port: int,
    coordinator_addr: str,
    coordinator_port: int,
    cpu_mode: bool = False,
    extra_env: dict[str, str] | None = None,
    native_port: int | None = None,
) -> dict[str, str]:
    """The env contract between launcher and worker.

    Mirrors the reference's env block (``HOROVOD_RANK`` et al. written in
    ``launch_gloo``) and adds the JAX bootstrap triple. ``RuntimeConfig``
    (utils/env.py) parses the same names on the worker side.
    """
    a = assignment
    env = dict(base_env)
    env.update(
        {
            "HOROVOD_RANK": str(a.rank),
            "HOROVOD_SIZE": str(a.size),
            "HOROVOD_LOCAL_RANK": str(a.local_rank),
            "HOROVOD_LOCAL_SIZE": str(a.local_size),
            "HOROVOD_CROSS_RANK": str(a.cross_rank),
            "HOROVOD_CROSS_SIZE": str(a.cross_size),
            "HOROVOD_CONTROLLER": "tpu",
            "HOROVOD_RENDEZVOUS_ADDR": rendezvous_addr,
            "HOROVOD_RENDEZVOUS_PORT": str(rendezvous_port),
            # Reference-compat aliases (Gloo names; RuntimeConfig reads them).
            "HOROVOD_GLOO_RENDEZVOUS_ADDR": rendezvous_addr,
            "HOROVOD_GLOO_RENDEZVOUS_PORT": str(rendezvous_port),
            # JAX multi-host bootstrap (consumed by basics._maybe_init_distributed).
            "HOROVOD_COORDINATOR_ADDR": f"{coordinator_addr}:{coordinator_port}",
            "HOROVOD_NUM_PROCESSES": str(a.size),
            "HOROVOD_PROCESS_ID": str(a.rank),
        }
    )
    # The per-job HMAC secret rides the env block even when base_env is
    # empty (Ray/Spark task envs) — without it workers can't talk to an
    # authenticated rendezvous KV.
    job_secret = os.environ.get("HOROVOD_SECRET_KEY", "")
    if job_secret and "HOROVOD_SECRET_KEY" not in env:
        env["HOROVOD_SECRET_KEY"] = job_secret
    if native_port is not None:
        # Port for the native C++ runtime's control plane (libhvdrt star
        # coordinator on process 0's host) — makes hvd.join() and
        # host_hierarchical_allreduce reachable under hvdrun without any
        # hand-set env (the reference launcher's env block likewise makes
        # its Gloo control plane unconditionally reachable).
        env["HOROVOD_NATIVE_PORT"] = str(native_port)
    if cpu_mode:
        env["JAX_PLATFORMS"] = "cpu"
        flags = env.get("XLA_FLAGS", "")
        env["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={a.slots}".strip()
        )
    if extra_env:
        env.update(extra_env)
    return env


@dataclasses.dataclass
class WorkerProc:
    assignment: ProcessAssignment
    popen: subprocess.Popen
    pump: threading.Thread
    # Remote-termination facts (ssh-launched workers only): killing the
    # local ssh client does not kill the remote process tree, so
    # terminate_worker needs the host and a unique marker to pkill by.
    remote_host: str | None = None
    ssh_port: int | None = None
    kill_marker: str | None = None


def _pump_output(
    proc: subprocess.Popen,
    prefix: str,
    sink: Callable[[str], None],
) -> None:
    """Line-multiplex a worker's combined stdout/stderr with a rank prefix.

    Parity: the reference's ``MultiFile``/prefixed streaming in
    ``gloo_run``; rank prefixes like ``[1]<stdout>`` become ``[1] `` here.
    """
    assert proc.stdout is not None
    for raw in iter(proc.stdout.readline, b""):
        # \r\n: ssh -tt allocates a pty, which emits CRLF line endings.
        line = raw.decode(errors="replace").rstrip("\r\n")
        sink(f"{prefix}{line}")
    proc.stdout.close()


def launch_worker(
    assignment: ProcessAssignment,
    command: Sequence[str],
    env: dict[str, str],
    ssh_port: int | None = None,
    sink: Callable[[str], None] | None = None,
) -> WorkerProc:
    """Start one worker (local subprocess, or ssh for a remote host)."""
    sink = sink or (lambda s: print(s, flush=True))
    if is_local(assignment.hostname):
        popen = subprocess.Popen(
            list(command),
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            start_new_session=True,
        )
        worker = WorkerProc(assignment, popen, None)  # pump set below
    else:
        # Remote: ssh with the env inlined (the reference does the same —
        # env vars exported in the remote command line). The remote shell
        # records its PID — which (under ssh -tt, making it the session
        # and group leader) is the process-group id of the whole worker
        # tree — into a pidfile, so terminate_worker can kill the tree by
        # group. The pidfile lives in a per-user 0700 directory with an
        # unpredictable (random-token) name, and an EXIT trap removes it on
        # normal worker exit so /tmp doesn't accumulate stale files.
        import secrets

        marker = f"hvd_{assignment.rank}_{secrets.token_hex(8)}"
        exports = " ".join(
            f"export {k}={shlex.quote(v)};"
            for k, v in env.items()
            if k.startswith(("HOROVOD_", "JAX_", "XLA_", "TPU_", "PATH", "PYTHON"))
        )
        pidfile = _remote_pidfile(marker)
        # umask scoped to a subshell so worker-written files keep the
        # user's umask; [ -O ] rejects a pre-planted dir owned by another
        # local user (sticky /tmp lets anyone create /tmp/hvd-<victim>,
        # which would let them redirect the group-kill).
        remote_cmd = (
            f'(umask 077; mkdir -p "/tmp/hvd-$(id -un)"); '
            f'[ -O "/tmp/hvd-$(id -un)" ] || '
            f'{{ echo "hvdrun: /tmp/hvd-$(id -un) not owned by us" >&2; '
            f"exit 86; }}; "
            f"echo $$ > {pidfile}; trap 'rm -f {pidfile}' EXIT; "
            f"cd {shlex.quote(os.getcwd())} >/dev/null 2>&1; {exports} "
            + " ".join(shlex.quote(c) for c in command)
        )
        # -tt forces a remote pty: when this ssh client dies, the pty closes
        # and the remote process group gets SIGHUP — so even an unclean
        # launcher death doesn't leave remote workers running.
        ssh_cmd = ["ssh", "-tt", "-o", "StrictHostKeyChecking=no"]
        if ssh_port:
            ssh_cmd += ["-p", str(ssh_port)]
        ssh_cmd += [assignment.hostname, remote_cmd]
        popen = subprocess.Popen(
            ssh_cmd,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            start_new_session=True,
        )
        worker = WorkerProc(assignment, popen, None,
                            remote_host=assignment.hostname,
                            ssh_port=ssh_port, kill_marker=marker)
    pump = threading.Thread(
        target=_pump_output,
        args=(popen, f"[{assignment.rank}] ", sink),
        name=f"hvd-pump-{assignment.rank}",
        daemon=True,
    )
    worker.pump = pump
    pump.start()
    return worker


def wait_for_workers(
    workers: list[WorkerProc],
    poll_interval: float = 0.1,
    on_failure: str = "kill",
) -> int:
    """Wait for all workers; on first non-zero exit, terminate the rest.

    Returns the first failing exit code, or 0. Parity: the reference
    propagates the first failure and kills remaining workers so a crashed
    rank cannot hang the job (the surviving ranks would block in collectives
    forever — the exact stall the stall inspector warns about).
    """
    pending = {w.assignment.rank: w for w in workers}
    first_rc = 0
    while pending:
        done = [r for r, w in pending.items() if w.popen.poll() is not None]
        for r in done:
            w = pending.pop(r)
            rc = w.popen.returncode
            if rc != 0 and first_rc == 0:
                first_rc = rc if rc is not None else 1
                if on_failure == "kill":
                    terminate_workers(list(pending.values()))
        if not done:
            time.sleep(poll_interval)
    for w in workers:
        w.pump.join(timeout=5)
    return first_rc


def _remote_pidfile(marker: str) -> str:
    # $(id -un) expands REMOTELY: a per-user directory (created 0700 by the
    # launch shell's umask) so another local user can't pre-plant a symlink
    # or rewrite the pidfile to aim the group-kill at an arbitrary process.
    return f'"/tmp/hvd-$(id -un)/{marker}.pid"'


def _remote_kill(w: WorkerProc, timeout_s: float = 15.0) -> None:
    """Kill an ssh-launched worker's REMOTE process tree via its pidfile.

    The local ssh client dying only closes the pty (SIGHUP — which a
    nohup'ing or signal-ignoring worker survives), so we explicitly signal
    the remote process group recorded at launch (kill -- -PID falls back to
    the single PID if the group signal fails). TERM is sent synchronously;
    the KILL escalation runs as a detached remote background job so this
    call doesn't block 2s per worker (elastic rescales terminate many).
    """
    pidfile = _remote_pidfile(w.kill_marker)
    script = (
        f"p=$(cat {pidfile} 2>/dev/null) && "
        "{ kill -TERM -- -$p 2>/dev/null || kill -TERM $p 2>/dev/null; "
        "(sleep 2; kill -KILL -- -$p 2>/dev/null || kill -KILL $p 2>/dev/null) "
        "</dev/null >/dev/null 2>&1 & "
        f"}}; rm -f {pidfile}"
    )
    cmd = ["ssh", "-o", "StrictHostKeyChecking=no", "-o", "BatchMode=yes"]
    if w.ssh_port:
        cmd += ["-p", str(w.ssh_port)]
    cmd += [w.remote_host, script]
    try:
        subprocess.run(cmd, timeout=timeout_s, capture_output=True)
    except (subprocess.TimeoutExpired, OSError):
        pass  # host unreachable: nothing more we can do


def drain_worker(w: WorkerProc, timeout_s: float = 15.0) -> None:
    """Deliver SIGTERM — the graceful-drain signal — to a worker,
    REMOTE process tree included, with no KILL escalation (the caller
    owns the grace wait and any escalation).

    A raw local ``killpg`` cannot drain an ssh-launched worker: it
    signals only the local ssh client, whose death closes the pty and
    delivers SIGHUP — not SIGTERM — to the remote tree (the
    :func:`_remote_kill` caveat), so the worker's drain handler never
    runs and the final commit never lands. Remote workers get an
    explicit ``kill -TERM`` of the pidfile-recorded group instead; the
    pidfile is left in place for the eventual :func:`terminate_worker`.
    """
    if w.remote_host and w.kill_marker:
        pidfile = _remote_pidfile(w.kill_marker)
        script = (
            f"p=$(cat {pidfile} 2>/dev/null) && "
            "{ kill -TERM -- -$p 2>/dev/null || kill -TERM $p 2>/dev/null; }"
        )
        cmd = ["ssh", "-o", "StrictHostKeyChecking=no", "-o",
               "BatchMode=yes"]
        if w.ssh_port:
            cmd += ["-p", str(w.ssh_port)]
        cmd += [w.remote_host, script]
        try:
            subprocess.run(cmd, timeout=timeout_s, capture_output=True)
        except (subprocess.TimeoutExpired, OSError):
            pass  # host unreachable: the caller's grace/escalation owns it
        return
    try:
        os.killpg(os.getpgid(w.popen.pid), signal.SIGTERM)
    except (ProcessLookupError, PermissionError):
        pass


def terminate_worker(w: WorkerProc, grace_s: float = 5.0) -> None:
    """SIGTERM the worker's process group, escalate to SIGKILL.

    For remote (ssh) workers this kills the remote process tree too: the
    explicit pidfile-based group kill runs even when the local ssh client
    already exited — a dropped connection leaves the remote worker running
    (SIGHUP-ignoring/nohup'd processes survive pty teardown), which is
    exactly the leak this path exists to close.
    """
    if w.remote_host and w.kill_marker and not getattr(w, "_remote_killed",
                                                      False):
        w._remote_killed = True
        _remote_kill(w)
    if w.popen.poll() is not None:
        return
    try:
        os.killpg(os.getpgid(w.popen.pid), signal.SIGTERM)
    except (ProcessLookupError, PermissionError):
        return
    deadline = time.time() + grace_s
    while time.time() < deadline:
        if w.popen.poll() is not None:
            return
        time.sleep(0.05)
    try:
        os.killpg(os.getpgid(w.popen.pid), signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        pass


def terminate_workers(workers: Sequence[WorkerProc],
                      grace_s: float = 5.0) -> None:
    """Terminate many workers concurrently.

    Remote terminations each pay an ssh round-trip; a serial loop over a
    large elastic rescale would block the driver (and every surviving rank
    sitting in a collective) for its sum — fan out instead. Remote workers
    whose local ssh client already exited still need the remote kill.
    """
    workers = [
        w for w in workers
        if w.popen.poll() is None or (w.remote_host and w.kill_marker)
    ]
    if not workers:
        return
    if len(workers) == 1:
        terminate_worker(workers[0], grace_s)
        return
    threads = [
        threading.Thread(target=terminate_worker, args=(w, grace_s),
                         daemon=True)
        for w in workers
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=grace_s + 20.0)


def python_command(script_and_args: Sequence[str]) -> list[str]:
    """Prefix a user command with the current interpreter when it's a .py."""
    cmd = list(script_and_args)
    if cmd and cmd[0].endswith(".py"):
        return [sys.executable] + cmd
    return cmd
