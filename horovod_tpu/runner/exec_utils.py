"""Worker process execution: env plumbing, spawn, output multiplexing.

TPU-native analog of the reference's Gloo launch path
(``horovod/runner/gloo_run.py — launch_gloo``): per-rank env construction,
exec on each host (local fork or ssh), stdout/stderr multiplexed with rank
prefixes, first failure propagated by terminating the rest.

Divergences, by design: workers are one controller process per host; the env
block carries both the reference's world facts (``HOROVOD_RANK/SIZE/...``)
and the JAX multi-host bootstrap (``HOROVOD_COORDINATOR_ADDR`` →
``jax.distributed.initialize``). CPU dev-mode fabricates virtual devices per
host via ``XLA_FLAGS=--xla_force_host_platform_device_count``.
"""

from __future__ import annotations

import dataclasses
import os
import shlex
import signal
import subprocess
import sys
import threading
import time
from typing import Callable, Sequence

from .hosts import ProcessAssignment
from .network import is_local


def build_worker_env(
    assignment: ProcessAssignment,
    base_env: dict[str, str],
    rendezvous_addr: str,
    rendezvous_port: int,
    coordinator_addr: str,
    coordinator_port: int,
    cpu_mode: bool = False,
    extra_env: dict[str, str] | None = None,
) -> dict[str, str]:
    """The env contract between launcher and worker.

    Mirrors the reference's env block (``HOROVOD_RANK`` et al. written in
    ``launch_gloo``) and adds the JAX bootstrap triple. ``RuntimeConfig``
    (utils/env.py) parses the same names on the worker side.
    """
    a = assignment
    env = dict(base_env)
    env.update(
        {
            "HOROVOD_RANK": str(a.rank),
            "HOROVOD_SIZE": str(a.size),
            "HOROVOD_LOCAL_RANK": str(a.local_rank),
            "HOROVOD_LOCAL_SIZE": str(a.local_size),
            "HOROVOD_CROSS_RANK": str(a.cross_rank),
            "HOROVOD_CROSS_SIZE": str(a.cross_size),
            "HOROVOD_CONTROLLER": "tpu",
            "HOROVOD_RENDEZVOUS_ADDR": rendezvous_addr,
            "HOROVOD_RENDEZVOUS_PORT": str(rendezvous_port),
            # Reference-compat aliases (Gloo names; RuntimeConfig reads them).
            "HOROVOD_GLOO_RENDEZVOUS_ADDR": rendezvous_addr,
            "HOROVOD_GLOO_RENDEZVOUS_PORT": str(rendezvous_port),
            # JAX multi-host bootstrap (consumed by basics._maybe_init_distributed).
            "HOROVOD_COORDINATOR_ADDR": f"{coordinator_addr}:{coordinator_port}",
            "HOROVOD_NUM_PROCESSES": str(a.size),
            "HOROVOD_PROCESS_ID": str(a.rank),
        }
    )
    if cpu_mode:
        env["JAX_PLATFORMS"] = "cpu"
        flags = env.get("XLA_FLAGS", "")
        env["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={a.slots}".strip()
        )
    if extra_env:
        env.update(extra_env)
    return env


@dataclasses.dataclass
class WorkerProc:
    assignment: ProcessAssignment
    popen: subprocess.Popen
    pump: threading.Thread


def _pump_output(
    proc: subprocess.Popen,
    prefix: str,
    sink: Callable[[str], None],
) -> None:
    """Line-multiplex a worker's combined stdout/stderr with a rank prefix.

    Parity: the reference's ``MultiFile``/prefixed streaming in
    ``gloo_run``; rank prefixes like ``[1]<stdout>`` become ``[1] `` here.
    """
    assert proc.stdout is not None
    for raw in iter(proc.stdout.readline, b""):
        line = raw.decode(errors="replace").rstrip("\n")
        sink(f"{prefix}{line}")
    proc.stdout.close()


def launch_worker(
    assignment: ProcessAssignment,
    command: Sequence[str],
    env: dict[str, str],
    ssh_port: int | None = None,
    sink: Callable[[str], None] | None = None,
) -> WorkerProc:
    """Start one worker (local subprocess, or ssh for a remote host)."""
    sink = sink or (lambda s: print(s, flush=True))
    if is_local(assignment.hostname):
        popen = subprocess.Popen(
            list(command),
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            start_new_session=True,
        )
    else:
        # Remote: ssh with the env inlined (the reference does the same —
        # env vars exported in the remote command line).
        exports = " ".join(
            f"export {k}={shlex.quote(v)};"
            for k, v in env.items()
            if k.startswith(("HOROVOD_", "JAX_", "XLA_", "TPU_", "PATH", "PYTHON"))
        )
        remote_cmd = f"cd {shlex.quote(os.getcwd())} >/dev/null 2>&1; {exports} " + " ".join(
            shlex.quote(c) for c in command
        )
        ssh_cmd = ["ssh", "-o", "StrictHostKeyChecking=no"]
        if ssh_port:
            ssh_cmd += ["-p", str(ssh_port)]
        ssh_cmd += [assignment.hostname, remote_cmd]
        popen = subprocess.Popen(
            ssh_cmd,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            start_new_session=True,
        )
    pump = threading.Thread(
        target=_pump_output,
        args=(popen, f"[{assignment.rank}] ", sink),
        name=f"hvd-pump-{assignment.rank}",
        daemon=True,
    )
    pump.start()
    return WorkerProc(assignment, popen, pump)


def wait_for_workers(
    workers: list[WorkerProc],
    poll_interval: float = 0.1,
    on_failure: str = "kill",
) -> int:
    """Wait for all workers; on first non-zero exit, terminate the rest.

    Returns the first failing exit code, or 0. Parity: the reference
    propagates the first failure and kills remaining workers so a crashed
    rank cannot hang the job (the surviving ranks would block in collectives
    forever — the exact stall the stall inspector warns about).
    """
    pending = {w.assignment.rank: w for w in workers}
    first_rc = 0
    while pending:
        done = [r for r, w in pending.items() if w.popen.poll() is not None]
        for r in done:
            w = pending.pop(r)
            rc = w.popen.returncode
            if rc != 0 and first_rc == 0:
                first_rc = rc if rc is not None else 1
                if on_failure == "kill":
                    for other in pending.values():
                        terminate_worker(other)
        if not done:
            time.sleep(poll_interval)
    for w in workers:
        w.pump.join(timeout=5)
    return first_rc


def terminate_worker(w: WorkerProc, grace_s: float = 5.0) -> None:
    """SIGTERM the worker's process group, escalate to SIGKILL."""
    if w.popen.poll() is not None:
        return
    try:
        os.killpg(os.getpgid(w.popen.pid), signal.SIGTERM)
    except (ProcessLookupError, PermissionError):
        return
    deadline = time.time() + grace_s
    while time.time() < deadline:
        if w.popen.poll() is not None:
            return
        time.sleep(0.05)
    try:
        os.killpg(os.getpgid(w.popen.pid), signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        pass


def python_command(script_and_args: Sequence[str]) -> list[str]:
    """Prefix a user command with the current interpreter when it's a .py."""
    cmd = list(script_and_args)
    if cmd and cmd[0].endswith(".py"):
        return [sys.executable] + cmd
    return cmd
