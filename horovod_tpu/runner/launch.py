"""``hvdrun`` — the launcher CLI.

TPU-native re-design of the reference's ``horovodrun``
(``horovod/runner/launch.py — parse_args(), run_commandline()``). The flag
surface keeps the reference's names where the concept survives; every runtime
flag is translated into the corresponding ``HOROVOD_*`` env var for the
children (the same CLI→env→config precedence contract, see
``horovod_tpu/utils/env.py``).

Differences, by design:
- workers are one controller process per host (JAX SPMD), so ``-np`` is the
  number of processes; per-chip ranks come from the device world at init.
- there is no MPI path: the launch substrate is always
  rendezvous-KV + (local fork | ssh), the analog of the reference's Gloo path.
- ``--cpu-mode`` runs the whole job on virtual CPU devices (dev/CI parity
  with the reference's CPU/Gloo mode).

Usage::

    hvdrun -np 2 -H host1:4,host2:4 python train.py
    hvdrun -np 2 --cpu-mode python train.py        # 2 local procs
    hvdrun -np 2 --min-np 1 --max-np 4 \
        --host-discovery-script ./discover.sh python train.py   # elastic
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import sys

from . import network
from .exec_utils import (
    build_worker_env,
    launch_worker,
    python_command,
    wait_for_workers,
)
from .hosts import (
    HostInfo,
    get_host_assignments,
    parse_hostfile,
    parse_hosts,
)
from .http.kv_server import RendezvousServer
from .schedulers import detect_scheduler_hosts


@dataclasses.dataclass
class Settings:
    """Resolved launch settings (reference: ``horovod/runner/common/util/
    settings.py — Settings``)."""

    num_proc: int
    hosts: list[HostInfo]
    command: list[str]
    cpu_mode: bool = False
    ssh_port: int | None = None
    start_timeout: float = 30.0
    verbose: bool = False
    env: dict[str, str] = dataclasses.field(default_factory=dict)
    network_probe: bool = False
    # Elastic:
    elastic: bool = False
    min_np: int | None = None
    max_np: int | None = None
    discovery_script: str | None = None
    elastic_timeout: float = 600.0


def parse_args(argv: list[str] | None = None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="hvdrun",
        description="Launch a horovod_tpu job across TPU VM hosts.",
        allow_abbrev=False,
    )
    p.add_argument("-v", "--version", action="store_true", help="print version")
    p.add_argument("-np", "--num-proc", type=int, default=None,
                   help="number of worker processes (one per host)")
    p.add_argument("-H", "--hosts", default=None,
                   help="comma separated host:slots (slots = chips per host)")
    p.add_argument("--hostfile", default=None,
                   help="hostfile path (host slots=N per line)")
    p.add_argument("--cpu-mode", action="store_true",
                   help="run on virtual CPU devices (dev/CI mode); slots = "
                        "virtual devices per process")
    p.add_argument("--ssh-port", type=int, default=None)
    p.add_argument("--network-probe", action="store_true",
                   help="pre-flight NIC probe: start a task service per "
                        "host, intersect interfaces, and advertise "
                        "addresses on the common network (multi-NIC hosts)")
    p.add_argument("--start-timeout", type=float,
                   default=float(os.environ.get("HOROVOD_START_TIMEOUT", 30)))
    p.add_argument("--verbose", action="store_true")
    p.add_argument("--check-build", action="store_true",
                   help="print framework capabilities and exit")
    # Runtime knobs → env for children (names match the reference CLI).
    p.add_argument("--fusion-threshold-mb", type=int, default=None)
    p.add_argument("--cycle-time-ms", type=float, default=None)
    p.add_argument("--cache-capacity", type=int, default=None)
    p.add_argument("--timeline-filename", default=None)
    p.add_argument("--timeline-mark-cycles", action="store_true")
    p.add_argument("--autotune", action="store_true")
    p.add_argument("--autotune-log-file", default=None)
    p.add_argument("--hierarchical-allreduce", action="store_true")
    p.add_argument("--log-level", default=None,
                   choices=["trace", "debug", "info", "warning", "error", "fatal"])
    p.add_argument("--stall-check-time", type=float, default=None)
    p.add_argument("--stall-shutdown-time", type=float, default=None)
    # Elastic.
    p.add_argument("--min-np", type=int, default=None)
    p.add_argument("--max-np", type=int, default=None)
    p.add_argument("--host-discovery-script", default=None,
                   help="script printing 'host:slots' per line; enables "
                        "elastic mode")
    p.add_argument("--elastic-timeout", type=float,
                   default=float(os.environ.get("HOROVOD_ELASTIC_TIMEOUT", 600)))
    p.add_argument("--config-file", default=None,
                   help="YAML of long-form flag defaults (CLI wins); "
                        "parity: horovodrun --config-file")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="training command (python train.py ...)")
    args = p.parse_args(argv)
    if args.config_file:
        _apply_config_file(p, args, argv)
    return args


def _apply_config_file(parser: argparse.ArgumentParser,
                       args: argparse.Namespace,
                       argv: list[str] | None) -> None:
    """YAML keys are long flag names (dashes or underscores); values fill
    any flag the command line did NOT set explicitly — the reference's
    config-file precedence (CLI > config file > defaults)."""
    import yaml

    with open(args.config_file) as f:
        config = yaml.safe_load(f) or {}
    if not isinstance(config, dict):
        raise SystemExit(f"--config-file {args.config_file}: expected a "
                         "mapping of flag: value")
    # Map EVERY option string (short and long) to its argparse dest so
    # explicit CLI flags always win, e.g. -H -> hosts, -np -> num_proc.
    opt_to_dest = {
        opt: a.dest
        for a in parser._actions
        for opt in a.option_strings
    }
    given = set()
    for tok in (argv if argv is not None else sys.argv[1:]):
        if tok.startswith("-") and not tok[1:2].isdigit():
            flag = tok.split("=", 1)[0]
            if flag in opt_to_dest:
                given.add(opt_to_dest[flag])
    valid = {a.dest for a in parser._actions}
    for key, value in config.items():
        dest = key.replace("-", "_")
        if dest not in valid:
            raise SystemExit(
                f"--config-file: unknown option {key!r}; valid: "
                + ", ".join(sorted(v for v in valid if v != "help"))
            )
        if dest in given:
            continue  # explicit CLI wins
        setattr(args, dest, value)


def args_to_env(args: argparse.Namespace) -> dict[str, str]:
    """CLI flags → HOROVOD_* env for children (the reference's contract)."""
    env: dict[str, str] = {}
    if args.fusion_threshold_mb is not None:
        env["HOROVOD_FUSION_THRESHOLD"] = str(args.fusion_threshold_mb * 1024 * 1024)
    if args.cycle_time_ms is not None:
        env["HOROVOD_CYCLE_TIME"] = str(args.cycle_time_ms)
    if args.cache_capacity is not None:
        env["HOROVOD_CACHE_CAPACITY"] = str(args.cache_capacity)
    if args.timeline_filename:
        env["HOROVOD_TIMELINE"] = args.timeline_filename
    if args.timeline_mark_cycles:
        env["HOROVOD_TIMELINE_MARK_CYCLES"] = "1"
    if args.autotune:
        env["HOROVOD_AUTOTUNE"] = "1"
    if args.autotune_log_file:
        env["HOROVOD_AUTOTUNE_LOG"] = args.autotune_log_file
    if args.hierarchical_allreduce:
        env["HOROVOD_HIERARCHICAL_ALLREDUCE"] = "1"
    if args.log_level:
        env["HOROVOD_LOG_LEVEL"] = args.log_level
    if args.stall_check_time is not None:
        env["HOROVOD_STALL_CHECK_TIME"] = str(args.stall_check_time)
    if args.stall_shutdown_time is not None:
        env["HOROVOD_STALL_SHUTDOWN_TIME"] = str(args.stall_shutdown_time)
    return env


def settings_from_args(args: argparse.Namespace) -> Settings:
    if args.hosts and args.hostfile:
        raise SystemExit("specify either -H/--hosts or --hostfile, not both")
    command = python_command([c for c in args.command if c != "--"])
    if not command:
        raise SystemExit("no training command given")
    elastic = args.host_discovery_script is not None
    if elastic:
        # Reference semantics: -np is the starting/target world size;
        # min/max default to it when not given explicitly.
        hosts = []  # discovered at runtime
        np = args.num_proc or (args.min_np or 1)
        if args.min_np is None:
            args.min_np = np
        if args.max_np is None and args.num_proc is not None:
            args.max_np = args.num_proc
    else:
        if args.hosts:
            hosts = parse_hosts(args.hosts)
        elif args.hostfile:
            hosts = parse_hostfile(args.hostfile)
        elif (
            not args.cpu_mode
            and (scheduler_hosts := detect_scheduler_hosts()) is not None
        ):
            # Inside an LSF/Slurm allocation with no -H/--hostfile: use the
            # allocation's hosts (parity: horovod/runner/util/lsf.py
            # auto-detection). Detection only runs when no explicit hosts
            # were given (explicit flags must win even over a malformed
            # allocation env), and --cpu-mode keeps its local fan-out.
            hosts = scheduler_hosts
        else:
            n = args.num_proc or 1
            hosts = [HostInfo("localhost", 1)]
            if n > 1:
                if not args.cpu_mode:
                    raise SystemExit(
                        "-np > 1 without -H/--hostfile requires --cpu-mode "
                        "(local multi-process is a CPU dev-mode feature; on "
                        "TPU each host runs one process)"
                    )
                hosts = [HostInfo("localhost", 1) for _ in range(n)]
        np = args.num_proc or len(hosts)
        if np > len(hosts):
            raise SystemExit(
                f"-np {np} exceeds {len(hosts)} host(s); one process per host"
            )
    return Settings(
        num_proc=np,
        hosts=hosts,
        command=command,
        cpu_mode=args.cpu_mode,
        ssh_port=args.ssh_port,
        network_probe=args.network_probe,
        start_timeout=args.start_timeout,
        verbose=args.verbose,
        env=args_to_env(args),
        elastic=elastic,
        min_np=args.min_np,
        max_np=args.max_np,
        discovery_script=args.host_discovery_script,
        elastic_timeout=args.elastic_timeout,
    )


def _network_probe(hosts, ssh_port, sink) -> dict[str, str] | None:
    """Pre-flight NIC probe (parity: driver_service._driver_fn): start a
    task service per host, read its port from the muxed output, intersect
    interfaces. Returns {hostname: address-on-common-network} or None.
    """
    import re
    import time

    from .driver_service import probe_cluster
    from .exec_utils import launch_worker, terminate_workers
    from .hosts import get_host_assignments as _assign

    ports: dict[str, int] = {}
    lines: list[str] = []

    def capture(line: str) -> None:
        lines.append(line)
        if sink:
            sink(line)

    # One task service per UNIQUE host (duplicate hostnames — local
    # cpu-mode — would make the port wait unsatisfiable).
    unique = []
    seen = set()
    for h in hosts:
        if h.hostname not in seen:
            seen.add(h.hostname)
            unique.append(type(h)(h.hostname, 1))
    assignments = _assign(unique)
    workers = [
        launch_worker(
            a, [sys.executable, "-m", "horovod_tpu.runner.task_fn"],
            dict(os.environ), ssh_port=ssh_port, sink=capture,
        )
        for a in assignments
    ]
    try:
        deadline = time.time() + 30.0
        while len(ports) < len(assignments) and time.time() < deadline:
            for line in list(lines):
                m = re.search(r"\[(\d+)\] HVD_TASK_SERVICE_PORT=(\d+)", line)
                if m:
                    rank = int(m.group(1))
                    ports[assignments[rank].hostname] = int(m.group(2))
            time.sleep(0.05)
        if len(ports) < len(assignments):
            return None  # probe inconclusive: fall back to defaults
        _, addrs = probe_cluster({
            h: (h if h != "localhost" else "127.0.0.1", p)
            for h, p in ports.items()
        })
        return addrs
    except Exception:
        return None
    finally:
        terminate_workers(workers)


def run_static(settings: Settings, sink=None) -> int:
    """The static (non-elastic) launch path.

    Parity: ``gloo_run`` — start rendezvous, assign ranks, exec workers,
    multiplex output, propagate first failure.
    """
    # Local multi-process: assignments replicate localhost.
    if settings.hosts and all(h.hostname == "localhost" for h in settings.hosts):
        hosts = settings.hosts[: settings.num_proc]
    else:
        hosts = settings.hosts
    assignments = get_host_assignments(hosts, settings.num_proc)

    # Per-job HMAC secret FIRST: the probe's task services and the KV
    # server snapshot their key at construction, and workers inherit it
    # through the env block (parity: the reference's secret-authenticated
    # driver/task services).
    from . import secret as _secret

    os.environ.setdefault(_secret.ENV_KEY, _secret.make_secret_key())
    probed = None
    if settings.network_probe:
        probed = _network_probe(hosts, settings.ssh_port, sink)
    server = RendezvousServer()
    port = server.start()
    hostnames = [h.hostname for h in hosts]
    kv_addr = network.driver_addr(hostnames)
    coord_addr = network.coordinator_addr(hostnames)
    if probed and hostnames and hostnames[0] in probed:
        # The probe's answer IS the coordinator address (rank 0's address
        # on the network every host shares) — hostnames[0] may resolve to
        # an unreachable management NIC on multi-NIC TPU VMs.
        coord_addr = probed[hostnames[0]]
    coord_port = network.free_port()
    native_port = network.free_port()
    try:
        workers = []
        for a in assignments:
            env = build_worker_env(
                a,
                base_env=dict(os.environ),
                rendezvous_addr=kv_addr,
                rendezvous_port=port,
                coordinator_addr=coord_addr,
                coordinator_port=coord_port,
                cpu_mode=settings.cpu_mode,
                extra_env=settings.env,
                native_port=native_port,
            )
            workers.append(
                launch_worker(
                    a, settings.command, env,
                    ssh_port=settings.ssh_port, sink=sink,
                )
            )
        return wait_for_workers(workers)
    finally:
        server.stop()


def check_build() -> str:
    from .. import __version__

    lines = [
        f"horovod_tpu v{__version__}",
        "",
        "Available frameworks:",
        "    [X] JAX / Flax",
        "    [X] NumPy (eager collectives)",
        "",
        "Available controllers:",
        "    [X] rendezvous-KV (TCP)",
        "",
        "Available collective backends:",
        "    [X] XLA:TPU (ICI/DCN)",
        "    [X] XLA:CPU (dev mode)",
        "",
        "Available features:",
        "    [X] elastic",
        "    [X] process sets",
        "    [X] grouped allreduce / tensor fusion",
        "    [X] adasum",
        "    [X] timeline / stall inspector",
    ]
    return "\n".join(lines)


def run_commandline(argv: list[str] | None = None) -> int:
    args = parse_args(argv)
    if args.version:
        from .. import __version__

        print(__version__)
        return 0
    if args.check_build:
        print(check_build())
        return 0
    settings = settings_from_args(args)
    if settings.elastic:
        from .elastic.driver import run_elastic

        return run_elastic(settings)
    return run_static(settings)


def main() -> None:
    sys.exit(run_commandline())


if __name__ == "__main__":
    main()
