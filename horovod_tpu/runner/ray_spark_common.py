"""Shared worker-env construction for cluster integrations (Ray/Spark)."""

from __future__ import annotations

from .exec_utils import build_worker_env
from .hosts import HostInfo, get_host_assignments


def task_env(rank: int, size: int, kv_addr: str, kv_port: int,
             coord_addr: str, coord_port: int,
             cpu_mode: bool = False,
             native_port: int | None = None) -> dict[str, str]:
    """The launcher env contract for an externally placed worker (one task
    per host): same keys ``hvdrun`` writes (see exec_utils)."""
    hosts = [HostInfo(f"host-{i}", 1) for i in range(size)]
    assignment = get_host_assignments(hosts)[rank]
    return build_worker_env(
        assignment,
        base_env={},
        rendezvous_addr=kv_addr,
        rendezvous_port=kv_port,
        coordinator_addr=coord_addr,
        coordinator_port=coord_port,
        cpu_mode=cpu_mode,
        native_port=native_port,
    )
