"""Shared-secret message authentication for launcher-side services.

Parity: ``horovod/runner/common/util/secret.py`` — the reference HMAC-signs
every driver↔task message with a per-job secret so a port scanner on the
cluster network cannot inject control messages. Same contract here:

- the launcher generates a per-job secret (:func:`make_secret_key`) and
  ships it to workers via ``HOROVOD_SECRET_KEY`` in the env block;
- services verify an HMAC-SHA256 tag over each message body;
- comparison is constant-time (``hmac.compare_digest``).
"""

from __future__ import annotations

import hmac
import os
import secrets as _secrets

ENV_KEY = "HOROVOD_SECRET_KEY"
DIGESTMOD = "sha256"


def make_secret_key() -> str:
    return _secrets.token_hex(32)


def current_key() -> bytes | None:
    """The job secret from env, or None (unauthenticated dev mode)."""
    val = os.environ.get(ENV_KEY, "")
    return val.encode() if val else None


def sign(body: bytes, key: bytes | None = None) -> str:
    key = key if key is not None else current_key()
    if key is None:
        return ""
    return hmac.new(key, body, DIGESTMOD).hexdigest()


def verify(body: bytes, tag: str, key: bytes | None = None) -> bool:
    key = key if key is not None else current_key()
    if key is None:
        return True  # no secret configured: open mode (dev/back-compat)
    if not tag:
        return False
    return hmac.compare_digest(hmac.new(key, body, DIGESTMOD).hexdigest(),
                               tag)
