"""``python -m horovod_tpu.runner`` == the ``hvdrun`` console script."""

from .launch import main

main()
