"""Pre-flight cluster probe: per-host NIC discovery + interface intersection.

Parity: ``horovod/runner/driver/driver_service.py`` (``_driver_fn`` — start
a task service on every host, collect each host's network interfaces,
compute the common routable set) + ``common/service/task_service.py``.
The reference runs this before every multi-host launch so Gloo/NCCL bind
the right NICs; here the result picks the address the rendezvous KV, the
jax.distributed coordinator, and the native runtime's control plane
advertise — on multi-NIC TPU VMs (DCN + management networks) the first
routable address is not always the mutually reachable one.

Task services speak the same HMAC-authenticated HTTP as the rendezvous KV
(``horovod_tpu.runner.secret``).
"""

from __future__ import annotations

import ipaddress
import json
import socket
import subprocess
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.request import Request, urlopen

from . import secret as _secret

AUTH_HEADER = "X-Hvd-Auth"


def list_interfaces() -> list[dict]:
    """This host's up, non-loopback IPv4 interfaces:
    ``[{name, address, prefixlen}]``. Prefers ``ip -j addr`` (iproute2);
    falls back to the resolver's single primary address."""
    try:
        out = subprocess.run(
            ["ip", "-j", "addr"], capture_output=True, timeout=5, check=True
        ).stdout
        result = []
        for link in json.loads(out):
            if "LOOPBACK" in link.get("flags", []):
                continue
            if link.get("operstate") not in ("UP", "UNKNOWN"):
                continue
            for addr in link.get("addr_info", []):
                if addr.get("family") != "inet":
                    continue
                result.append({
                    "name": link.get("ifname", "?"),
                    "address": addr["local"],
                    "prefixlen": int(addr.get("prefixlen", 32)),
                })
        if result:
            return result
    except Exception:
        pass
    try:
        addr = socket.gethostbyname(socket.gethostname())
        return [{"name": "default", "address": addr, "prefixlen": 24}]
    except OSError:
        return []


def common_routable_interfaces(
    per_host: dict[str, list[dict]],
) -> tuple[list[str], dict[str, str]]:
    """Intersect hosts' interface networks.

    Returns ``(common_network_cidrs, {host: address_on_first_common})`` —
    the networks present on EVERY host, and each host's address on the
    first (most specific) one. Raises when no common network exists.
    """
    nets_per_host: dict[str, dict] = {}
    for host, ifaces in per_host.items():
        nets = {}
        for i in ifaces:
            net = ipaddress.ip_network(
                f"{i['address']}/{i['prefixlen']}", strict=False
            )
            nets[str(net)] = i["address"]
        nets_per_host[host] = nets
    if not nets_per_host:
        raise ValueError("no hosts probed")
    common = set.intersection(*[set(n) for n in nets_per_host.values()])
    if not common:
        raise RuntimeError(
            "no common network across hosts; interfaces per host: "
            + json.dumps({h: sorted(n) for h, n in nets_per_host.items()})
        )
    ordered = sorted(
        common, key=lambda c: -ipaddress.ip_network(c).prefixlen
    )
    first = ordered[0]
    return ordered, {h: nets_per_host[h][first] for h in nets_per_host}


class _TaskHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # noqa: D102
        pass

    def do_GET(self):  # noqa: N802
        tag = self.headers.get(AUTH_HEADER, "")
        body_sig = b"GET\n" + self.path.encode() + b"\n"
        if not _secret.verify(body_sig, tag,
                              key=self.server.secret):  # type: ignore[attr-defined]
            return self._reply(403, b"bad auth tag")
        if self.path == "/interfaces":
            return self._reply(
                200, json.dumps(list_interfaces()).encode()
            )
        if self.path == "/ping":
            return self._reply(200, b"pong")
        self._reply(404, b"")

    def _reply(self, code: int, body: bytes):
        self.send_response(code)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


class TaskService:
    """Per-host probe responder (parity: HorovodRunTaskService)."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0):
        self._httpd = ThreadingHTTPServer((host, port), _TaskHandler)
        self._httpd.secret = _secret.current_key()  # type: ignore[attr-defined]
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> int:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="hvd-task-svc", daemon=True
        )
        self._thread.start()
        return self.port

    def stop(self) -> None:
        self._httpd.shutdown()
        if self._thread:
            self._thread.join(timeout=5)
        self._httpd.server_close()


def _signed_get(base: str, path: str, timeout: float = 10.0) -> bytes:
    req = Request(f"{base}{path}")
    tag = _secret.sign(b"GET\n" + path.encode() + b"\n")
    if tag:
        req.add_header(AUTH_HEADER, tag)
    with urlopen(req, timeout=timeout) as r:
        return r.read()


def probe_host(addr: str, port: int, timeout: float = 10.0) -> list[dict]:
    """Ask one task service for its interfaces."""
    return json.loads(_signed_get(f"http://{addr}:{port}", "/interfaces",
                                  timeout))


def probe_cluster(
    endpoints: dict[str, tuple[str, int]], timeout: float = 10.0,
) -> tuple[list[str], dict[str, str]]:
    """Probe every host's task service and intersect.

    ``endpoints``: {hostname: (reachable_addr, task_service_port)}.
    Returns ``common_routable_interfaces`` of the collected views.
    """
    views = {
        host: probe_host(addr, port, timeout)
        for host, (addr, port) in endpoints.items()
    }
    return common_routable_interfaces(views)
