"""The launcher / orchestration layer (SURVEY.md §3.4).

TPU-native re-design of ``horovod/runner/``: the ``hvdrun`` CLI
(``launch.py``), host parsing + one-process-per-host rank assignment
(``hosts.py``), the rendezvous KV server (``http/kv_server.py``), worker
exec with output multiplexing (``exec_utils.py``), and the elastic driver
(``elastic/``).

Programmatic entry (parity: ``horovod.run()``)::

    from horovod_tpu.runner import run
    run(["python", "train.py"], np=2, cpu_mode=True)
"""

from __future__ import annotations

from .hosts import HostInfo, get_host_assignments, parse_hostfile, parse_hosts  # noqa: F401
from .http.kv_server import KVClient, RendezvousServer  # noqa: F401
from .launch import (  # noqa: F401
    Settings,
    args_to_env,
    parse_args,
    run_commandline,
    run_static,
    settings_from_args,
)


def run(
    command: list[str],
    np: int = 1,
    hosts: str | None = None,
    hostfile: str | None = None,
    cpu_mode: bool = False,
    min_np: int | None = None,
    max_np: int | None = None,
    host_discovery_script: str | None = None,
    extra_args: list[str] | None = None,
    sink=None,
) -> int:
    """Programmatic launch (the reference's ``horovod.run()``)."""
    argv: list[str] = ["-np", str(np)]
    if hosts:
        argv += ["-H", hosts]
    if hostfile:
        argv += ["--hostfile", hostfile]
    if cpu_mode:
        argv += ["--cpu-mode"]
    if min_np is not None:
        argv += ["--min-np", str(min_np)]
    if max_np is not None:
        argv += ["--max-np", str(max_np)]
    if host_discovery_script:
        argv += ["--host-discovery-script", host_discovery_script]
    if extra_args:
        argv += extra_args
    argv += command
    args = parse_args(argv)
    settings = settings_from_args(args)
    if settings.elastic:
        from .elastic.driver import run_elastic

        return run_elastic(settings, sink=sink)
    return run_static(settings, sink=sink)
