"""Rendezvous key-value server over HTTP.

TPU-native analog of the reference's launcher-side KV store
(``horovod/runner/http/http_server.py — RendezvousServer, KVStoreHandler``),
which Gloo contexts rendezvoused against. Here the *data plane* needs no
rendezvous (XLA collectives bootstrap via ``jax.distributed``); the KV server
serves the **control plane**: worker registration, elastic host-update
notification, and generic scoped key/value exchange (used e.g. by
``broadcast_object`` fallbacks and the native runtime's coordinator
discovery).

Protocol: ``PUT /scope/key`` (body = value bytes), ``GET /scope/key``
(200 + bytes, or 404), ``DELETE /scope`` (drop a scope),
``GET /_scope/scope`` (list keys, newline separated). A monotonically
increasing ``version`` is bumped by ``reset()`` on elastic reconfiguration;
workers read it at ``GET /_version``.

World generation & coordinated abort: the epoch ``version`` doubles as the
monotonic **world generation**. Two mechanisms hang off it:

- **Abort records** (``abort/<generation>`` scope): the elastic driver
  posts one (``post_abort``) whenever it kills/blacklists a host or reaps
  an unclean worker exit, and a worker's stall inspector posts one when a
  stall crosses its shutdown deadline. Workers poll the record for *their*
  generation (``horovod_tpu.abort``) and convert a wedged collective into
  ``HorovodInternalError`` → elastic recovery.
- **Generation fencing**: a write (PUT/DELETE) carrying
  ``X-Hvd-Generation`` older than the current generation is rejected with
  409. A zombie worker from the pre-abort world (SIGSTOP'd through a
  recovery, then resumed) replays its buffered writes with its stale
  generation and corrupts nothing — the re-formed world's rendezvous and
  heartbeat records stay authoritative. Clients without the header (plain
  tooling, static launches) are not fenced.

Authentication (parity: ``horovod/runner/common/util/secret.py`` — the
reference HMAC-signs driver↔task traffic): when ``HOROVOD_SECRET_KEY`` is
set (the launcher generates one per job and ships it in the worker env
block), every request carries ``X-Hvd-Auth: HMAC-SHA256(method\\npath\\n
body)`` and the server rejects missing/invalid tags with 403 — a port
scanner on the cluster network cannot read or poison the rendezvous state.
No key set = open dev mode.

Metrics plane: ``GET /metrics`` serves a Prometheus-text aggregate of the
whole job — driver-side gauges (world generation/size, blacklisted hosts,
fenced writes, per-host heartbeat ages) plus every worker's instrument
snapshot, which workers piggyback on the heartbeat PUTs they already send
(``runner/elastic/worker.py``), labeled per rank/host. The endpoint is
exempt from HMAC auth by design: a standard Prometheus scraper cannot sign
requests, and the data is read-only operational telemetry (it carries no
rendezvous state a scraper could poison). See ``docs/observability.md``.

Tracing plane (``horovod_tpu.tracing``): heartbeat PUT replies carry the
server's wall clock (``{"t_server": ...}``) so workers can estimate their
clock offset NTP-style from timestamps they already have; workers post
sampled step spans to ``PUT /trace/<host>`` (bounded payloads, replaced
per host); ``GET /timeline`` serves the merged, offset-corrected
Chrome/Perfetto trace JSON with one track per rank; ``GET /stragglers``
serves the per-collective arrival-skew attribution as JSON, and the
``/metrics`` scrape gains ``hvd_collective_skew_seconds{rank}`` /
``hvd_straggler_score{host}`` gauges from the same computation. The
read-only ``/timeline`` and ``/stragglers`` routes share ``/metrics``'s
auth exemption (trace viewers can't HMAC either). See
``docs/timeline.md``.

Communication observatory (``horovod_tpu.comms_model``): each worker's
heartbeat also piggybacks its fitted α–β link cost model (``"comms"``
key); ``GET /comms`` (auth-exempt, read-only) serves the cluster-merged
view — per-rank fits, effective-sample-weighted cluster aggregates per
(op, algorithm, link_class), and the per-host predicted-vs-observed
residuals the self-healing policy consumes as a second
straggler-evidence channel. A cold cluster serves an explicit
``insufficient_samples`` body, never a 500. See
``docs/observability.md``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable
from urllib.error import HTTPError
from urllib.parse import parse_qs, urlsplit
from urllib.request import Request, urlopen

from ... import attribution as _attribution
from ... import comms_model as _comms_model
from ... import memory as _memory
from ... import faults
from ... import integrity as _integrity
from ... import metrics as _metrics
from ... import peercheck as _peercheck
from ... import tracing as _tracing
from ...checkpoint import rotate_slots
from ...utils.env import get_float, get_int
from ...utils.retry import call_with_retries
from .. import secret as _secret

AUTH_HEADER = "X-Hvd-Auth"
GENERATION_HEADER = "X-Hvd-Generation"

# Split-brain fence (control-plane fault tolerance): alongside the world
# generation, writes may carry the monotonic DRIVER EPOCH (bumped on
# every driver (re)start, persisted in runner/elastic/driver_state.py).
# A write stamped with an epoch LOWER than the serving driver's is a
# resurrected stale driver's (or a worker still loyal to one) and is
# rejected with 409 — a SIGSTOP'd-through-takeover driver can never
# reclaim or corrupt the re-formed world. Writes without the header are
# unfenced (plain tooling, static launches).
DRIVER_EPOCH_HEADER = "X-Hvd-Driver-Epoch"

# Liveness scope: workers PUT /heartbeat/<host>; the server records the
# RECEIVE time (server clock — worker clocks don't enter the liveness
# decision, so skew/NTP steps on preempted VMs can't fake death or life).
HEARTBEAT_SCOPE = "heartbeat"

# Coordinated-abort scope: one record per world generation, posted by the
# driver (host kill/blacklist/unclean exit) or a worker's stall inspector.
ABORT_SCOPE = "abort"

# Tracing scope: workers PUT /trace/<host> with sampled step spans + their
# measured clock offset; one payload per host (replaced on each ship).
TRACE_SCOPE = _tracing.TRACE_SCOPE

# Warm-spare registration scope: a spare worker (launched with
# HOROVOD_SPARE=1, waiting for an assignment) PUTs /spare/<host> once its
# framework imports are done — the driver's policy plane treats presence
# here (plus a fresh heartbeat) as "warm and promotable".
SPARE_SCOPE = "spare"

# Completion scope: an elastic worker whose training function RETURNED
# announces it here (``PUT /done/<host>``) before exiting 0. The driver
# normally learns completion from the exit code it reaps — but a worker
# ADOPTED across a driver restart is not the new driver's child, so its
# exit code is unreadable; the done record is how job completion
# survives a control-plane takeover.
DONE_SCOPE = "done"

# Preemption-notice scope: an external agent (cloud metadata watcher,
# maintenance tooling) PUTs /preempt/<host> to announce the host is about
# to be reclaimed. The elastic driver polls the scope and drains the host
# through the SIGTERM -> final-commit path — driver-side forwarding, so
# the notice works even when the cloud cannot signal the worker process
# directly. Notices are consumed once handled.
PREEMPT_SCOPE = "preempt"

#: The self-healing policy's action vocabulary (the `action` label values
#: of hvd_policy_decisions_total; zero-materialized on every scrape).
POLICY_ACTIONS = ("drain", "promote", "preempt")

# Peer-replication scope: each elastic rank PUTs its owned-shard replica
# record to /peerstate/<rank> on every commit (generation-fenced like all
# worker writes). Records are checksum-verified at install time — a torn
# body from a SIGKILL mid-PUT is rejected with 422 and the previous good
# record survives — and rotated (<rank> + <rank>.prev) through the same
# helper as the durable checkpoint's .prev file, so the replica pool is
# never left half-written. The scope deliberately SURVIVES epoch
# publication: the replica set of generation g is exactly what the peer
# recovery rung of generation g+1 assembles (horovod_tpu/peercheck.py).
PEERSTATE_SCOPE = _peercheck.PEERSTATE_SCOPE

# Training→serving bridge scope: trainers (HOROVOD_SERVE_PUBLISH=1)
# mirror each commit's replica record to ``PUT /modelstate/<rank>`` —
# same wire format, same install-time verification, same
# generation/epoch/quarantine fences as peerstate, but a scope of its
# own so serving-side consumption never contends with recovery. The
# read-only health/age view is the auth-exempt ``GET /model``.
MODELSTATE_SCOPE = _peercheck.MODELSTATE_SCOPE

# Payload bound for /trace PUTs: the worker caps spans/steps at the
# source; this is the server-side backstop against a misbehaving client.
_TRACE_MAX_BYTES = 1 << 20


def timeline_max_events() -> int:
    """Span-event cap for UNFILTERED ``GET /timeline`` bodies
    (``HOROVOD_TIMELINE_MAX_EVENTS``, default 200000; 0 disables): a
    large world's full merge can run to hundreds of MB, so past the cap
    the server answers **413** and the caller must bound the request
    with ``?steps=N`` / ``?rank=R``. Filtered requests are never capped
    (the caller already bounded them), and ``/criticalpath`` is never
    capped (its body is the small per-group analysis, not the raw
    spans). Documented in docs/timeline.md."""
    return get_int("HOROVOD_TIMELINE_MAX_EVENTS", 200000)


def _trace_query(query: str) -> tuple[int | None, str | None] | None:
    """Parse the shared ``?steps=N&rank=R`` trace-route filters.
    Returns (steps, rank), or None when a value is malformed (400)."""
    try:
        q = parse_qs(query, keep_blank_values=False)
    except ValueError:
        return None
    steps = None
    rank = None
    if "steps" in q:
        try:
            steps = int(q["steps"][-1])
        except (ValueError, IndexError):
            return None
        if steps <= 0:
            return None
    if "rank" in q:
        rank = q["rank"][-1]
    unknown = set(q) - {"steps", "rank"}
    if unknown:
        return None
    return steps, rank


def env_generation() -> int | None:
    """The launcher-written world generation, or None outside elastic
    worlds (static/manual launches are never fenced)."""
    import os

    raw = os.environ.get("HOROVOD_WORLD_VERSION", "")
    try:
        return int(raw)
    except ValueError:
        return None


def _auth_payload(method: str, path: str, body: bytes) -> bytes:
    return method.encode() + b"\n" + path.encode() + b"\n" + body


class _KVHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    # Silence per-request stderr logging (the launcher multiplexes worker
    # output; interleaved request logs would corrupt it).
    def log_message(self, fmt, *args):  # noqa: D102
        pass

    def _serve_fault(self) -> bool:
        """The ``kv.serve`` injection point: firing (drop semantics)
        closes the connection without answering — to the client that is
        a transport failure, indistinguishable from a driver dying
        mid-request; ``delay``/``hang`` stretch the request in place."""
        if faults.fire(faults.KV_SERVE):
            self.close_connection = True
            return True
        return False

    def _authenticate(self, body: bytes = b"") -> bool:
        tag = self.headers.get(AUTH_HEADER, "")
        key = self.server.secret  # type: ignore[attr-defined]
        if _secret.verify(_auth_payload(self.command, self.path, body), tag,
                          key=key):
            return True
        self._reply(403, b"bad auth tag")
        return False

    def _split(self):
        # Key = last path component; scope = everything before it (scopes may
        # contain slashes, e.g. "world/3").
        path = self.path.strip("/")
        if path.startswith("_scope/"):
            return "_scope", path[len("_scope/"):]
        if "/" not in path:
            return path, None
        scope, key = path.rsplit("/", 1)
        return scope, key

    def do_GET(self):  # noqa: N802
        if self._serve_fault():
            return
        route = urlsplit(self.path)
        if self.path == "/metrics":
            # Unauthenticated by design: Prometheus scrapers can't HMAC.
            return self._serve_metrics()
        if route.path in ("/timeline", "/criticalpath"):
            # Same exemption: Perfetto/curl can't sign; read-only. Both
            # routes take ?steps=N / ?rank=R so large-world scrapes stay
            # bounded; an unfiltered body past the event cap answers 413
            # (see timeline_max_events).
            return self._serve_trace_route(route.path, route.query)
        if self.path == "/stragglers":
            return self._serve_json(
                lambda httpd: _compute_cluster_skew(httpd)[0],
                "application/json")
        if self.path == "/comms":
            # Same exemption as /metrics: read-only operational
            # telemetry (the cluster-merged alpha-beta link cost model).
            return self._serve_json(_render_comms, "application/json")
        if self.path == "/memory":
            # Same exemption: the cluster-merged HBM breakdown (per-rank
            # resident bytes by kind, phase watermarks, headroom, model
            # drift) — read-only operational telemetry like /comms.
            return self._serve_json(_render_memory, "application/json")
        if self.path == "/integrity":
            # Same exemption: the collected integrity fingerprints (one
            # per rank, piggybacked on heartbeats) plus the live vote —
            # the SDC defense plane's observability window.
            return self._serve_json(_render_integrity, "application/json")
        if self.path == "/model":
            # Same exemption: the training→serving bridge's health/age
            # view (newest assemblable modelstate commit, publish
            # counters, staleness) — load balancers and serving probes
            # can't HMAC either.
            return self._serve_json(_render_model, "application/json")
        if not self._authenticate():
            return
        store = self.server.store  # type: ignore[attr-defined]
        scope, key = self._split()
        if scope == "_version":
            body = str(self.server.version).encode()  # type: ignore[attr-defined]
            return self._reply(200, body)
        if scope == "_epoch":
            body = str(self.server.driver_epoch).encode()  # type: ignore[attr-defined]
            return self._reply(200, body)
        if scope == "_scope":
            with self.server.lock:  # type: ignore[attr-defined]
                keys = sorted(store.get(key or "", {}).keys())
            return self._reply(200, ("\n".join(keys)).encode())
        with self.server.lock:  # type: ignore[attr-defined]
            val = store.get(scope, {}).get(key)
        if val is None:
            return self._reply(404, b"")
        self._reply(200, val)

    def _fence_check_locked(self) -> bytes | None:
        """Generation fence (call under the server lock): a write stamped
        with a generation older than the current world generation is a
        zombie from a pre-abort world — reject it so it cannot corrupt the
        re-formed world's records. Returns the 409 body, or None to
        proceed. Writes without the header are unfenced (plain clients)."""
        raw = self.headers.get(GENERATION_HEADER)
        if raw is None:
            # No generation stamp, but the driver-epoch fence must still
            # run: epoch-only clients (abort.post) fence on it alone.
            return self._epoch_fence_locked()
        try:
            gen = int(raw)
        except ValueError:
            return b"bad generation header"
        current = self.server.version  # type: ignore[attr-defined]
        if gen < current:
            self.server.fenced += 1  # type: ignore[attr-defined]
            return (f"stale generation {gen} rejected "
                    f"(world at generation {current})").encode()
        return self._epoch_fence_locked()

    def _epoch_fence_locked(self) -> bytes | None:
        """Driver-epoch fence (under the server lock): a write stamped
        with a driver epoch older than the serving driver's comes from a
        resurrected stale driver's world — 409 it so a
        SIGSTOP'd-through-takeover driver can never corrupt the state of
        the driver that superseded it. Headerless writes are unfenced."""
        raw = self.headers.get(DRIVER_EPOCH_HEADER)
        if raw is None:
            return None
        try:
            epoch = int(raw)
        except ValueError:
            return b"bad driver-epoch header"
        current = self.server.driver_epoch  # type: ignore[attr-defined]
        if epoch < current:
            self.server.fenced += 1  # type: ignore[attr-defined]
            return (f"stale driver epoch {epoch} rejected "
                    f"(world owned by driver epoch {current})").encode()
        return None

    def _integrity_quarantine_locked(self, key: str) -> bytes | None:
        """The integrity-vote fence on the ``peerstate`` scope (under
        the server lock): a rank named divergent by the voting plane has
        its replica PUTs rejected with 409 until a write arrives from a
        STRICTLY newer world generation (the re-formed world reuses the
        rank id for a healthy worker) — a corrupt shard must never
        displace a good replica. Headerless writes from a quarantined
        rank are rejected too: a corrupt host replaying unfenced is
        exactly who this fence exists for."""
        base = key
        while base.endswith(_peercheck.PREV_SUFFIX):
            base = base[:-len(_peercheck.PREV_SUFFIX)]
        raw = self.headers.get(GENERATION_HEADER)
        try:
            gen = int(raw) if raw is not None else None
        except ValueError:
            gen = None
        quarantine = getattr(self.server, "integrity_quarantine", None)
        entry = (quarantine or {}).get(base)
        if entry is not None:
            if entry.get("lifted"):
                # Tombstone: the formal fence is down (PUTs flow, the
                # condemned range still filters assembly) — but the
                # LIVE-vote fence must keep evaluating, or a rank id
                # re-condemned in a later generation would go unfenced
                # during the vote-to-driver-tick window.
                return self._live_vote_fence_locked(base, gen)
            if gen is not None and gen > int(entry.get("generation", 0)):
                # New world owns the rank id again: lift the PUT fence
                # but TOMBSTONE the entry instead of deleting it — the
                # condemned (possibly back-dated) range still filters
                # peer-rung assembly, or a failure before the new
                # generation's replica group completes could fall back
                # to and install the proven-corrupt old records.
                entry["lifted"] = True
                return None
            self.server.fenced += 1  # type: ignore[attr-defined]
            return (f"integrity quarantine: rank {base} was voted "
                    f"divergent at generation {entry.get('generation')} "
                    f"step {entry.get('step')} (host "
                    f"{entry.get('host')}); replica PUTs are fenced "
                    "until a newer generation").encode()
        return self._live_vote_fence_locked(base, gen)

    def _live_vote_fence_locked(self, base: str, gen: int | None
                                ) -> bytes | None:
        """The formal quarantine lands only on the driver's next monitor
        tick — latency a corrupt rank's NEXT commit can race, rotating
        the last good ``.prev`` away before ``quarantine_rank`` evicts
        anything. The server already holds every rank's fingerprint
        (heartbeat piggyback), so the fence votes inline: a replica PUT
        from the named outlier of a complete unambiguous divergent vote
        is rejected unless it proves a strictly newer world generation.
        Unarmed plane → no fingerprint has ever ridden a heartbeat → the
        ``integrity_seen`` latch short-circuits before any heartbeat
        body is parsed (inertness); armed, the parse+vote is cached per
        heartbeat mutation (``hb_version``), not re-run per PUT."""
        if not getattr(self.server, "integrity_seen", False):
            return None
        _records, voted = _cached_integrity_vote(self.server, locked=True)
        if voted is None:
            return None
        (vgen, vstep), verdict = voted
        if not verdict.get("divergent") or verdict.get("ambiguous"):
            return None
        try:
            outlier = int(verdict["outlier_rank"])
        except (KeyError, TypeError, ValueError):
            return None
        if str(outlier) != base or (gen is not None and gen > vgen):
            return None
        self.server.fenced += 1  # type: ignore[attr-defined]
        return (f"integrity live-vote fence: rank {base} is the outlier "
                f"of a divergent vote at generation {vgen} step {vstep}; "
                "replica PUT rejected pending driver quarantine").encode()

    def _drain_and_413(self, length: int, reason: bytes):
        """Reject an oversize body WITHOUT buffering it: the backstop
        must bound server memory, not just storage — the whole control
        plane rides this one process. The body is drained in small
        chunks and discarded (so the client reads a clean 413 instead of
        a connection reset mid-upload), never held whole."""
        remaining = length
        while remaining > 0:
            chunk = self.rfile.read(min(remaining, 1 << 16))
            if not chunk:
                break
            remaining -= len(chunk)
        return self._reply(413, reason)

    def do_PUT(self):  # noqa: N802
        if self._serve_fault():
            return
        scope, key = self._split()
        if key is None:
            return self._reply(400, b"missing key")
        length = int(self.headers.get("Content-Length", 0))
        if (scope in (PEERSTATE_SCOPE, MODELSTATE_SCOPE)
                and length > _peercheck.max_record_bytes()):
            return self._drain_and_413(length, b"replica record too large")
        if scope == TRACE_SCOPE and length > _TRACE_MAX_BYTES:
            return self._drain_and_413(length, b"trace payload too large")
        body = self.rfile.read(length)
        if not self._authenticate(body):
            return
        if scope in (PEERSTATE_SCOPE, MODELSTATE_SCOPE):
            # Install-time integrity gate: a half-received body (SIGKILL
            # mid-PUT, cut connection) or a corrupt record is rejected
            # BEFORE it can touch the pool — the previous good replica
            # (and its .prev) stay authoritative. The modelstate scope
            # rides the identical gate: a torn publish must never become
            # a servable record.
            why = _peercheck.verify_wire(body)
            if why is not None:
                if scope == MODELSTATE_SCOPE:
                    with self.server.lock:  # type: ignore[attr-defined]
                        self.server.model_rejected += 1  # type: ignore[attr-defined]
                return self._reply(422, why.encode())
        with self.server.lock:  # type: ignore[attr-defined]
            rejected = self._fence_check_locked()
            if rejected is None and scope in (PEERSTATE_SCOPE,
                                              MODELSTATE_SCOPE):
                rejected = self._integrity_quarantine_locked(key)
            if rejected is not None and scope == MODELSTATE_SCOPE:
                self.server.model_rejected += 1  # type: ignore[attr-defined]
            if rejected is None:
                if scope == MODELSTATE_SCOPE:
                    self.server.model_publishes += 1  # type: ignore[attr-defined]
                    self.server.model_last_t = time.time()  # type: ignore[attr-defined]
                if scope in (PEERSTATE_SCOPE, MODELSTATE_SCOPE):
                    # Rotate, don't overwrite: <rank> + <rank>.prev, via
                    # the same helper as the durable .prev file — the
                    # previous good commit survives until this one is
                    # verified and installed. An armed integrity plane
                    # keeps one slot more: its quarantine condemns up to
                    # a commit of detection latency, and assembly must
                    # still find an uncondemned group underneath.
                    rotate_slots(
                        self.server.store.setdefault(scope, {}),  # type: ignore[attr-defined]
                        key, body, prev_suffix=_peercheck.PREV_SUFFIX,
                        depth=_peercheck.retention_depth())
                else:
                    self.server.store.setdefault(scope, {})[key] = body  # type: ignore[attr-defined]
                if scope == TRACE_SCOPE:
                    # Attribution cache key: one bump per trace mutation
                    # so /criticalpath and the regression sentinel
                    # re-analyze exactly when new spans arrive.
                    self.server.trace_version = (  # type: ignore[attr-defined]
                        getattr(self.server, "trace_version", 0) + 1)
                if scope == HEARTBEAT_SCOPE:
                    # Liveness plane: stamp the receive time on the SERVER
                    # clock (driver-side monotonic; worker clocks
                    # irrelevant).
                    self.server.hb_times[key] = time.monotonic()  # type: ignore[attr-defined]
                    # Arm/refresh the live-vote fence: a cheap substring
                    # scan (no JSON parse) latches integrity_seen, and
                    # the mutation counter invalidates the vote cache.
                    self.server.hb_version = (  # type: ignore[attr-defined]
                        getattr(self.server, "hb_version", 0) + 1)
                    if (not getattr(self.server, "integrity_seen", False)
                            and b'"integrity"' in body):
                        self.server.integrity_seen = True  # type: ignore[attr-defined]
        if rejected is not None:
            return self._reply(409, rejected)
        if scope == HEARTBEAT_SCOPE:
            # Clock-alignment plane: the reply carries the SERVER's wall
            # clock so the worker can estimate its offset NTP-style from
            # its own send/receive stamps (horovod_tpu.tracing.ClockSync)
            # — no extra round trip, no extra route.
            return self._reply(
                200, json.dumps({"t_server": time.time()}).encode())
        self._reply(200, b"")

    def do_DELETE(self):  # noqa: N802
        if self._serve_fault():
            return
        if not self._authenticate():
            return
        scope = self.path.strip("/")
        with self.server.lock:  # type: ignore[attr-defined]
            rejected = self._fence_check_locked()
            if rejected is None:
                self.server.store.pop(scope, None)  # type: ignore[attr-defined]
        if rejected is not None:
            return self._reply(409, rejected)
        self._reply(200, b"")

    def _serve_trace_route(self, path: str, query: str):
        parsed = _trace_query(query)
        if parsed is None:
            return self._reply(
                400, b"bad query: use ?steps=N (positive int) "
                     b"and/or ?rank=R")
        steps, rank = parsed
        if path == "/timeline" and steps is None and rank is None:
            # The cap guards /timeline only: its body scales with the
            # raw span count. /criticalpath serves the small per-group
            # analysis (computed cached on every scrape regardless), so
            # capping it would deny the route while protecting nothing.
            cap = timeline_max_events()
            if cap > 0:
                count = _timeline_span_count(self.server)
                if count > cap:
                    return self._reply(
                        413,
                        (f"merged trace holds {count} span events > cap "
                         f"{cap} (HOROVOD_TIMELINE_MAX_EVENTS); bound "
                         f"the request with ?steps=N and/or ?rank=R"
                         ).encode())
        if path == "/criticalpath":
            render = (lambda httpd:
                      _render_criticalpath(httpd, steps=steps, rank=rank))
        else:
            render = (lambda httpd:
                      _render_timeline(httpd, steps=steps, rank=rank))
        return self._serve_json(render, "application/json")

    def _serve_metrics(self):
        try:
            body = _render_cluster_metrics(self.server).encode()
        except Exception as e:  # noqa: BLE001 — scrape must not kill the KV
            return self._reply(500, f"metrics render failed: {e}".encode())
        self.send_response(200)
        self.send_header("Content-Type",
                         "text/plain; version=0.0.4; charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _serve_json(self, render, content_type: str):
        try:
            body = json.dumps(render(self.server)).encode()
        except Exception as e:  # noqa: BLE001 — must not kill the KV
            return self._reply(500, f"render failed: {e}".encode())
        self.send_response(200)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _reply(self, code: int, body: bytes):
        self.send_response(code)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


def _trace_payloads(httpd) -> dict[str, dict]:
    """Parsed ``PUT /trace`` payloads by host (malformed ones dropped —
    a broken worker must not break the merge for everyone else)."""
    with httpd.lock:
        raw = dict(httpd.store.get(TRACE_SCOPE, {}))
    out: dict[str, dict] = {}
    for host, body in raw.items():
        try:
            payload = json.loads(body)
        except (ValueError, UnicodeDecodeError):
            continue
        if isinstance(payload, dict):
            out[host] = payload
    return out


def _timeline_span_count(httpd) -> int:
    """Span events an unfiltered /timeline body would carry (the 413
    cap's cheap estimate — no JSON re-render)."""
    total = 0
    for payload in _trace_payloads(httpd).values():
        for steprec in payload.get("steps", ()) or ():
            if isinstance(steprec, dict):
                total += len(steprec.get("spans", ()) or ())
    return total


def _render_timeline(httpd, steps: int | None = None,
                     rank: str | None = None) -> dict:
    """The merged cross-rank trace: every shipped payload's spans on one
    server timebase (each rank's measured clock offset applied), one
    Chrome-trace process track per rank. Loadable directly in Perfetto /
    chrome://tracing. ``steps`` keeps only each rank's last N buffered
    steps; ``rank`` keeps one rank's track — the ``?steps=N`` /
    ``?rank=R`` query filters that keep large-world scrapes bounded."""
    payloads = _trace_payloads(httpd)
    events: list[dict] = []
    for host, payload in sorted(payloads.items()):
        if rank is not None and str(payload.get("rank", "?")) != str(rank):
            continue
        try:
            pid = int(payload.get("rank", 0))
        except (TypeError, ValueError):
            pid = 0
        try:
            offset = float(payload.get("clock_offset_s", 0.0) or 0.0)
        except (TypeError, ValueError):
            offset = 0.0
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "args": {"name": f"rank {pid} ({host})"}})
        events.append({"name": "process_sort_index", "ph": "M", "pid": pid,
                       "args": {"sort_index": pid}})
        steprecs = list(payload.get("steps", ()) or ())
        if steps is not None and steps > 0:
            steprecs = steprecs[-steps:]  # ring order: oldest first
        for steprec in steprecs:
            if not isinstance(steprec, dict):
                continue
            for sp in steprec.get("spans", ()) or ():
                if not isinstance(sp, dict):
                    continue
                try:
                    ts_us = (float(sp["t"]) + offset) * 1e6
                    dur_us = max(float(sp.get("dur", 0.0)), 0.0) * 1e6
                except (KeyError, TypeError, ValueError):
                    continue
                events.append({
                    "name": str(sp.get("name", "?")),
                    "cat": str(sp.get("cat", "phase")),
                    "ph": "X",
                    "ts": ts_us,
                    "dur": dur_us,
                    "pid": pid,
                    "tid": 0,
                    "args": {
                        "step": steprec.get("step"),
                        "host": host,
                        **(sp.get("args") or {}),
                    },
                })
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "metadata": {
            "timebase": "rendezvous-server wall clock (offsets applied)",
            "ranks": sorted(
                str(p.get("rank", "?")) for p in payloads.values()),
        },
    }


def _compute_cluster_skew(httpd) -> tuple[dict, dict[str, dict]]:
    """Arrival-skew attribution over the shipped payloads, plus the
    payloads themselves (so /metrics renders offsets without re-parsing).
    Journals a throttled ``straggler_detected`` event when the worst
    matched instance crosses ``HOROVOD_STRAGGLER_WARN_SKEW``."""
    payloads = _trace_payloads(httpd)
    skew = _tracing.compute_skew(payloads)
    worst = skew.get("worst")
    # Threshold on skew MINUS the combined clock-error bound: congested
    # heartbeats widen each rank's offset uncertainty (up to ~RTT/2), and
    # that uncertainty must never journal a healthy host as a straggler.
    if worst and (worst["skew_s"] - worst.get("err_s", 0.0)
                  >= _tracing.straggler_warn_skew()):
        with httpd.lock:
            version = httpd.version
            logged = getattr(httpd, "straggler_logged", None)
            if logged is None:
                logged = httpd.straggler_logged = set()
            key = (version, worst["last_rank"])
            fresh = key not in logged
            logged.add(key)
        if fresh:
            _metrics.event(
                "straggler_detected", generation=version,
                rank=worst["last_rank"], host=worst["last_host"],
                skew_s=worst["skew_s"], collective=worst["name"],
                step=worst["step"])
    return skew, payloads


def _cluster_attribution(httpd) -> dict:
    """The full-cluster step attribution (``attribution.analyze_cluster``
    over the shipped payloads), cached per trace-store mutation
    (``trace_version``) so repeated scrapes and replica polls cost one
    integer compare. A cache MISS additionally folds any new
    (generation, step) groups into the server's regression sentinel —
    the one place the sentinel ticks, so it advances exactly once per
    new sampled step no matter how many routes render it."""
    with httpd.lock:
        version = getattr(httpd, "trace_version", 0)
        cached = getattr(httpd, "attrib_cache", None)
    if cached is not None and cached[0] == version:
        return cached[1]
    analysis = _attribution.analyze_cluster(_trace_payloads(httpd))
    _sentinel_fold(httpd, analysis)
    with httpd.lock:
        httpd.attrib_cache = (version, analysis)
    return analysis


def _sentinel_fold(httpd, analysis: dict) -> None:
    """Feed NEW (generation, step) groups into the server's regression
    sentinel (EWMA baseline per phase over the cluster-mean
    decomposition), journal a ``step_regression`` event for each phase
    that newly crosses the drift threshold — naming the suspect rank the
    group's critical path gated on — and refresh the advisory
    ``regression_suspects`` map ({host: excess seconds}) the self-healing
    policy may consult (``HOROVOD_POLICY_STEP_REGRESSION``)."""
    sentinel = getattr(httpd, "attrib_sentinel", None)
    if sentinel is None:
        return
    with httpd.lock:
        folded = httpd.attrib_folded
        new = [g for g in analysis.get("groups", ())
               if (g["generation"], g["step"]) not in folded]
        folded.update((g["generation"], g["step"]) for g in new)
        if len(folded) > 4096:
            # Evict the OLDEST keys only: the per-rank ring advances
            # monotonically, so a low (generation, step) can never
            # reappear in the payloads — while an arbitrary set.pop()
            # could evict a still-buffered group and double-fold it
            # into the sentinel on the next mutation.
            for key in sorted(folded)[:len(folded) - 2048]:
                folded.discard(key)
    suspects: dict[str, float] = {}
    for g in new:
        ranks = g.get("ranks") or {}
        if not ranks:
            continue
        phases = {
            p: sum(d["phases"].get(p, 0.0) for d in ranks.values())
            / len(ranks)
            for p in _attribution.STEP_PHASES
        }
        verdict = sentinel.observe(phases, wall=g.get("wall_s"))
        alarmed = sorted(sentinel.snapshot()["alarmed"])
        if verdict["alarms"]:
            _metrics.event(
                "step_regression",
                generation=g["generation"], step=g["step"],
                phases=verdict["alarms"],
                scores={p: verdict["scores"].get(p)
                        for p in verdict["alarms"]},
                excess_s={p: verdict["excess_s"].get(p)
                          for p in verdict["alarms"]},
                suspect_rank=g.get("suspect_rank"),
                suspect_host=g.get("suspect_host"))
        # Advisory policy channel: while ANY phase is in alarm, the
        # latest group's critical-path suspect carries the worst
        # alarmed excess (seconds — directly comparable to the skew
        # and comms-residual lateness channels). No alarm = empty map.
        if alarmed and g.get("suspect_host"):
            suspects = {
                str(g["suspect_host"]): max(
                    (verdict["excess_s"].get(p, 0.0) for p in alarmed),
                    default=0.0)
            }
        elif not alarmed:
            suspects = {}
    if new:
        with httpd.lock:
            httpd.regression_suspects = suspects


def _render_criticalpath(httpd, steps: int | None = None,
                         rank: str | None = None) -> dict:
    """``GET /criticalpath``: the merged per-step attribution — per-rank
    phase decomposition (phases sum to each rank's step wall time), the
    cluster critical path with a named gating rank per collective
    barrier, per-rank MFU where the model declared its FLOPs, and the
    regression sentinel's state. A world with no synced samples yet
    (cold start, ``HOROVOD_TRACE_SAMPLE=0``) serves an explicit
    ``insufficient_samples`` body — never a 500. ``steps``/``rank`` are
    the bounding query filters (applied to the cached full analysis)."""
    analysis = _cluster_attribution(httpd)
    groups = list(analysis.get("groups", ()))
    if steps is not None and steps > 0:
        groups = groups[-steps:]
    if rank is not None:
        groups = [
            dict(g, ranks={r: d for r, d in g.get("ranks", {}).items()
                           if r == str(rank)})
            for g in groups
        ]
        groups = [g for g in groups if g["ranks"]]
    with httpd.lock:
        generation = httpd.version
        sentinel = getattr(httpd, "attrib_sentinel", None)
        suspects = dict(getattr(httpd, "regression_suspects", {}))
    return {
        "status": "ok" if groups else "insufficient_samples",
        "generation": generation,
        "groups": groups,
        "regression": {
            "sentinel": (sentinel.snapshot()
                         if sentinel is not None else None),
            "suspects": suspects,
        },
    }


def _comms_payloads(httpd) -> dict[str, dict]:
    """Per-rank comms-model payloads, as piggybacked on heartbeat PUTs
    (the ``"comms"`` key of each heartbeat body), keyed by host.
    Malformed heartbeats are skipped — same tolerance as the metrics
    piggyback."""
    with httpd.lock:
        raw = dict(httpd.store.get(HEARTBEAT_SCOPE, {}))
    out: dict[str, dict] = {}
    for host, body in raw.items():
        try:
            hb = json.loads(body)
        except (ValueError, UnicodeDecodeError):
            continue
        if not isinstance(hb, dict):
            continue
        comms = hb.get("comms")
        if isinstance(comms, dict):
            out[host] = comms
    return out


def _render_comms(httpd) -> dict:
    """``GET /comms``: the cluster-merged α–β link cost model. A world
    where nothing fitted yet (cold start, parked spares, single-device
    smoke) serves an explicit ``insufficient_samples`` body — never a
    500 (``comms_model.merge_payloads`` owns that contract)."""
    merged = _comms_model.merge_payloads(_comms_payloads(httpd))
    with httpd.lock:
        merged["generation"] = httpd.version
    return merged


def _memory_payloads(httpd) -> dict[str, dict]:
    """Per-rank memory-observatory payloads, as piggybacked on heartbeat
    PUTs (the ``"memory"`` key of each heartbeat body), keyed by host.
    Malformed heartbeats are skipped — same tolerance as the comms
    piggyback."""
    with httpd.lock:
        raw = dict(httpd.store.get(HEARTBEAT_SCOPE, {}))
    out: dict[str, dict] = {}
    for host, body in raw.items():
        try:
            hb = json.loads(body)
        except (ValueError, UnicodeDecodeError):
            continue
        if not isinstance(hb, dict):
            continue
        mem = hb.get("memory")
        if isinstance(mem, dict):
            out[host] = mem
    return out


def _render_memory(httpd) -> dict:
    """``GET /memory``: the cluster-merged HBM breakdown. A world where
    nothing measured yet (cold start, parked spares) serves an explicit
    ``insufficient_samples`` body — never a 500
    (``memory.merge_payloads`` owns that contract). Generation-fenced
    like ``/comms``: the body carries the world generation so readers
    can discard cross-generation merges."""
    merged = _memory.merge_payloads(_memory_payloads(httpd))
    with httpd.lock:
        merged["generation"] = httpd.version
    return merged


def _integrity_records(httpd, locked: bool = False) -> dict[int, dict]:
    """Per-rank integrity fingerprints, as piggybacked on heartbeat PUTs
    (the ``"integrity"`` key of each heartbeat body), keyed by the
    record's self-reported rank. Malformed heartbeats are skipped. Pass
    ``locked=True`` from a caller already holding ``httpd.lock`` (it is
    not reentrant)."""
    if locked:
        raw = dict(httpd.store.get(HEARTBEAT_SCOPE, {}))
    else:
        with httpd.lock:
            raw = dict(httpd.store.get(HEARTBEAT_SCOPE, {}))
    out: dict[int, dict] = {}
    for host, body in raw.items():
        try:
            hb = json.loads(body)
        except (ValueError, UnicodeDecodeError):
            continue
        if not isinstance(hb, dict):
            continue
        rec = hb.get("integrity")
        if not isinstance(rec, dict):
            continue
        try:
            rank = int(rec.get("rank", 0))
        except (TypeError, ValueError):
            continue
        # Colliding self-reported ranks: freshest record wins, so a
        # stale zombie's payload cannot shadow the live rank's.
        held = out.get(rank)
        if held is None or rec.get("t", 0) >= held.get("t", 0):
            out[rank] = rec
    return out


def _cached_integrity_vote(server, locked: bool = False):
    """(records, voted) for the current heartbeat store, cached per
    (``hb_version``, ``world_np``) mutation — repeated replica PUTs and
    idle ``GET /integrity`` polls (the scraper, every peer-rung
    assembly's quarantine fetch) cost one integer compare instead of a
    JSON parse of every fattened heartbeat body plus a re-vote."""
    world_np = getattr(server, "world_np", 0)
    key = (getattr(server, "hb_version", 0), world_np)
    cached = getattr(server, "integrity_vote_cache", None)
    if cached is not None and cached[0] == key:
        return cached[1], cached[2]
    records = _integrity_records(server, locked=locked)
    if not records:
        voted = None
    else:
        voted = _integrity.vote_latest(records, world_np or len(records))
    server.integrity_vote_cache = (key, records, voted)
    return records, voted


def _render_integrity(httpd) -> dict:
    """``GET /integrity``: the collected fingerprints plus the newest
    complete group's vote. A world where nothing fingerprinted yet
    (plane unarmed, cold start) serves an explicit ``no_records`` body —
    never a 500."""
    records, voted = _cached_integrity_vote(httpd)
    with httpd.lock:
        generation = httpd.version
        world_np = getattr(httpd, "world_np", 0)
        quarantined = dict(getattr(httpd, "integrity_quarantine", {}))
        divergence = dict(getattr(httpd, "integrity_divergence", {}))
    out = {
        "status": "ok" if records else "no_records",
        "generation": generation,
        "world_size": world_np,
        "records": {str(r): rec for r, rec in sorted(records.items())},
        "quarantined": quarantined,
        "divergence_counts": divergence,
        "vote": None,
    }
    if voted is not None:
        (gen, step), verdict = voted
        out["vote"] = {"group": [gen, step], **verdict}
    return out


def _render_model(httpd) -> dict:
    """``GET /model``: the training→serving bridge's health/age view —
    the newest complete, checksum-valid, unquarantined ``modelstate``
    commit the stored records can assemble right now, plus publish
    counters and the model age. A cold scope serves an explicit
    ``no_model`` body, an unassemblable one serves the reason — never a
    500: this is what load balancers and readiness probes poll."""
    with httpd.lock:
        generation = httpd.version
        publishes = getattr(httpd, "model_publishes", 0)
        rejected = getattr(httpd, "model_rejected", 0)
        last_t = getattr(httpd, "model_last_t", None)
        blobs = list(httpd.store.get(MODELSTATE_SCOPE, {}).values())
        quarantine = dict(getattr(httpd, "integrity_quarantine", {}))
    records = []
    for blob in blobs:
        try:
            records.append(_peercheck.decode_record(blob, verify=True))
        except Exception:  # noqa: BLE001 — judged at assembly, not here
            continue
    out = {
        "status": "no_model",
        "generation": generation,
        "publishes": publishes,
        "rejected": rejected,
        "age_seconds": (None if last_t is None
                        else max(0.0, time.time() - last_t)),
        "model": None,
    }
    if not records:
        return out
    try:
        members = _peercheck.assemble_records(
            records, generation, quarantine=quarantine)
    except _peercheck.ReplicaUnavailableError as e:
        out["status"] = "unassemblable"
        out["reason"] = str(e)
        return out
    out["status"] = "ok"
    out["model"] = {
        "generation": members[0].generation,
        "step": members[0].step,
        "world_size": members[0].world_size,
        "ranks": [r.rank for r in members],
        "bytes": sum(len(r.payload) for r in members),
        "digest": _peercheck.replica_set_digest(members),
    }
    return out


def _render_cluster_metrics(httpd) -> str:
    """The driver's cluster-wide scrape: driver-plane gauges built from
    live server state, then every worker snapshot found piggybacked on a
    heartbeat payload, rendered with per-rank/host labels."""
    with httpd.lock:
        version = httpd.version
        fenced = httpd.fenced
        world_np = getattr(httpd, "world_np", 0)
        blacklisted = getattr(httpd, "blacklisted", 0)
        spares = getattr(httpd, "spare_count", 0)
        policy_actions = dict(getattr(httpd, "policy_actions", {}))
        driver_epoch = getattr(httpd, "driver_epoch", 0)
        driver_lost = dict(getattr(httpd, "driver_lost", {}))
        integrity_div = dict(getattr(httpd, "integrity_divergence", {}))
        quarantined = sum(
            1 for e in getattr(httpd, "integrity_quarantine", {}).values()
            if not e.get("lifted"))  # tombstones only filter assembly
        now = time.monotonic()
        ages = {h: now - t for h, t in httpd.hb_times.items()}
        payloads = dict(httpd.store.get(HEARTBEAT_SCOPE, {}))
    driver_families = [
        _metrics.make_family(
            "hvd_world_generation", "gauge",
            "Monotonic world generation (the rendezvous epoch version).",
            [({}, version)]),
        _metrics.make_family(
            "hvd_world_size", "gauge",
            "Hosts in the current world epoch (0 before the first "
            "elastic publish).", [({}, world_np)]),
        _metrics.make_family(
            "hvd_blacklisted_hosts", "gauge",
            "Hosts currently blacklisted by the elastic driver.",
            [({}, blacklisted)]),
        _metrics.make_family(
            "hvd_fenced_writes_total", "counter",
            "Stale-generation writes rejected by the generation fence.",
            [({}, fenced)]),
        _metrics.make_family(
            "hvd_heartbeat_age_seconds", "gauge",
            "Seconds since each host's last heartbeat (server clock).",
            [({"host": h}, age) for h, age in sorted(ages.items())]),
        # Self-healing policy plane: zero-materialized so the scrape gate
        # can assert the instruments exist before any decision fires, and
        # dashboards can tell "no drains yet" from "not measuring".
        _metrics.make_family(
            "hvd_policy_spare_hosts", "gauge",
            "Warm spare hosts currently launched, heartbeating, and held "
            "out of the world by the elastic driver.",
            [({}, spares)]),
        _metrics.make_family(
            "hvd_policy_decisions_total", "counter",
            "Self-healing policy actions taken by the elastic driver "
            "(drain|promote|preempt).",
            [({"action": a}, policy_actions.get(a, 0))
             for a in POLICY_ACTIONS]),
        # Control-plane fault tolerance: the driver epoch (split-brain
        # fence identity; 0 = no driver-state plane) and per-host
        # EXIT_DRIVER_LOST reap counts. The unlabeled sample is the
        # job-wide total, zero-materialized so the scrape gate can
        # assert the instrument before any flap.
        _metrics.make_family(
            "hvd_driver_epoch", "gauge",
            "Monotonic driver epoch: bumped on every driver (re)start; "
            "stale-epoch writes are 409-fenced.",
            [({}, driver_epoch)]),
        _metrics.make_family(
            "hvd_driver_lost_total", "counter",
            "Workers reaped with EXIT_DRIVER_LOST (rendezvous KV "
            "unreachable past the deadline) — control-plane flaps, by "
            "host, plus the unlabeled job-wide total.",
            [({}, sum(driver_lost.values()))]
            + [({"host": h}, n) for h, n in sorted(driver_lost.items())]),
        # Integrity defense plane (driver-side vote outcomes): the
        # unlabeled sample is the job-wide total, zero-materialized so
        # the scrape gate can assert the instrument before any
        # corruption ever happens.
        _metrics.make_family(
            "hvd_integrity_divergence_total", "counter",
            "Cross-rank integrity votes that named a host's replica "
            "state divergent (silent data corruption evidence), by "
            "host, plus the unlabeled job-wide total.",
            [({}, sum(integrity_div.values()))]
            + [({"host": h}, n)
               for h, n in sorted(integrity_div.items())]),
        _metrics.make_family(
            "hvd_integrity_quarantined_ranks", "gauge",
            "Ranks whose peer-replica PUTs are currently fenced by an "
            "integrity-vote quarantine.", [({}, quarantined)]),
    ]
    # Multi-tenant pod: a driver serving one job of a shared pool
    # (HOROVOD_JOB_ID set per job process tree by the scheduler) stamps
    # every family on its scrape with the job dimension, so N per-job
    # scrape targets merge in PromQL without relabeling. Unset (every
    # single-job path) the scrape is bit-for-bit the HEAD body.
    job = os.environ.get("HOROVOD_JOB_ID") or ""
    job_labels = {"job": job} if job else {}
    groups: list = [(job_labels, driver_families)]
    steps_samples: list = []
    commit_samples: list = []
    for host, raw in sorted(payloads.items()):
        try:
            payload = json.loads(raw)
        except (ValueError, UnicodeDecodeError):
            continue
        if not isinstance(payload, dict):
            continue
        labels = {"host": host, **job_labels}
        rank = payload.get("rank")
        if rank is not None:
            labels["rank"] = str(rank)
        if isinstance(payload.get("steps"), (int, float)):
            steps_samples.append((labels, payload["steps"]))
        if isinstance(payload.get("commits"), (int, float)):
            commit_samples.append((labels, payload["commits"]))
        families = payload.get("metrics")
        if isinstance(families, list):
            families = [f for f in families
                        if isinstance(f, dict) and "name" in f]
            if families:
                groups.append((labels, families))
    driver_families.append(_metrics.make_family(
        "hvd_worker_steps_total", "counter",
        "Watched steps reported on each worker's last heartbeat.",
        steps_samples))
    driver_families.append(_metrics.make_family(
        "hvd_worker_commits_total", "counter",
        "State commits reported on each worker's last heartbeat.",
        commit_samples))
    # Tick the step-attribution plane on every scrape (cached per trace
    # mutation, so an idle poll costs one integer compare): the
    # regression sentinel must advance on the operator's regular
    # /metrics cadence even when nobody fetches /criticalpath.
    try:
        _cluster_attribution(httpd)
    except Exception:  # noqa: BLE001 — attribution must not kill the scrape
        pass
    # Straggler attribution from the tracing plane: per-rank arrival skew
    # against the earliest rank on matched collectives/steps (shipped
    # trace payloads, offset-corrected), and a per-host score the
    # autoscaler (ROADMAP item 3) can threshold on. Empty when no traces
    # have shipped (HOROVOD_TRACE_SAMPLE=0) — absent series, not zeros,
    # so dashboards can tell "no stragglers" from "not measuring".
    skew, payloads = _compute_cluster_skew(httpd)
    skew_samples = []
    host_lateness: dict[str, list[float]] = {}
    for rank, info in sorted(skew.get("ranks", {}).items()):
        labels = {"rank": rank, "host": info.get("host", "")}
        skew_samples.append((labels, info["max_lateness_s"]))
        host_lateness.setdefault(info.get("host", ""), []).append(
            info["mean_lateness_s"])
    if skew_samples:
        driver_families.append(_metrics.make_family(
            "hvd_collective_skew_seconds", "gauge",
            "Max arrival lateness of each rank behind the earliest rank "
            "on matched collectives (offset-corrected trace spans).",
            skew_samples))
        driver_families.append(_metrics.make_family(
            "hvd_straggler_score", "gauge",
            "Mean arrival lateness per host across its ranks' matched "
            "collectives — the straggler-replacement signal.",
            [({"host": h}, sum(ls) / len(ls))
             for h, ls in sorted(host_lateness.items())]))
    offset_samples = [
        ({"rank": str(p.get("rank", "?")), "host": h},
         float(p.get("clock_offset_s", 0.0) or 0.0))
        for h, p in sorted(payloads.items())
    ]
    if offset_samples:
        driver_families.append(_metrics.make_family(
            "hvd_trace_clock_offset_seconds", "gauge",
            "Each rank's measured wall-clock offset vs the rendezvous "
            "server (server - local), as shipped with its trace.",
            offset_samples))
    return _metrics.render_families(groups)


class RendezvousServer:
    """In-memory scoped KV over HTTP, owned by the launcher."""

    def __init__(self, host: str = "0.0.0.0"):
        self._httpd = ThreadingHTTPServer((host, 0), _KVHandler)
        self._httpd.store = {}  # type: ignore[attr-defined]
        self._httpd.lock = threading.Lock()  # type: ignore[attr-defined]
        self._httpd.version = 0  # type: ignore[attr-defined]
        self._httpd.fenced = 0  # type: ignore[attr-defined]
        self._httpd.hb_times = {}  # type: ignore[attr-defined]
        self._httpd.world_np = 0  # type: ignore[attr-defined]
        self._httpd.blacklisted = 0  # type: ignore[attr-defined]
        self._httpd.spare_count = 0  # type: ignore[attr-defined]
        self._httpd.policy_actions = {}  # type: ignore[attr-defined]
        self._httpd.driver_epoch = 0  # type: ignore[attr-defined]
        self._httpd.driver_lost = {}  # type: ignore[attr-defined]
        self._httpd.integrity_quarantine = {}  # type: ignore[attr-defined]
        self._httpd.integrity_divergence = {}  # type: ignore[attr-defined]
        # Training→serving bridge counters (the GET /model health view):
        # accepted / fence-or-verify-rejected modelstate publishes and
        # the wall time of the last accepted one (model age).
        self._httpd.model_publishes = 0  # type: ignore[attr-defined]
        self._httpd.model_rejected = 0  # type: ignore[attr-defined]
        self._httpd.model_last_t = None  # type: ignore[attr-defined]
        # Inertness latch + vote cache for the live-vote fence: until a
        # heartbeat actually carries an integrity fingerprint, peerstate
        # PUTs must not pay a JSON parse of every heartbeat body; once
        # armed, the parse+vote runs once per heartbeat mutation, not
        # once per replica PUT.
        self._httpd.integrity_seen = False  # type: ignore[attr-defined]
        self._httpd.hb_version = 0  # type: ignore[attr-defined]
        self._httpd.integrity_vote_cache = None  # type: ignore[attr-defined]
        self._httpd.straggler_logged = set()  # type: ignore[attr-defined]
        # Step-attribution plane: the analysis cache (keyed by the trace
        # mutation counter), the regression sentinel, the set of
        # (generation, step) groups already folded into it, and the
        # advisory {host: excess seconds} suspect map the policy may
        # consult (HOROVOD_POLICY_STEP_REGRESSION).
        self._httpd.trace_version = 0  # type: ignore[attr-defined]
        self._httpd.attrib_cache = None  # type: ignore[attr-defined]
        self._httpd.attrib_sentinel = (  # type: ignore[attr-defined]
            _attribution.RegressionSentinel())
        self._httpd.attrib_folded = set()  # type: ignore[attr-defined]
        self._httpd.regression_suspects = {}  # type: ignore[attr-defined]
        # Key snapshot at construction: the job's secret must not drift
        # under a live server (and env edits elsewhere must not rekey it).
        self._httpd.secret = _secret.current_key()  # type: ignore[attr-defined]
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def version(self) -> int:
        return self._httpd.version  # type: ignore[attr-defined]

    @property
    def generation(self) -> int:
        """The monotonic world generation (alias of the epoch version:
        both bump together on every world re-formation)."""
        return self._httpd.version  # type: ignore[attr-defined]

    @property
    def fenced_writes(self) -> int:
        """How many stale-generation/stale-epoch writes the fences have
        rejected."""
        with self._httpd.lock:  # type: ignore[attr-defined]
            return self._httpd.fenced  # type: ignore[attr-defined]

    @property
    def driver_epoch(self) -> int:
        return self._httpd.driver_epoch  # type: ignore[attr-defined]

    def seed(self, generation: int | None = None,
             driver_epoch: int | None = None) -> None:
        """Takeover entry (``runner/elastic/driver_state.py``): a
        restarted driver seeds its fresh server with the snapshot's
        world generation — so the takeover epoch publishes at g+1 and
        the existing generation fence stays monotonic across the crash —
        and with its own (bumped) driver epoch, arming the split-brain
        fence. Call before :meth:`start`."""
        with self._httpd.lock:  # type: ignore[attr-defined]
            if generation is not None:
                self._httpd.version = int(generation)  # type: ignore[attr-defined]
            if driver_epoch is not None:
                self._httpd.driver_epoch = int(driver_epoch)  # type: ignore[attr-defined]

    def seed_driver_lost(self, counts: dict) -> None:
        """Takeover resume: carry the predecessor's per-host
        EXIT_DRIVER_LOST counts into the scrape, so
        ``hvd_driver_lost_total`` keeps telling the truth about flaps
        building toward the blacklist cap across the very control-plane
        event it exists to expose."""
        with self._httpd.lock:  # type: ignore[attr-defined]
            table = self._httpd.driver_lost  # type: ignore[attr-defined]
            for host, n in (counts or {}).items():
                try:
                    table[str(host)] = max(table.get(str(host), 0),
                                           int(n))
                except (TypeError, ValueError):
                    continue

    def record_driver_lost(self, host: str) -> None:
        """Count one EXIT_DRIVER_LOST reap into the scrape's
        ``hvd_driver_lost_total{host}`` counter (the control-plane flap
        signal operators watch before the 3-consecutive cap blacklists
        a healthy host)."""
        with self._httpd.lock:  # type: ignore[attr-defined]
            counts = self._httpd.driver_lost  # type: ignore[attr-defined]
            counts[host] = counts.get(host, 0) + 1

    def done_records(self) -> dict[str, dict]:
        """Hosts whose workers announced clean completion (parsed
        ``PUT /done/<host>`` records) — how an ADOPTED worker's rc=0
        survives the driver restart that orphaned it."""
        return self._scope_records(DONE_SCOPE)

    def set_cluster_info(self, world_np: int | None = None,
                         blacklisted: int | None = None,
                         spares: int | None = None) -> None:
        """Driver-side gauges for the ``/metrics`` scrape: the elastic
        driver refreshes these on every world publish / blacklist / spare
        change, since only it knows them (the server sees heartbeats, not
        topology)."""
        with self._httpd.lock:  # type: ignore[attr-defined]
            if world_np is not None:
                self._httpd.world_np = int(world_np)  # type: ignore[attr-defined]
            if blacklisted is not None:
                self._httpd.blacklisted = int(blacklisted)  # type: ignore[attr-defined]
            if spares is not None:
                self._httpd.spare_count = int(spares)  # type: ignore[attr-defined]

    def record_policy_action(self, action: str) -> None:
        """Count one self-healing policy action into the scrape's
        ``hvd_policy_decisions_total{action=...}`` counter."""
        with self._httpd.lock:  # type: ignore[attr-defined]
            counts = self._httpd.policy_actions  # type: ignore[attr-defined]
            counts[action] = counts.get(action, 0) + 1

    # -- warm-spare registration + preemption notices -------------------------

    def _scope_records(self, scope: str) -> dict[str, dict]:
        with self._httpd.lock:  # type: ignore[attr-defined]
            raw = dict(self._httpd.store.get(scope, {}))  # type: ignore[attr-defined]
        out: dict[str, dict] = {}
        for key, body in raw.items():
            try:
                rec = json.loads(body)
            except (ValueError, UnicodeDecodeError):
                rec = {}
            out[key] = rec if isinstance(rec, dict) else {}
        return out

    def spare_records(self) -> dict[str, dict]:
        """Hosts whose spare workers have registered as warm (parsed
        ``PUT /spare/<host>`` records)."""
        return self._scope_records(SPARE_SCOPE)

    def clear_spare(self, host: str) -> None:
        """Drop a host's spare registration (promotion into the world, or
        spare teardown)."""
        with self._httpd.lock:  # type: ignore[attr-defined]
            self._httpd.store.get(  # type: ignore[attr-defined]
                SPARE_SCOPE, {}).pop(host, None)

    def preempt_notices(self) -> dict[str, dict]:
        """Outstanding external preemption notices by host (parsed
        ``PUT /preempt/<host>`` records)."""
        return self._scope_records(PREEMPT_SCOPE)

    def consume_preempt(self, host: str) -> None:
        """Drop a handled preemption notice so the drain fires once."""
        with self._httpd.lock:  # type: ignore[attr-defined]
            self._httpd.store.get(  # type: ignore[attr-defined]
                PREEMPT_SCOPE, {}).pop(host, None)

    # -- integrity defense plane ----------------------------------------------

    def heartbeat_version(self) -> int:
        """Monotonic heartbeat-store mutation counter (bumped on every
        heartbeat PUT and ``clear_heartbeat``): lets pollers skip
        re-parsing every heartbeat body when nothing has changed."""
        return getattr(self._httpd, "hb_version", 0)

    def integrity_records(self) -> dict[int, dict]:
        """Per-rank integrity fingerprints from the heartbeat piggyback
        — what the driver's voting tick consumes."""
        return _integrity_records(self._httpd)

    def integrity_vote_cached(self):
        """(records, voted) via the ``(hb_version, world_np)``-keyed
        cache shared with the live-vote fence and ``GET /integrity`` —
        the driver's voting tick must not re-parse every heartbeat body
        when the in-process fence already did."""
        return _cached_integrity_vote(self._httpd)

    def integrity_summary(self) -> dict:
        """The collected records + live vote (what ``GET /integrity``
        serves over HTTP), rendered in-process."""
        return _render_integrity(self._httpd)

    def record_integrity_divergence(self, host: str) -> None:
        """Count one divergence vote against ``host`` into the scrape's
        ``hvd_integrity_divergence_total{host}``."""
        with self._httpd.lock:  # type: ignore[attr-defined]
            counts = self._httpd.integrity_divergence  # type: ignore[attr-defined]
            counts[host] = counts.get(host, 0) + 1

    def quarantine_rank(self, rank, host: str, generation: int,
                        step: int, from_generation: int | None = None,
                        from_step: int | None = None) -> None:
        """Fence a divergent rank's peer-replica PUTs and EVICT its
        current ``peerstate`` record (the corrupt shard): the retained
        ``.prev`` slot — the last commit the vote did not condemn —
        stays, so peer-rung assembly falls back one commit instead of
        installing corruption. The fence lifts when a write arrives from
        a strictly newer world generation (the re-formed world reuses
        the rank id for a healthy worker). ``generation``/``step`` are
        the VOTE's group (the fence-lift anchor);
        ``from_generation``/``from_step`` (default: the same group) are
        where the condemned range STARTS — a vote that back-dated the
        corruption to a prior generation's fingerprint condemns that
        generation's replica records too."""
        with self._httpd.lock:  # type: ignore[attr-defined]
            self._httpd.integrity_quarantine[str(rank)] = {  # type: ignore[attr-defined]
                "host": str(host),
                "generation": int(generation),
                "step": int(step),
                "from_generation": int(generation if from_generation is None
                                       else from_generation),
                "from_step": int(step if from_step is None else from_step),
                "t": time.time(),
            }
            self._httpd.store.get(  # type: ignore[attr-defined]
                PEERSTATE_SCOPE, {}).pop(str(rank), None)

    def quarantine_export(self) -> dict:
        """The integrity-quarantine map (incl. tombstones), JSON-able —
        persisted in the driver snapshot so a takeover driver's fresh
        server re-fences a condemned rank instead of re-admitting its
        proven-corrupt replicas to peer-rung assembly."""
        with self._httpd.lock:  # type: ignore[attr-defined]
            return {r: dict(e) for r, e in
                    self._httpd.integrity_quarantine.items()}  # type: ignore[attr-defined]

    def restore_quarantine(self, entries) -> None:
        if not isinstance(entries, dict):
            return
        with self._httpd.lock:  # type: ignore[attr-defined]
            for r, e in entries.items():
                if isinstance(e, dict):
                    self._httpd.integrity_quarantine[str(r)] = dict(e)  # type: ignore[attr-defined]

    def metrics_text(self) -> str:
        """The scrape body, rendered in-process (what ``GET /metrics``
        serves over HTTP)."""
        return _render_cluster_metrics(self._httpd)

    def timeline_json(self) -> dict:
        """The merged cross-rank Chrome trace (what ``GET /timeline``
        serves over HTTP), rendered in-process."""
        return _render_timeline(self._httpd)

    def criticalpath_summary(self, steps: int | None = None,
                             rank: str | None = None) -> dict:
        """The merged step attribution (what ``GET /criticalpath``
        serves over HTTP), rendered in-process."""
        return _render_criticalpath(self._httpd, steps=steps, rank=rank)

    def regression_suspects(self) -> dict[str, float]:
        """The regression sentinel's advisory {host: excess seconds}
        map — non-empty only while a phase baseline is in alarm, naming
        the critical path's gating host. The elastic driver feeds this
        to the policy controller when ``HOROVOD_POLICY_STEP_REGRESSION``
        arms that channel. Ticks the (cached) analysis first so the map
        reflects the latest shipped traces."""
        try:
            _cluster_attribution(self._httpd)
        except Exception:  # noqa: BLE001 — advisory channel
            pass
        with self._httpd.lock:  # type: ignore[attr-defined]
            return dict(getattr(self._httpd, "regression_suspects", {}))

    def straggler_summary(self) -> dict:
        """The arrival-skew attribution (what ``GET /stragglers``
        serves), rendered in-process."""
        return _compute_cluster_skew(self._httpd)[0]

    def comms_summary(self) -> dict:
        """The cluster-merged α–β link cost model (what ``GET /comms``
        serves), rendered in-process. Its ``"residuals"`` map (host →
        worst predicted-vs-observed residual seconds) is the second
        straggler-evidence channel the elastic driver feeds
        ``elastic/policy.py``."""
        return _render_comms(self._httpd)

    def memory_summary(self) -> dict:
        """The cluster-merged HBM breakdown (what ``GET /memory``
        serves), rendered in-process — per-rank resident bytes by kind,
        phase watermark maxes, the minimum headroom ratio, and the
        worst model drift."""
        return _render_memory(self._httpd)

    def trace_payload(self, host: str) -> dict | None:
        """The last trace payload a host shipped, parsed, or None."""
        return _trace_payloads(self._httpd).get(host)

    def start(self) -> int:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="hvd-rendezvous", daemon=True
        )
        self._thread.start()
        return self.port

    def reset(self) -> int:
        """Elastic reconfiguration: clear state, bump the world version."""
        with self._httpd.lock:  # type: ignore[attr-defined]
            self._httpd.store.clear()  # type: ignore[attr-defined]
            self._httpd.version += 1  # type: ignore[attr-defined]
            # Trace scope went with the store: invalidate the cached
            # attribution analysis or /criticalpath would keep serving
            # the dead world's groups.
            self._httpd.trace_version = (  # type: ignore[attr-defined]
                getattr(self._httpd, "trace_version", 0) + 1)
            return self._httpd.version  # type: ignore[attr-defined]

    def publish_epoch(self, scope_prefix: str, data: dict[str, bytes],
                      keep_epochs: int = 2) -> int:
        """Atomically publish a new epoch: write ``<scope_prefix>/<v+1>``
        first, THEN bump the version — in-flight readers of the previous
        epoch keep seeing their scope (the last ``keep_epochs`` are kept)."""
        with self._httpd.lock:  # type: ignore[attr-defined]
            version = self._httpd.version + 1  # type: ignore[attr-defined]
            store = self._httpd.store  # type: ignore[attr-defined]
            store[f"{scope_prefix}/{version}"] = dict(data)
            stale = version - keep_epochs
            if stale > 0:
                store.pop(f"{scope_prefix}/{stale}", None)
            self._httpd.version = version  # type: ignore[attr-defined]
            return version

    # -- coordinated abort plane --------------------------------------------

    def post_abort(self, reason: str, generation: int | None = None) -> int:
        """Post the abort record for a world generation (default: the
        current one). Every worker of that generation polls it and
        converts its current wedge into ``HorovodInternalError``; posted
        BEFORE the driver bumps the generation so survivors still at the
        dying generation see it. Returns the generation posted for."""
        record = json.dumps({"reason": reason, "time": time.time()}).encode()
        with self._httpd.lock:  # type: ignore[attr-defined]
            gen = (self._httpd.version  # type: ignore[attr-defined]
                   if generation is None else generation)
            self._httpd.store.setdefault(  # type: ignore[attr-defined]
                ABORT_SCOPE, {})[str(gen)] = record
        return gen

    def abort_record(self, generation: int) -> bytes | None:
        with self._httpd.lock:  # type: ignore[attr-defined]
            return self._httpd.store.get(  # type: ignore[attr-defined]
                ABORT_SCOPE, {}).get(str(generation))

    # -- heartbeat liveness plane -------------------------------------------

    def heartbeat_ages(self) -> dict[str, float]:
        """Seconds since each host's last heartbeat (server clock)."""
        now = time.monotonic()
        with self._httpd.lock:  # type: ignore[attr-defined]
            return {h: now - t
                    for h, t in self._httpd.hb_times.items()}  # type: ignore[attr-defined]

    def heartbeat_age(self, host: str) -> float | None:
        """Seconds since `host`'s last heartbeat, or None if never seen."""
        with self._httpd.lock:  # type: ignore[attr-defined]
            t = self._httpd.hb_times.get(host)  # type: ignore[attr-defined]
        return None if t is None else time.monotonic() - t

    def heartbeat_payload(self, host: str) -> bytes | None:
        """The host's last heartbeat body (JSON: step/commit counters)."""
        with self._httpd.lock:  # type: ignore[attr-defined]
            return self._httpd.store.get(  # type: ignore[attr-defined]
                HEARTBEAT_SCOPE, {}).get(host)

    def clear_heartbeat(self, host: str) -> None:
        """Forget a host's liveness record (worker relaunch/removal): a
        stale timestamp must neither mask a hung relaunch nor instantly
        condemn a fresh one. The host's trace payload goes with it — a
        departed rank's spans must not keep skewing the merged timeline
        and straggler gauges against the re-formed world."""
        with self._httpd.lock:  # type: ignore[attr-defined]
            self._httpd.hb_times.pop(host, None)  # type: ignore[attr-defined]
            self._httpd.store.get(  # type: ignore[attr-defined]
                HEARTBEAT_SCOPE, {}).pop(host, None)
            self._httpd.store.get(  # type: ignore[attr-defined]
                TRACE_SCOPE, {}).pop(host, None)
            # The departed host's fingerprint left the record set: the
            # live-vote fence must not keep serving a vote over it.
            self._httpd.hb_version = (  # type: ignore[attr-defined]
                getattr(self._httpd, "hb_version", 0) + 1)
            # Its trace payload left too: the attribution cache must
            # re-analyze without the departed rank's spans.
            self._httpd.trace_version = (  # type: ignore[attr-defined]
                getattr(self._httpd, "trace_version", 0) + 1)

    def stop(self) -> None:
        self._httpd.shutdown()
        if self._thread:
            self._thread.join(timeout=5)
        self._httpd.server_close()


class KVClient:
    """Worker-side client for the rendezvous KV server. Signs every
    request with the job secret when HOROVOD_SECRET_KEY is set.

    Every request retries transient transport failures with bounded
    exponential backoff + jitter (``HOROVOD_KV_RETRIES`` attempts, base
    ``HOROVOD_KV_RETRY_BACKOFF`` seconds): a driver mid-restart or a
    network blip below the retry budget is fully absorbed, while a dead
    driver still surfaces as an exception the caller's escalation path
    (``worker.start_polling``) can act on — never an unbounded silent
    retry. HTTP status answers (404 = no value, 403 = bad auth, 409 =
    fenced stale-generation write) are answers, not blips, and propagate
    immediately.

    ``generation_fn`` (elastic workers pass their live world-generation
    view) stamps every write with ``X-Hvd-Generation`` so the server's
    fence can reject zombies from a pre-abort world; ``epoch_fn``
    likewise stamps ``X-Hvd-Driver-Epoch`` (the split-brain fence: a
    write still loyal to a superseded driver's epoch is 409'd). ``None``
    (or a fn returning ``None``) leaves writes unfenced.
    """

    def __init__(self, addr: str, port: int, timeout: float = 10.0,
                 retries: int | None = None, backoff: float | None = None,
                 generation_fn: Callable[[], int | None] | None = None,
                 epoch_fn: Callable[[], int | None] | None = None):
        self._base = f"http://{addr}:{port}"
        self._timeout = timeout
        self._retries = (get_int("HOROVOD_KV_RETRIES", 3)
                         if retries is None else retries)
        self._backoff = (get_float("HOROVOD_KV_RETRY_BACKOFF", 0.1)
                         if backoff is None else backoff)
        self._generation_fn = generation_fn
        self._epoch_fn = epoch_fn

    def _request(self, method: str, path: str, body: bytes | None = None):
        def attempt():
            if faults.fire(faults.KV_REQUEST):
                # drop: the request never happened — to the caller that is
                # a transport failure, so surface it as one (and retry).
                raise faults.InjectedFault(f"kv request dropped: {path}")
            req = Request(f"{self._base}{path}", data=body, method=method)
            tag = _secret.sign(_auth_payload(method, path, body or b""))
            if tag:
                req.add_header(AUTH_HEADER, tag)
            if self._generation_fn is not None and method in ("PUT",
                                                              "DELETE"):
                gen = self._generation_fn()
                if gen is not None:
                    if faults.fire(faults.KV_FENCE):
                        # Chaos: impersonate a zombie from the pre-abort
                        # world — the server must 409 this write.
                        gen -= 1
                    req.add_header(GENERATION_HEADER, str(gen))
            if self._epoch_fn is not None and method in ("PUT", "DELETE"):
                epoch = self._epoch_fn()
                if epoch is not None:
                    req.add_header(DRIVER_EPOCH_HEADER, str(epoch))
            return urlopen(req, timeout=self._timeout)

        return call_with_retries(
            attempt,
            attempts=max(1, self._retries),
            base_delay=self._backoff,
            give_up_on=(HTTPError,),
        )

    def put(self, scope: str, key: str, value: bytes) -> bytes:
        """Write one key; returns the reply body (heartbeat PUTs carry
        the server's wall clock there — see ``tracing.ClockSync``)."""
        with self._request("PUT", f"/{scope}/{key}", value) as r:
            return r.read()

    def get(self, scope: str, key: str) -> bytes | None:
        try:
            with self._request("GET", f"/{scope}/{key}") as r:
                return r.read()
        except HTTPError as e:
            if e.code == 404:
                return None
            raise

    def integrity_view(self) -> dict:
        """``GET /integrity`` (auth-exempt): the SDC defense plane's
        collected fingerprints, live vote, and quarantine map — what the
        peer-replica assembly consults so a condemned rank's records are
        dropped from its LOCAL pool too, not just evicted from the KV."""
        with self._request("GET", "/integrity") as r:
            return json.loads(r.read().decode())

    def model_view(self) -> dict:
        """``GET /model`` (auth-exempt): the training→serving bridge's
        health/age view — the newest assemblable ``modelstate`` commit,
        publish counters, and the model age (what serving readiness
        probes and the premerge HTTP gate poll)."""
        with self._request("GET", "/model") as r:
            return json.loads(r.read().decode())

    def keys(self, scope: str) -> list[str]:
        with self._request("GET", f"/_scope/{scope}") as r:
            body = r.read().decode()
        return [k for k in body.split("\n") if k]

    def delete_scope(self, scope: str) -> None:
        with self._request("DELETE", f"/{scope}"):
            pass

    def world_version(self) -> int:
        with self._request("GET", "/_version") as r:
            return int(r.read())

    def driver_epoch(self) -> int:
        """The serving driver's epoch (``GET /_epoch``; 0 when the
        driver-state plane is off)."""
        with self._request("GET", "/_epoch") as r:
            return int(r.read())

    def abort_posted(self, generation: int) -> dict | None:
        """The abort record for a world generation, or None. Decoded JSON
        (``{"reason", "time", ...}``); raw text falls back to a dict."""
        raw = self.get(ABORT_SCOPE, str(generation))
        if raw is None:
            return None
        try:
            return json.loads(raw)
        except ValueError:
            return {"reason": raw.decode(errors="replace")}
