from .kv_server import KVClient, RendezvousServer  # noqa: F401
