"""Host parsing and rank assignment for the launcher.

TPU-native re-design of the reference's host bookkeeping
(``horovod/runner/common/util/hosts.py — parse_hosts, get_host_assignments``).
The reference assigns one rank per GPU in host:slot order. Here the unit of
launch is one **controller process per host** (JAX single-controller SPMD: a
process drives all of its host's chips), so "slots" count the chips a host
contributes — they size the per-host device world, not extra processes.

For CPU dev-mode (``--cpu-mode``), slots instead mean emulated device ranks:
each process is told to fabricate ``slots`` virtual CPU devices via
``XLA_FLAGS=--xla_force_host_platform_device_count``.
"""

from __future__ import annotations

import dataclasses
import re


class HostParseError(ValueError):
    pass


@dataclasses.dataclass(frozen=True)
class HostInfo:
    """One host spec: ``hostname:slots`` (slots default 1)."""

    hostname: str
    slots: int

    @classmethod
    def from_string(cls, spec: str) -> "HostInfo":
        spec = spec.strip()
        m = re.fullmatch(r"([^\s:]+)(?::(\d+))?", spec)
        if not m:
            raise HostParseError(f"bad host spec {spec!r}; expected host[:slots]")
        slots = int(m.group(2)) if m.group(2) else 1
        if slots < 1:
            raise HostParseError(f"bad slot count in {spec!r}: must be >= 1")
        return cls(m.group(1), slots)


def parse_hosts(hosts_string: str) -> list[HostInfo]:
    """Parse ``-H h1:4,h2:4`` (comma separated host:slots)."""
    hosts = [
        HostInfo.from_string(s) for s in hosts_string.split(",") if s.strip()
    ]
    if not hosts:
        raise HostParseError(f"no hosts in {hosts_string!r}")
    seen: set[str] = set()
    for h in hosts:
        if h.hostname in seen:
            raise HostParseError(f"duplicate host {h.hostname!r}")
        seen.add(h.hostname)
    return hosts


def parse_hostfile(path: str) -> list[HostInfo]:
    """Parse a hostfile: one ``host slots=N`` or ``host:N`` per line."""
    hosts: list[HostInfo] = []
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            m = re.fullmatch(r"(\S+)\s+slots\s*=\s*(\d+)", line)
            if m:
                hosts.append(HostInfo(m.group(1), int(m.group(2))))
            else:
                hosts.append(HostInfo.from_string(line))
    if not hosts:
        raise HostParseError(f"hostfile {path!r} contains no hosts")
    return hosts


@dataclasses.dataclass(frozen=True)
class ProcessAssignment:
    """One launched worker process and its world facts.

    ``rank`` here is the *process* rank (the reference's rank): the device
    ranks a process owns are ``[first_device_rank, first_device_rank +
    num_devices)`` in the canonical ICI order once JAX initializes.
    """

    hostname: str
    rank: int  # process index (HOROVOD_PROCESS_ID / jax process_index)
    size: int  # total processes
    local_rank: int  # index among processes on this host (always 0 here)
    local_size: int  # processes on this host (always 1: one per host)
    cross_rank: int  # host index
    cross_size: int  # number of hosts
    slots: int  # chips this host contributes (device count)
    first_device_rank: int  # offset of this host's devices in rank space


def get_host_assignments(
    hosts: list[HostInfo], np: int | None = None
) -> list[ProcessAssignment]:
    """Assign one controller process per host, hosts in given order.

    Parity: ``horovod/runner/common/util/hosts.py — get_host_assignments``,
    re-shaped for the one-process-per-host model. ``np`` (if given) limits the
    number of *processes* (hosts used); the reference's per-GPU ``-np`` maps
    to the chip total, which is ``sum(slots)`` of the hosts used.

    Host order is rank order at the process level; within the device world,
    ``horovod_tpu.topology`` re-sorts chips into ICI order at init. Keeping
    the host list stable across elastic re-assignments minimizes rank churn
    (the reference rebalances the same way).
    """
    use = hosts if np is None else hosts[:np]
    if np is not None and len(hosts) < np:
        raise HostParseError(
            f"requested {np} processes but only {len(hosts)} hosts available"
        )
    out: list[ProcessAssignment] = []
    offset = 0
    for i, h in enumerate(use):
        out.append(
            ProcessAssignment(
                hostname=h.hostname,
                rank=i,
                size=len(use),
                local_rank=0,
                local_size=1,
                cross_rank=i,
                cross_size=len(use),
                slots=h.slots,
                first_device_rank=offset,
            )
        )
        offset += h.slots
    return out


def total_slots(assignments: list[ProcessAssignment]) -> int:
    return sum(a.slots for a in assignments)
