"""Communication observatory: the online α–β link cost model.

ROADMAP item 1 (TACCL-style collective synthesis) needs a *model* of what
the interconnect actually delivers — per (collective op, algorithm, link
class) — whose ground truth is the latencies the metrics/tracing planes
already measure. The classic decomposition (the MPI characterization
study, PAPERS.md arXiv:1810.11112) is the α–β model::

    t(bytes) = α + β · bytes        # α = launch/latency, β = 1/bandwidth

This module fits that model ONLINE, per key ``(op, algorithm,
link_class)``:

- **Samples** arrive from the eager dispatch path
  (``ops/collective_ops._eager_dispatch`` observes every timed eager
  collective), from an explicit **microprobe**
  (``ops.collective_ops.run_comms_microprobe`` — small/large payload
  sweeps over a process set, the seeding pass ``bench.py``'s comms lane
  runs), and from shipped trace spans whose names carry the fusion
  pass's static bucket bytes (``allreduce.bucket0.1048576B`` — see
  :func:`ingest_steps`).
- **Fit** is exponentially-weighted least squares
  (``HOROVOD_COMMS_DECAY``): old samples decay so a drifting link
  re-fits instead of being averaged away, with confidence intervals
  from the weighted residual variance and min-sample gating
  (``HOROVOD_COMMS_MIN_SAMPLES``) so a two-point fluke never drives a
  decision.
- **Consumers**: the live roofline gauges
  (``hvd_link_bandwidth_bytes_per_second{link_class,op,algorithm}``,
  ``hvd_link_latency_seconds{link_class,op}``,
  ``hvd_collective_efficiency_ratio`` — achieved vs α–β-predicted), the
  per-host predicted-vs-observed residual gauge
  (``hvd_comms_residual_seconds`` — a link going bad shows up as a
  residual before it shows up as cross-rank skew, so
  ``elastic/policy.py`` consumes it as a second straggler-evidence
  channel), ``GET /comms`` on the rendezvous KV server (per-rank
  payloads piggybacked on heartbeats, cluster-merged by
  :func:`merge_payloads`), ``profiler.summary()["comms"]``, and the
  model-guided autotune mode (:func:`prune_candidates` — predicted
  candidate costs prune dominated grid points before the measured
  sweep; see ``autotune.py``).

Algorithm vocabulary (the ``algorithm`` label): ``flat`` (one flat
ring collective — every eager dispatch), ``hierarchical`` (the 2-level
ICI×DCN legs), ``rs_ag`` (the sharded mode's reduce-scatter + allgather
halves), ``fsdp`` (the fsdp gather/scatter halves — K per-segment
collectives per step, so per-algorithm attribution is where the signal
is). Byte counts follow the stacked-rank payload convention of
``hvd_collective_payload_bytes`` so the two planes agree.

Stdlib-only and jax-free by design (like ``tracing.py``/``peercheck.py``):
the rendezvous KV server imports :func:`merge_payloads` on the driver
before any framework init.
"""

from __future__ import annotations

import math
import os
import re
import socket
import threading
import time
from typing import Any, Callable, Mapping, Sequence

from . import faults
from .utils.env import get_float, get_int

#: Canonical link classes (`link_class` label values).
LINK_CLASSES = ("ici", "dcn")

#: Canonical algorithm tags (`algorithm` label values). ``rhd`` and
#: ``two_level`` are the comms planner's scheduled algorithms
#: (``ops/comms_planner.py``) — each gets its own LinkFit, which is what
#: closes the model's own training loop: plans are priced by fits the
#: planned dispatches themselves feed.
ALGORITHMS = ("flat", "hierarchical", "rs_ag", "fsdp", "rhd", "two_level")

#: Span-name vocabulary carrying static bucket bytes (ops/fusion.py's
#: ``annotate_collective`` names and the eager dispatch span args). A
#: trailing ``.<algorithm>`` names the planner's chosen schedule
#: (``allreduce.bucket0.1048576B.two_level``); absent = flat. The MoE
#: dispatch/combine probes (``parallel/moe.py``) emit the same grammar
#: under a dotted op (``moe.dispatch.4224B.two_level``) so the
#: alltoall wire trains its own per-algorithm fits.
_BUCKET_NAME_RE = re.compile(
    r"^(?P<op>allreduce|reducescatter|allgather"
    r"|alltoall|moe\.(?:dispatch|combine))\."
    r"(?:bucket\d+\.)?(?P<bytes>\d+)B"
    r"(?:\.(?P<algo>[a-z0-9_]+))?$")


def min_samples() -> int:
    """Samples a fit needs before it predicts / drives decisions."""
    return max(2, get_int("HOROVOD_COMMS_MIN_SAMPLES", 4))


def decay() -> float:
    """Per-sample exponential decay of the fit's sufficient statistics
    (1.0 = never forget; smaller = faster drift tracking)."""
    d = get_float("HOROVOD_COMMS_DECAY", 0.98)
    return min(max(d, 0.5), 1.0)


def residual_alpha() -> float:
    """EWMA weight for the predicted-vs-observed residual channel."""
    a = get_float("HOROVOD_COMMS_RESIDUAL_ALPHA", 0.3)
    return min(max(a, 0.01), 1.0)


def _rank() -> str:
    return os.environ.get("HOROVOD_RANK", "0") or "0"


def _host() -> str:
    return os.environ.get("HOROVOD_HOSTNAME", "") or socket.gethostname()


def key_of(op: str, algorithm: str, link_class: str) -> str:
    """The wire/JSON form of a fit key."""
    return f"{op}|{algorithm}|{link_class}"


def split_key(key: str) -> tuple[str, str, str] | None:
    parts = str(key).split("|")
    if len(parts) != 3 or not all(parts):
        return None
    return (parts[0], parts[1], parts[2])


class LinkFit:
    """One (op, algorithm, link_class) α–β fit: exponentially-weighted
    least squares of latency on bytes, with confidence intervals.

    Sufficient statistics (weight n and the weighted sums Sx, Sy, Sxx,
    Sxy, Syy) decay by ``HOROVOD_COMMS_DECAY`` per sample, so the fit is
    an EWMA over the sample stream — a degrading link re-fits within
    ~1/(1-decay) samples instead of being diluted forever.
    """

    __slots__ = ("n", "sx", "sy", "sxx", "sxy", "syy", "count", "t_last",
                 "_lock")

    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0.0
        self.sx = self.sy = self.sxx = self.sxy = self.syy = 0.0
        self.count = 0
        self.t_last = 0.0

    def observe(self, nbytes: float, seconds: float) -> None:
        x, y = float(nbytes), float(seconds)
        if not (x >= 0.0) or not (y >= 0.0) \
                or not math.isfinite(x) or not math.isfinite(y):
            return  # NaN/inf/negative: a broken clock must not poison
            # the fit (inf passes a bare >= 0 check but turns β into
            # NaN while ready() stays True — permanent poisoning)
        d = decay()
        with self._lock:
            self.n = self.n * d + 1.0
            self.sx = self.sx * d + x
            self.sy = self.sy * d + y
            self.sxx = self.sxx * d + x * x
            self.sxy = self.sxy * d + x * y
            self.syy = self.syy * d + y * y
            self.count += 1
            self.t_last = time.time()

    # -- solve ----------------------------------------------------------------

    def _solve_locked(self) -> tuple[float, float | None]:
        """(alpha, beta): beta None when the sample xs are degenerate
        (all one payload size — only a latency mean is identifiable)."""
        if self.n <= 0:
            return 0.0, None
        mean_x = self.sx / self.n
        mean_y = self.sy / self.n
        sxx_c = self.sxx - self.n * mean_x * mean_x
        sxy_c = self.sxy - self.n * mean_x * mean_y
        if sxx_c <= max(1e-12, 1e-9 * self.sxx):
            return mean_y, None
        beta = sxy_c / sxx_c
        alpha = mean_y - beta * mean_x
        return alpha, beta

    def ready(self) -> bool:
        """Min-sample gate: enough raw samples AND ≥2 distinct payload
        sizes (otherwise β is unidentifiable)."""
        with self._lock:
            if self.count < min_samples():
                return False
            _, beta = self._solve_locked()
            return beta is not None

    def predict(self, nbytes: float) -> float | None:
        """α + β·bytes (clamped ≥ 0), or the latency mean when only one
        payload size was ever seen, or None before any sample."""
        with self._lock:
            if self.n <= 0:
                return None
            alpha, beta = self._solve_locked()
            if beta is None:
                return max(alpha, 0.0)
            return max(alpha + beta * float(nbytes), 0.0)

    def solved(self) -> tuple[float, float | None]:
        """The current (alpha, beta) — beta None when only one payload
        size was ever seen (a latency mean). The planner's snapshot
        entry (``ops/comms_planner._synced_snapshot``)."""
        with self._lock:
            return self._solve_locked()

    def as_dict(self) -> dict:
        """JSON-able fit summary (the ``/comms`` payload entry)."""
        with self._lock:
            alpha, beta = self._solve_locked()
            n_eff = self.n
            count = self.count
            out: dict[str, Any] = {
                "alpha_s": round(alpha, 9),
                "beta_s_per_byte": (round(beta, 15)
                                    if beta is not None else None),
                "bandwidth_bytes_per_second": (
                    round(1.0 / beta, 3)
                    if beta is not None and beta > 0 else None),
                "samples": count,
                "effective_samples": round(n_eff, 3),
                "t_last": self.t_last,
            }
            # Confidence intervals from the weighted residual variance:
            # s² = Syy_c·(1 − r²) / (n − 2), the standard OLS machinery
            # on decayed sums. Reported as ±95% half-widths.
            if beta is not None and n_eff > 2:
                mean_x = self.sx / n_eff
                mean_y = self.sy / n_eff
                sxx_c = self.sxx - n_eff * mean_x * mean_x
                syy_c = max(self.syy - n_eff * mean_y * mean_y, 0.0)
                ss_res = max(syy_c - beta * (self.sxy
                                             - n_eff * mean_x * mean_y), 0.0)
                s2 = ss_res / (n_eff - 2)
                se_beta = math.sqrt(s2 / sxx_c) if sxx_c > 0 else None
                se_alpha = (math.sqrt(s2 * (1.0 / n_eff
                                            + mean_x * mean_x / sxx_c))
                            if sxx_c > 0 else None)
                out["alpha_ci95_s"] = (round(1.96 * se_alpha, 9)
                                       if se_alpha is not None else None)
                out["beta_ci95"] = (round(1.96 * se_beta, 15)
                                    if se_beta is not None else None)
                out["r2"] = (round(1.0 - ss_res / syy_c, 4)
                             if syy_c > 0 else None)
        out["ready"] = self.ready()
        return out


class CommsModel:
    """The per-process observatory: fits by key, the efficiency/residual
    EWMAs, and the last-seen gradient leaf layout (the autotune
    predictor's input)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._fits: dict[tuple[str, str, str], LinkFit] = {}
        self._residual_ewma = 0.0
        self._efficiency_ewma: float | None = None
        self._leaf_sizes: list[tuple[int, str]] = []
        self._probes = 0
        self._export_skip: dict[tuple[str, str, str], int] = {}
        self._ready_exported: set[tuple[str, str, str]] = set()

    # -- intake ---------------------------------------------------------------

    def observe(self, op: str, algorithm: str, link_class: str,
                nbytes: float, seconds: float) -> None:
        """Fold one measured collective into the model.

        Fires the ``comms.link`` fault point first with DELAY semantics
        folded into the observation (an armed delay inflates the
        observed latency — the deterministic slow-link injector the
        residual-channel chaos tests ride). The residual/efficiency
        EWMAs are updated against the PRE-update prediction, so a
        degradation registers before the drifting fit absorbs it.
        """
        try:
            seconds = float(seconds)
            nbytes = float(nbytes)
        except (TypeError, ValueError):
            return
        if not (seconds >= 0.0) or not (nbytes >= 0.0) \
                or not math.isfinite(seconds) or not math.isfinite(nbytes):
            return  # NaN/inf/negative: a broken clock must not poison
            # the EWMAs below (LinkFit.observe guards itself too)
        t0 = time.monotonic()  # monotonic: an NTP step between the two
        if faults.fire(faults.COMMS_LINK):  # reads must not fake a
            return  # drop semantics (sample lost)   # slow link
        fired = time.monotonic() - t0
        if fired >= 1e-3:
            # An armed delay slept here: fold it into the observation
            # (the injected slow link). Below the threshold it is just
            # clock-read noise and must not perturb exact fits.
            seconds += fired
        fit = self._fit_for(op, algorithm, link_class, create=True)
        predicted = fit.predict(nbytes) if fit.ready() else None
        fit.observe(nbytes, seconds)
        if predicted is not None and predicted >= 0.0:
            a = residual_alpha()
            resid = max(seconds - predicted, 0.0)
            eff = (predicted / seconds if seconds > 0 else 1.0)
            eff = min(max(eff, 0.0), 2.0)
            with self._lock:
                self._residual_ewma += a * (resid - self._residual_ewma)
                prev = self._efficiency_ewma
                self._efficiency_ewma = (eff if prev is None
                                         else prev + a * (eff - prev))
        self._export_gauges(op, algorithm, link_class)

    def note_probe(self) -> None:
        with self._lock:
            self._probes += 1

    def note_leaf_sizes(self, sizes: Sequence[tuple[int, str]]) -> None:
        """Remember the gradient wire's leaf layout ``[(nbytes, dtype),
        ...]`` — recorded at trace time by the fusion pass / overlap
        scheduler. The LARGEST flush seen wins (segmented flushes note
        per-segment subsets; the full-model flush is the layout the
        autotune predictor wants)."""
        sizes = [(int(b), str(d)) for b, d in sizes if int(b) > 0]
        if not sizes:
            return
        with self._lock:
            if sum(b for b, _ in sizes) >= sum(
                    b for b, _ in self._leaf_sizes):
                self._leaf_sizes = sizes

    def leaf_sizes(self) -> list[tuple[int, str]]:
        with self._lock:
            return list(self._leaf_sizes)

    def ingest_steps(self, steps: Sequence[Mapping]) -> int:
        """Feed span records (the tracer ring / a shipped trace payload)
        whose names or args carry payload bytes — the fusion pass's
        ``<op>.bucketN.<bytes>B`` vocabulary and the eager dispatch
        spans. Malformed records are skipped. Returns samples folded."""
        folded = 0
        for steprec in steps or ():
            if not isinstance(steprec, Mapping):
                continue
            for sp in steprec.get("spans", ()) or ():
                if not isinstance(sp, Mapping):
                    continue
                if sp.get("cat") != "collective":
                    continue
                try:
                    dur = float(sp.get("dur", 0.0))
                except (TypeError, ValueError):
                    continue
                if not (dur > 0.0):  # rejects NaN too (NaN > 0 is False)
                    continue
                args = sp.get("args") or {}
                name = str(sp.get("name", ""))
                m = _BUCKET_NAME_RE.match(name.split("#")[0])
                nbytes = None
                op = None
                if isinstance(args, Mapping) and "bytes" in args:
                    try:
                        nbytes = float(args["bytes"])
                    except (TypeError, ValueError):
                        nbytes = None
                    op = str(args.get("op", "")) or None
                if nbytes is None and m is not None:
                    nbytes = float(m.group("bytes"))
                    op = m.group("op")
                    if op.startswith("moe."):
                        op = "alltoall"  # the MoE wire IS an alltoall
                if nbytes is None or op is None:
                    continue
                name_algo = (m.group("algo") or "flat") \
                    if m is not None else "flat"
                algorithm = str(args.get("algorithm", name_algo)) \
                    if isinstance(args, Mapping) else name_algo
                link = str(args.get("link_class", "ici")) \
                    if isinstance(args, Mapping) else "ici"
                self.observe(op, algorithm, link, nbytes, dur)
                folded += 1
        return folded

    # -- lookup / prediction --------------------------------------------------

    def _fit_for(self, op, algorithm, link_class,
                 create: bool = False) -> LinkFit | None:
        key = (str(op), str(algorithm), str(link_class))
        with self._lock:
            fit = self._fits.get(key)
            if fit is None and create:
                fit = self._fits[key] = LinkFit()
            return fit

    def predict(self, op: str, algorithm: str, link_class: str,
                nbytes: float) -> float | None:
        """Predicted seconds for one collective, with a documented
        fallback chain when the exact key has no ready fit: same op via
        the ``flat`` algorithm on the same link class, then same op on
        any link class, then the flat allreduce fit (every wire
        degenerates to 'a collective moving N bytes' at zeroth order).
        None when nothing relevant is fitted."""
        chain = [
            (op, algorithm, link_class),
            (op, "flat", link_class),
        ]
        with self._lock:
            any_link = [k for k in self._fits if k[0] == op]
        chain.extend(any_link)
        chain.append(("allreduce", "flat", link_class))
        with self._lock:
            flat_any = [k for k in self._fits if k[0] == "allreduce"]
        chain.extend(flat_any)
        seen = set()
        for key in chain:
            if key in seen:
                continue
            seen.add(key)
            fit = self._fit_for(*key)
            if fit is not None and fit.ready():
                return fit.predict(nbytes)
        return None

    def predict_exact(self, op: str, algorithm: str, link_class: str,
                      nbytes: float) -> float | None:
        """Predicted seconds from the EXACT (op, algorithm, link_class)
        key only — no fallback chain. The comms planner prices candidate
        algorithms against each other, where the chain's cross-algorithm
        substitutions would collapse every candidate onto one fit."""
        fit = self._fit_for(op, algorithm, link_class)
        if fit is None or not fit.ready():
            return None
        return fit.predict(nbytes)

    def fit_snapshot(self, ops: Sequence[str] | None = None,
                     algorithms: Sequence[str] | None = None
                     ) -> dict[str, tuple[float, float | None]]:
        """``{key: (alpha, beta)}`` over the READY fits (optionally
        filtered by op/algorithm) — the rank-portable form the planner
        broadcasts so every rank plans from rank 0's model."""
        with self._lock:
            fits = dict(self._fits)
        out: dict[str, tuple[float, float | None]] = {}
        for (op, algorithm, link_class), fit in fits.items():
            if ops is not None and op not in ops:
                continue
            if algorithms is not None and algorithm not in algorithms:
                continue
            if not fit.ready():
                continue
            out[key_of(op, algorithm, link_class)] = fit.solved()
        return out

    def ready(self) -> bool:
        with self._lock:
            fits = list(self._fits.values())
        return any(f.ready() for f in fits)

    def residual_s(self) -> float:
        with self._lock:
            return self._residual_ewma

    def efficiency(self) -> float | None:
        with self._lock:
            return self._efficiency_ewma

    # -- export ---------------------------------------------------------------

    def _export_gauges(self, op, algorithm, link_class) -> None:
        """Mirror the model into the scrape gauges (best-effort).

        The residual/efficiency EWMAs export on EVERY observation (two
        float sets — and they are the degradation signal that must stay
        fresh); the α/β fit export (``as_dict``'s CI math) is throttled
        per key to every 8th observation — the fit moves slowly and the
        gauges hold the last value between exports anyway."""
        key = (str(op), str(algorithm), str(link_class))
        with self._lock:
            skip = self._export_skip.get(key, 0)
            self._export_skip[key] = (skip + 1) % 8
        try:
            from . import metrics

            eff = self.efficiency()
            if eff is not None:
                metrics.COLLECTIVE_EFFICIENCY.set(eff)
            metrics.COMMS_RESIDUAL.set(self.residual_s())
            fit = self._fit_for(op, algorithm, link_class)
            if fit is None or not fit.ready():
                return
            with self._lock:
                first_ready = key not in self._ready_exported
                self._ready_exported.add(key)
            if skip and not first_ready:
                return
            d = fit.as_dict()
            bw = d.get("bandwidth_bytes_per_second")
            if bw is not None:
                metrics.LINK_BANDWIDTH.set(
                    bw, link_class=link_class, op=op,
                    algorithm=algorithm)
            metrics.LINK_LATENCY.set(
                max(d.get("alpha_s") or 0.0, 0.0),
                link_class=link_class, op=op)
        except Exception:  # noqa: BLE001 — gauges are advisory
            pass

    def payload(self) -> dict:
        """The per-rank wire format piggybacked on heartbeats and merged
        by ``GET /comms``. A model with no ready fit serves an explicit
        ``insufficient_samples`` status — never an error."""
        with self._lock:
            fits = dict(self._fits)
            probes = self._probes
        fit_dicts = {key_of(*k): f.as_dict() for k, f in fits.items()}
        status = ("ok" if any(d.get("ready") for d in fit_dicts.values())
                  else "insufficient_samples")
        eff = self.efficiency()
        # The comms planner's plan table rides along so GET /comms shows
        # WHY each bucket got its schedule (algorithm + provenance:
        # fitted model vs static_crossover vs a pin). Best-effort and
        # jax-guarded: on a driver-side import (no jax) the planner leg
        # degrades to an explicit disabled marker — never an error.
        try:
            from .ops.comms_planner import summary as _planner_summary

            planner = _planner_summary()
        except Exception:  # noqa: BLE001 — the plan view is advisory
            planner = {"enabled": False}
        return {
            "rank": _rank(),
            "host": _host(),
            "t": time.time(),
            "status": status,
            "residual_s": round(self.residual_s(), 9),
            "efficiency": round(eff, 4) if eff is not None else None,
            "samples_total": sum(d["samples"] for d in fit_dicts.values()),
            "probes": probes,
            "fits": fit_dicts,
            "planner": planner,
        }

    def summary(self) -> dict:
        """``profiler.summary()["comms"]``: the fitted model, sample
        counts, and the residual/efficiency EWMAs, process-local."""
        p = self.payload()
        return {
            "status": p["status"],
            "fits": p["fits"],
            "samples_total": p["samples_total"],
            "probes": p["probes"],
            "residual_s": p["residual_s"],
            "efficiency": p["efficiency"],
            "leaf_sizes_noted": len(self.leaf_sizes()),
        }


# ---------------------------------------------------------------------------
# Singleton + module facade
# ---------------------------------------------------------------------------

_lock = threading.Lock()
_model: CommsModel | None = None


def get_model() -> CommsModel:
    global _model
    with _lock:
        if _model is None:
            _model = CommsModel()
        return _model


def reset_for_testing() -> None:
    """Fresh model (``hvd.cache_stats()``-style reset semantics: the
    singleton is replaced, env knobs re-read on next use)."""
    global _model
    with _lock:
        _model = None


def observe(op: str, algorithm: str, link_class: str, nbytes: float,
            seconds: float) -> None:
    get_model().observe(op, algorithm, link_class, nbytes, seconds)


def summary() -> dict:
    return get_model().summary()


# ---------------------------------------------------------------------------
# Microprobe (jax-free driver; the measure callable owns the collective)
# ---------------------------------------------------------------------------

#: Default probe payload sizes: a small/large sweep wide enough to
#: separate α (launch latency) from β (inverse bandwidth).
DEFAULT_PROBE_SIZES = (4096, 65536, 1 << 20)


def microprobe(measure: Callable[[int], float],
               op: str,
               algorithm: str = "flat",
               link_class: str = "ici",
               sizes: Sequence[int] | None = None,
               repeats: int = 3,
               model: CommsModel | None = None) -> dict:
    """Seed the model with an explicit payload sweep.

    ``measure(nbytes) -> seconds`` times ONE collective of that payload
    (the caller owns warmup/compile exclusion —
    ``ops.collective_ops.run_comms_microprobe`` is the jax-side
    convenience). Each (size, repeat) sample is folded via
    :meth:`CommsModel.observe`; returns ``{size: [seconds, ...]}``.
    """
    model = model or get_model()
    sizes = list(sizes or DEFAULT_PROBE_SIZES)
    out: dict[int, list[float]] = {}
    for nbytes in sizes:
        samples = []
        for _ in range(max(1, int(repeats))):
            seconds = float(measure(int(nbytes)))
            model.observe(op, algorithm, link_class, nbytes, seconds)
            samples.append(seconds)
        out[int(nbytes)] = samples
    model.note_probe()
    return out


# ---------------------------------------------------------------------------
# Cluster merge (driver-side; the KV server's GET /comms)
# ---------------------------------------------------------------------------


def merge_payloads(payloads: Mapping[str, Mapping]) -> dict:
    """Cluster-merged view over per-rank ``payload()`` dicts (keyed by
    host, as the heartbeat scope stores them). Malformed payloads are
    skipped — one broken worker must not break the merge. A cluster
    where nothing fitted yet reports ``status: insufficient_samples``
    with whatever partial per-rank state exists (never an error)."""
    ranks: dict[str, dict] = {}
    cluster: dict[str, dict] = {}
    residuals: dict[str, float] = {}
    for host, payload in (payloads or {}).items():
        if not isinstance(payload, Mapping):
            continue
        rank = str(payload.get("rank", "?"))
        fits = payload.get("fits")
        fits = fits if isinstance(fits, Mapping) else {}
        clean_fits: dict[str, dict] = {}
        for key, d in fits.items():
            if split_key(key) is None or not isinstance(d, Mapping):
                continue
            clean_fits[str(key)] = {
                str(fk): (None if isinstance(fv, float)
                          and not math.isfinite(fv) else fv)
                for fk, fv in d.items()}  # bare NaN/Infinity would make
            # the whole /comms body unparseable to strict JSON readers
        try:
            resid = float(payload.get("residual_s", 0.0) or 0.0)
        except (TypeError, ValueError):
            resid = 0.0
        if not (resid >= 0.0) or not math.isfinite(resid):
            resid = 0.0  # NaN/inf/negative must not poison the merge
            # (or emit NaN into the /comms JSON body)
        try:
            eff = payload.get("efficiency")
            eff = float(eff) if eff is not None else None
            if eff is not None and not math.isfinite(eff):
                eff = None
        except (TypeError, ValueError):
            eff = None
        try:
            samples_total = int(float(payload.get("samples_total", 0) or 0))
        except (TypeError, ValueError, OverflowError):
            samples_total = 0  # OverflowError: int(inf); same
            # JSON-poisoning hazard as the fields above
        hostname = str(payload.get("host", host))
        if rank in ranks:
            # Self-reported rank labels can collide (HOROVOD_RANK unset
            # defaults every worker to "0"; a departed host's lingering
            # heartbeat can share a reassigned rank). Qualify by host so
            # no worker's model is silently last-writer-wins dropped.
            rank = f"{rank}@{hostname}"
        planner = payload.get("planner")
        ranks[rank] = {
            "host": hostname,
            "status": str(payload.get("status", "insufficient_samples")),
            "residual_s": round(resid, 9),
            "efficiency": eff,
            "samples_total": samples_total,
            "fits": clean_fits,
            "planner": (dict(planner) if isinstance(planner, Mapping)
                        else {"enabled": False}),
        }
        residuals[hostname] = max(residuals.get(hostname, 0.0), resid)
        for key, d in clean_fits.items():
            if not d.get("ready"):
                continue
            try:
                alpha = float(d["alpha_s"])
                beta = d.get("beta_s_per_byte")
                beta = float(beta) if beta is not None else None
                n = float(d.get("effective_samples", d.get("samples", 1)))
            except (KeyError, TypeError, ValueError):
                continue
            if (not math.isfinite(alpha) or not math.isfinite(n)
                    or (beta is not None and not math.isfinite(beta))):
                continue  # same JSON-poisoning hazard as residual_s
            slot = cluster.setdefault(key, {
                "alpha_s": 0.0, "beta_s_per_byte": 0.0, "weight": 0.0,
                "beta_weight": 0.0, "samples": 0, "ranks": 0})
            slot["alpha_s"] += alpha * n
            slot["weight"] += n
            if beta is not None:
                slot["beta_s_per_byte"] += beta * n
                slot["beta_weight"] += n
            slot["samples"] += int(d.get("samples", 0) or 0)
            slot["ranks"] += 1
    merged_cluster: dict[str, dict] = {}
    for key, slot in cluster.items():
        w = slot["weight"]
        bw_w = slot["beta_weight"]
        alpha = slot["alpha_s"] / w if w > 0 else 0.0
        beta = (slot["beta_s_per_byte"] / bw_w) if bw_w > 0 else None
        merged_cluster[key] = {
            "alpha_s": round(alpha, 9),
            "beta_s_per_byte": (round(beta, 15)
                                if beta is not None else None),
            "bandwidth_bytes_per_second": (
                round(1.0 / beta, 3)
                if beta is not None and beta > 0 else None),
            "samples": slot["samples"],
            "ranks": slot["ranks"],
        }
    status = ("ok" if any(r["status"] == "ok" for r in ranks.values())
              else "insufficient_samples")
    return {
        "status": status,
        "ranks": ranks,
        "cluster": merged_cluster,
        "residuals": {h: round(v, 9) for h, v in residuals.items()},
    }


# ---------------------------------------------------------------------------
# Candidate cost prediction + dominance pruning (the autotune consumer)
# ---------------------------------------------------------------------------


def prune_margin() -> float:
    """Dominance margin: a candidate is pruned only when its predicted
    cost exceeds the best predicted cost by more than this FACTOR —
    conservative by default, so model error prunes only clearly
    dominated grid points, never near-ties."""
    m = get_float("HOROVOD_AUTOTUNE_PRUNE_MARGIN", 1.5)
    return max(m, 1.0)


def bucket_byte_sizes(leaf_sizes: Sequence[tuple[int, str]],
                      threshold_bytes: int) -> list[int]:
    """Total bytes per fusion bucket for a leaf layout under a candidate
    threshold — a faithful stdlib mirror of ``ops.fusion.bucket_leaves``
    (order-preserving greedy same-dtype packing; threshold <= 0 means
    one bucket per leaf)."""
    buckets: list[int] = []
    bucket_dtype: str | None = None
    bucket_bytes = 0
    first = True
    for nbytes, dtype in leaf_sizes:
        nbytes = int(nbytes)
        if (threshold_bytes <= 0 or first or bucket_dtype != dtype
                or bucket_bytes + nbytes > threshold_bytes):
            buckets.append(nbytes)
            bucket_dtype = dtype
            bucket_bytes = nbytes
            first = False
        else:
            buckets[-1] += nbytes
            bucket_bytes += nbytes
    return buckets


def segment_byte_runs(leaf_sizes: Sequence[tuple[int, str]],
                      num_segments: int) -> list[list[tuple[int, str]]]:
    """Split a leaf layout into <= K contiguous byte-balanced runs — the
    stdlib mirror of ``ops.fusion.segment_leaves`` (byte-midpoint rule),
    so predicted per-segment bucketing matches what the scheduler will
    actually emit."""
    k = max(1, int(num_segments))
    sizes = [int(b) for b, _ in leaf_sizes]
    total = sum(sizes)
    if not sizes:
        return []
    if total <= 0 or k == 1:
        return [list(leaf_sizes)]
    runs: list[list[tuple[int, str]]] = [[] for _ in range(k)]
    cum = 0
    for leaf, nbytes in zip(leaf_sizes, sizes):
        mid = cum + nbytes / 2.0
        runs[min(k - 1, int(mid * k / total))].append(leaf)
        cum += nbytes
    return [r for r in runs if r]


#: Which collective halves each sync mode's gradient wire issues per
#: bucket (the per-algorithm attribution the predictor prices).
_MODE_WIRE = {
    "allreduce": (("allreduce", "flat"),),
    "sharded": (("reducescatter", "rs_ag"), ("allgather", "rs_ag")),
    "fsdp": (("allgather", "fsdp"), ("reducescatter", "fsdp")),
}

#: The comms planner's schedule vocabulary (mirrored from
#: ``ops/comms_planner.PLANNER_ALGORITHMS`` so this module stays
#: importable jax-free; ``auto`` names the un-pinned planner axis).
PLANNER_ALGORITHM_NAMES = ("flat", "rhd", "two_level", "auto")


def _planned_wire_algorithm(op: str, label: str, bucket_bytes: int,
                            algorithm: str | None) -> str:
    """The fit key a bucket's collective half should be priced under.

    ``algorithm`` explicit (an autotune candidate's axis): ``flat``
    keeps the mode's historical label (``flat``/``rs_ag``/``fsdp`` —
    those fits ARE the flat schedule's samples); a planner algorithm
    names its own key. ``None``/``auto``: ask the live planner what it
    would schedule for this bucket, so the prediction prices the
    PLANNED wire, not an assumed flat ring — degrading to the label
    when the planner is off or unimportable (driver-side, jax-free)."""
    if algorithm is not None and algorithm not in (None, "auto"):
        return label if algorithm == "flat" else algorithm
    try:
        from .ops.comms_planner import enabled, planned_algorithm

        if enabled():
            from .ops.comms_planner import default_world_size

            # sync=False: this predictor runs on rank-local paths (the
            # attribution plane's status thread, autotune pricing) that
            # must never block in the planner's snapshot broadcast.
            planned = planned_algorithm(op, bucket_bytes,
                                        default_world_size(), sync=False)
            if planned != "flat":
                return planned
    except Exception:  # noqa: BLE001 — planner is advisory here
        pass
    return label


def predict_flush_cost(leaf_sizes: Sequence[tuple[int, str]],
                       threshold_bytes: int,
                       num_segments: int = 1,
                       sync_mode: str = "allreduce",
                       link_class: str = "ici",
                       model: CommsModel | None = None,
                       algorithm: str | None = None) -> float | None:
    """Predicted per-step communication seconds for one autotune
    candidate: segment the leaf layout, bucket each run under the
    candidate threshold, and price every bucket's collective halves with
    the fitted α–β model (fallback chain in :meth:`CommsModel.predict`).
    ``algorithm`` — the joint grid's planner axis — prices the halves
    under that schedule's fit keys; None/``auto`` prices whatever the
    live planner would schedule per bucket (flat when it is off), so
    model-guided pruning and the attribution plane's exposed-comm
    residual see the PLANNED wire. None when the model cannot price the
    wire yet."""
    model = model or get_model()
    wire = _MODE_WIRE.get(str(sync_mode) or "allreduce",
                          _MODE_WIRE["allreduce"])
    total = 0.0
    for run in segment_byte_runs(leaf_sizes, num_segments):
        for bucket_bytes in bucket_byte_sizes(run, threshold_bytes):
            for op, label in wire:
                algo = _planned_wire_algorithm(op, label, bucket_bytes,
                                               algorithm)
                cost = model.predict(op, algo, link_class, bucket_bytes)
                if cost is None:
                    return None
                total += cost
    return total


def predict_step_comm_s(sync_mode: str | None = None,
                        link_class: str = "ici",
                        threshold_bytes: int | None = None,
                        num_segments: int | None = None,
                        model: CommsModel | None = None) -> float | None:
    """The fitted model's price for this process's gradient wire under
    the LIVE fusion configuration — the per-step communication roofline
    the attribution plane compares the *observed* exposed-comm phase
    against (``profiler.summary()["attribution"]``'s
    ``exposed_comm_predicted_s`` / ``exposed_comm_residual_s``).

    Unspecified axes resolve exactly like the wire itself would:
    threshold/segments through ``ops.fusion`` (autotune pin > config >
    env; jax-free env fallback on the driver), sync mode through the
    ``HOROVOD_SYNC_MODE`` contract. None until the model has both a
    ready fit and a noted leaf layout.
    """
    model = model or get_model()
    leaf_sizes = model.leaf_sizes()
    if not leaf_sizes:
        return None
    if threshold_bytes is None or num_segments is None:
        try:
            from .ops.fusion import fusion_threshold_bytes, overlap_segments

            if threshold_bytes is None:
                threshold_bytes = fusion_threshold_bytes()
            if num_segments is None:
                num_segments = overlap_segments()
        except Exception:  # noqa: BLE001 — driver side: jax-free env read
            from .utils.env import get_int as _get_int

            if threshold_bytes is None:
                threshold_bytes = _get_int("HOROVOD_FUSION_THRESHOLD",
                                           64 * 1024 * 1024)
            if num_segments is None:
                num_segments = max(
                    1, _get_int("HOROVOD_OVERLAP_SEGMENTS", 4))
    if sync_mode is None:
        sync_mode = (os.environ.get("HOROVOD_SYNC_MODE", "")
                     .strip().lower() or "allreduce")
    return predict_flush_cost(leaf_sizes, threshold_bytes, num_segments,
                              sync_mode, link_class, model=model)


def candidate_axes(candidate) -> tuple[int, int, str, str | None]:
    """Normalize an autotune grid candidate — an int threshold or a
    ``(threshold[, segments][, sync_mode][, algorithm])`` tuple — to
    ``(threshold, segments, sync_mode, algorithm)``. String items are
    assigned by vocabulary membership: planner algorithm names
    (:data:`PLANNER_ALGORITHM_NAMES`) land on the algorithm axis,
    anything else is a sync mode; ``algorithm`` is None when the grid
    has no planner axis."""
    if isinstance(candidate, (tuple, list)):
        threshold = int(candidate[0])
        segments = 1
        sync_mode = "allreduce"
        algorithm = None
        for item in candidate[1:]:
            if isinstance(item, str):
                if item in PLANNER_ALGORITHM_NAMES:
                    algorithm = item
                else:
                    sync_mode = item
            else:
                segments = int(item)
        return threshold, segments, sync_mode, algorithm
    return int(candidate), 1, "allreduce", None


def prune_candidates(candidates: Sequence[Any],
                     leaf_sizes: Sequence[tuple[int, str]],
                     link_class: str = "ici",
                     margin: float | None = None,
                     model: CommsModel | None = None) -> dict:
    """Model-guided dominance pruning of an autotune grid.

    Pure and deterministic: the same (candidates, leaf layout, fitted
    model) always yields the same verdicts — the rank-identical
    guarantee reduces to feeding every rank the same inputs, which
    ``autotune.AutotuneStep`` ensures by broadcasting rank 0's kept
    list (the same exchange its winner already rides).

    A candidate is kept unless its predicted cost exceeds the best
    predicted cost by more than ``margin`` (default
    ``HOROVOD_AUTOTUNE_PRUNE_MARGIN``); candidates the model cannot
    price are always kept. Dominance is judged WITHIN each sync-mode
    group only: fits for the rs_ag/fsdp halves usually resolve through
    the flat-allreduce fallback, which systematically overprices those
    wires (two halves at full bucket bytes vs one ring), so a
    cross-mode comparison could prune the truly-best mode — while
    within one mode the bias is a common factor and threshold/segment
    dominance stays sound. A group whose best prediction is <= 0 (a
    noisy fit's clamped-negative α) is left unpruned: a free-comms
    model cannot rank anything. Returns ``{"kept", "pruned", "costs"}``
    with ``costs`` aligned to ``candidates`` (None = unpriced).
    """
    model = model or get_model()
    margin = prune_margin() if margin is None else max(float(margin), 1.0)
    costs: list[float | None] = []
    modes: list[str] = []
    for cand in candidates:
        threshold, segments, sync_mode, algorithm = candidate_axes(cand)
        modes.append(sync_mode)
        costs.append(predict_flush_cost(
            leaf_sizes, threshold, segments, sync_mode, link_class,
            model=model, algorithm=algorithm))
    if not leaf_sizes:
        return {"kept": list(candidates), "pruned": [], "costs": costs}
    best_by_mode: dict[str, float] = {}
    for mode, cost in zip(modes, costs):
        if cost is not None:
            best_by_mode[mode] = min(best_by_mode.get(mode, cost), cost)
    kept, pruned = [], []
    for cand, mode, cost in zip(candidates, modes, costs):
        best = best_by_mode.get(mode)
        if (cost is not None and best is not None and best > 0.0
                and cost > best * margin):
            pruned.append(cand)
        else:
            kept.append(cand)
    if not kept:  # numerical pathology: never prune the whole grid
        return {"kept": list(candidates), "pruned": [], "costs": costs}
    return {"kept": kept, "pruned": pruned, "costs": costs}
