"""Centralized environment-variable configuration.

TPU-native analog of the reference's ``horovod/common/utils/env_parser.cc``:
every runtime knob is an ``HOROVOD_*`` env var, parsed once into a typed
config object. The precedence contract mirrors the reference exactly
(API kwarg > env var > default; the launcher CLI writes env vars for its
children).

Knob names are kept identical to the reference where the concept survives
the port, so existing Horovod deployment scripts keep working.
"""

from __future__ import annotations

import dataclasses
import os


def get_bool(name: str, default: bool = False) -> bool:
    val = os.environ.get(name)
    if val is None:
        return default
    return val.strip().lower() in ("1", "true", "yes", "on")


def get_int(name: str, default: int) -> int:
    val = os.environ.get(name)
    if val is None or not val.strip():
        return default
    try:
        return int(val)
    except ValueError:
        return default

def get_float(name: str, default: float) -> float:
    val = os.environ.get(name)
    if val is None or not val.strip():
        return default
    try:
        return float(val)
    except ValueError:
        return default


def get_str(name: str, default: str = "") -> str:
    return os.environ.get(name, default)


@dataclasses.dataclass
class RuntimeConfig:
    """Typed view of all HOROVOD_* runtime knobs.

    Fields map 1:1 onto the reference's env contract
    (``horovod/common/utils/env_parser.cc`` + ``common.h`` constants):

    - fusion_threshold_bytes: HOROVOD_FUSION_THRESHOLD (default 64 MiB). In
      the JAX path this is the trace-time gradient bucketing threshold; in the
      runtime path it sizes the native fusion buffer.
    - cycle_time_ms: HOROVOD_CYCLE_TIME — background-loop cadence of the
      native runtime (no-op for fully compiled JAX steps).
    - cache_capacity: HOROVOD_CACHE_CAPACITY — executable/response cache
      entries.
    - timeline_path: HOROVOD_TIMELINE — Chrome-trace output path.
    - stall_warning_s / stall_shutdown_s: HOROVOD_STALL_CHECK_TIME /
      HOROVOD_STALL_SHUTDOWN_TIME.
    - autotune: HOROVOD_AUTOTUNE (+ HOROVOD_AUTOTUNE_LOG).
    - hierarchical_allreduce: HOROVOD_HIERARCHICAL_ALLREDUCE — two-level
      ICI/DCN reduction.
    - num_ranks/rank/...: world facts written by the launcher.
    """

    fusion_threshold_bytes: int = 64 * 1024 * 1024
    cycle_time_ms: float = 1.0
    cache_capacity: int = 1024
    timeline_path: str = ""
    timeline_mark_cycles: bool = False
    stall_warning_s: float = 60.0
    stall_shutdown_s: float = 0.0
    autotune: bool = False
    autotune_log: str = ""
    hierarchical_allreduce: bool = False
    log_level: str = "warning"

    # World facts (written by the launcher for multi-process mode).
    rank: int = -1
    size: int = -1
    local_rank: int = -1
    local_size: int = -1
    cross_rank: int = -1
    cross_size: int = -1
    rendezvous_addr: str = ""
    rendezvous_port: int = -1
    controller: str = ""

    @classmethod
    def from_env(cls) -> "RuntimeConfig":
        return cls(
            fusion_threshold_bytes=get_int(
                "HOROVOD_FUSION_THRESHOLD", 64 * 1024 * 1024
            ),
            cycle_time_ms=get_float("HOROVOD_CYCLE_TIME", 1.0),
            cache_capacity=get_int("HOROVOD_CACHE_CAPACITY", 1024),
            timeline_path=get_str("HOROVOD_TIMELINE"),
            timeline_mark_cycles=get_bool("HOROVOD_TIMELINE_MARK_CYCLES"),
            stall_warning_s=get_float("HOROVOD_STALL_CHECK_TIME", 60.0),
            stall_shutdown_s=get_float("HOROVOD_STALL_SHUTDOWN_TIME", 0.0),
            autotune=get_bool("HOROVOD_AUTOTUNE"),
            autotune_log=get_str("HOROVOD_AUTOTUNE_LOG"),
            hierarchical_allreduce=get_bool("HOROVOD_HIERARCHICAL_ALLREDUCE"),
            log_level=get_str("HOROVOD_LOG_LEVEL", "warning"),
            rank=get_int("HOROVOD_RANK", -1),
            size=get_int("HOROVOD_SIZE", -1),
            local_rank=get_int("HOROVOD_LOCAL_RANK", -1),
            local_size=get_int("HOROVOD_LOCAL_SIZE", -1),
            cross_rank=get_int("HOROVOD_CROSS_RANK", -1),
            cross_size=get_int("HOROVOD_CROSS_SIZE", -1),
            rendezvous_addr=get_str("HOROVOD_GLOO_RENDEZVOUS_ADDR"),
            rendezvous_port=get_int("HOROVOD_GLOO_RENDEZVOUS_PORT", -1),
            controller=get_str("HOROVOD_CONTROLLER"),
        )
