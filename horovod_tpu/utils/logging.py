"""Leveled logging, mirroring the reference's ``horovod/common/logging.cc``.

``HOROVOD_LOG_LEVEL`` in {trace, debug, info, warning, error, fatal};
``HOROVOD_LOG_TIMESTAMP`` / ``HOROVOD_LOG_HIDE_TIME`` control the prefix.
"""

from __future__ import annotations

import logging
import os
import sys

_LEVELS = {
    "trace": 5,
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "fatal": logging.CRITICAL,
}

logging.addLevelName(5, "TRACE")

_logger: logging.Logger | None = None


def get_logger() -> logging.Logger:
    global _logger
    if _logger is None:
        logger = logging.getLogger("horovod_tpu")
        level = os.environ.get("HOROVOD_LOG_LEVEL", "warning").lower()
        logger.setLevel(_LEVELS.get(level, logging.WARNING))
        if not logger.handlers:
            handler = logging.StreamHandler(sys.stderr)
            if os.environ.get("HOROVOD_LOG_HIDE_TIME"):
                fmt = "[%(levelname)s] %(message)s"
            else:
                fmt = "%(asctime)s [%(levelname)s] %(message)s"
            handler.setFormatter(logging.Formatter(fmt))
            logger.addHandler(handler)
        logger.propagate = False
        _logger = logger
    return _logger
