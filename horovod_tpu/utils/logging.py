"""Leveled logging, mirroring the reference's ``horovod/common/logging.cc``.

``HOROVOD_LOG_LEVEL`` in {trace, debug, info, warning, error, fatal};
``HOROVOD_LOG_TIMESTAMP`` / ``HOROVOD_LOG_HIDE_TIME`` control the prefix.

Every record is additionally prefixed with ``[rank/size g<generation>]``
when the process runs inside a launched world (``HOROVOD_RANK`` set), the
generation part appearing only in elastic worlds — so the interleaved
stdout of a multi-worker job stays attributable per line without grepping
hostnames, and a line from generation 3 cannot be mistaken for the re-formed
generation 4's. On a multi-tenant pod (``HOROVOD_JOB_ID`` set by the
gang scheduler — ``runner/elastic/scheduler.py``) the prefix leads with
the job id — ``[job/rank/size g<gen>]`` for workers, ``[job]`` for the
job's rankless driver process — so two jobs' interleaved logs stay
attributable per line; an unset job id keeps the exact single-job prefix
(unprefixed-job: bit-for-bit HEAD). The prefix re-reads the env per
record: an elastic resize rewrites
``HOROVOD_RANK``/``HOROVOD_WORLD_VERSION`` in place (and the scheduler
sets ``HOROVOD_JOB_ID`` per job process tree), and the very next log
line must carry the new identity.
"""

from __future__ import annotations

import logging
import os
import sys

_LEVELS = {
    "trace": 5,
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "fatal": logging.CRITICAL,
}

logging.addLevelName(5, "TRACE")

_logger: logging.Logger | None = None


def rank_prefix() -> str:
    """``"[rank/size g<generation>] "`` for launched workers — with the
    job id prepended (``[job/rank/size g<gen>] ``) when ``HOROVOD_JOB_ID``
    is set — ``"[job] "`` for a job-tagged rankless process (the per-job
    elastic driver under the multi-tenant scheduler), and ``""``
    elsewhere (single-process scripts keep clean logs)."""
    job = os.environ.get("HOROVOD_JOB_ID") or ""
    rank = os.environ.get("HOROVOD_RANK")
    if rank is None:
        return f"[{job}] " if job else ""
    size = os.environ.get("HOROVOD_SIZE") or "?"
    prefix = f"[{job}/{rank}/{size}" if job else f"[{rank}/{size}"
    if (os.environ.get("HOROVOD_ELASTIC") == "1"
            or "HOROVOD_WORLD_VERSION" in os.environ):
        prefix += f" g{os.environ.get('HOROVOD_WORLD_VERSION', '0') or '0'}"
    return prefix + "] "


class RankPrefixFormatter(logging.Formatter):
    """Injects :func:`rank_prefix` as ``%(hvdctx)s`` — computed per
    record, not per handler, so elastic identity changes show up live."""

    def format(self, record: logging.LogRecord) -> str:
        record.hvdctx = rank_prefix()
        return super().format(record)


def get_logger() -> logging.Logger:
    global _logger
    if _logger is None:
        logger = logging.getLogger("horovod_tpu")
        level = os.environ.get("HOROVOD_LOG_LEVEL", "warning").lower()
        logger.setLevel(_LEVELS.get(level, logging.WARNING))
        if not logger.handlers:
            handler = logging.StreamHandler(sys.stderr)
            if os.environ.get("HOROVOD_LOG_HIDE_TIME"):
                fmt = "[%(levelname)s] %(hvdctx)s%(message)s"
            else:
                fmt = "%(asctime)s [%(levelname)s] %(hvdctx)s%(message)s"
            handler.setFormatter(RankPrefixFormatter(fmt))
            logger.addHandler(handler)
        logger.propagate = False
        _logger = logger
    return _logger
