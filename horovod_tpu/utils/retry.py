"""Bounded retry with exponential backoff + jitter.

The one retry policy the control plane shares: rendezvous KV requests
(``runner/http/kv_server.py — KVClient``), durable checkpoint writes
(``checkpoint.py``), and anything else that talks to a service that can
blip. Bounded by construction — the unbounded-silent-retry loops this
replaces are exactly what let a dead driver hang a worker forever.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Iterable, TypeVar

T = TypeVar("T")


def call_with_retries(
    fn: Callable[[], T],
    *,
    attempts: int = 3,
    base_delay: float = 0.1,
    max_delay: float = 5.0,
    jitter: float = 0.5,
    retry_on: tuple[type[BaseException], ...] = (Exception,),
    give_up_on: tuple[type[BaseException], ...] = (),
    deadline_s: float | None = None,
    on_retry: Callable[[int, BaseException], None] | None = None,
) -> T:
    """Call ``fn`` up to ``attempts`` times.

    Backoff before attempt k+1 is ``min(max_delay, base_delay * 2**(k-1))``
    scaled by a uniform ``1 ± jitter`` factor (jitter decorrelates a fleet
    of workers hammering a recovering driver). ``give_up_on`` exceptions
    propagate immediately (e.g. an HTTP 404 is an answer, not a blip);
    ``deadline_s`` bounds total wall time regardless of attempts left.
    """
    if attempts < 1:
        raise ValueError("attempts must be >= 1")
    start = time.monotonic()
    for attempt in range(1, attempts + 1):
        try:
            return fn()
        except give_up_on:
            raise
        except retry_on as e:
            if attempt >= attempts:
                raise
            if deadline_s is not None and \
                    time.monotonic() - start >= deadline_s:
                raise
            try:
                from .. import metrics

                metrics.RETRIES.inc()
            except Exception:  # noqa: BLE001 — counting never blocks retry
                pass
            if on_retry is not None:
                on_retry(attempt, e)
            delay = min(max_delay, base_delay * (2 ** (attempt - 1)))
            delay *= 1.0 + random.uniform(-jitter, jitter)
            time.sleep(max(0.0, delay))
    raise AssertionError("unreachable")


def retrying(**retry_kwargs) -> Callable[[Callable[..., T]], Callable[..., T]]:
    """Decorator form: ``@retrying(attempts=5, base_delay=0.5)``."""
    def deco(fn: Callable[..., T]) -> Callable[..., T]:
        import functools

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            return call_with_retries(
                lambda: fn(*args, **kwargs), **retry_kwargs)
        return wrapped
    return deco


def iter_backoff(
    attempts: int,
    base_delay: float = 0.1,
    max_delay: float = 5.0,
    jitter: float = 0.5,
) -> Iterable[float]:
    """The bare delay schedule (for loops that retry inline)."""
    for attempt in range(1, attempts):
        delay = min(max_delay, base_delay * (2 ** (attempt - 1)))
        yield max(0.0, delay * (1.0 + random.uniform(-jitter, jitter)))
