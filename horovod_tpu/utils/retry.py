"""Bounded retry with exponential backoff + jitter.

The one retry policy the control plane shares: rendezvous KV requests
(``runner/http/kv_server.py — KVClient``), durable checkpoint writes
(``checkpoint.py``), the serving subscriber's scope polls
(``serving.py``), and anything else that talks to a service that can
blip. Bounded by construction — the unbounded-silent-retry loops this
replaces are exactly what let a dead driver hang a worker forever.

Exhaustion is observable: when the attempt budget (or ``deadline_s``)
runs out, a ``retry_budget_exhausted`` record lands in the lifecycle
journal before the final exception propagates — a subscriber loop that
silently gives up is precisely the dark failure the serving tier's
staleness SLO must be able to explain.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Iterable, TypeVar

T = TypeVar("T")


def backoff_delay(attempt: int, base_delay: float, max_delay: float,
                  jitter: float) -> float:
    """The delay before attempt ``attempt + 1`` (attempts are 1-based):
    ``min(max_delay, base_delay * 2**(attempt-1))`` scaled by a uniform
    ``1 ± jitter`` factor, floored at 0. The cap applies BEFORE jitter,
    so the worst-case sleep is ``max_delay * (1 + jitter)`` — a bounded,
    testable envelope (see tests/test_faults.py's property tests)."""
    delay = min(max_delay, base_delay * (2 ** (attempt - 1)))
    return max(0.0, delay * (1.0 + random.uniform(-jitter, jitter)))


def _note_exhausted(name: str | None, attempts: int,
                    error: BaseException, deadline: bool) -> None:
    """Journal one ``retry_budget_exhausted`` event (best-effort — the
    observability must never mask the exception about to propagate)."""
    try:
        from .. import metrics

        metrics.event(
            "retry_budget_exhausted", name=name or "",
            attempts=attempts, deadline=deadline,
            error=str(error)[:200])
    except Exception:  # noqa: BLE001 — journaling never blocks the raise
        pass


def call_with_retries(
    fn: Callable[[], T],
    *,
    attempts: int = 3,
    base_delay: float = 0.1,
    max_delay: float = 5.0,
    jitter: float = 0.5,
    retry_on: tuple[type[BaseException], ...] = (Exception,),
    give_up_on: tuple[type[BaseException], ...] = (),
    deadline_s: float | None = None,
    on_retry: Callable[[int, BaseException], None] | None = None,
    name: str | None = None,
) -> T:
    """Call ``fn`` up to ``attempts`` times.

    Backoff before attempt k+1 is ``min(max_delay, base_delay * 2**(k-1))``
    scaled by a uniform ``1 ± jitter`` factor (jitter decorrelates a fleet
    of workers hammering a recovering driver). ``give_up_on`` exceptions
    propagate immediately (e.g. an HTTP 404 is an answer, not a blip);
    ``deadline_s`` bounds total wall time regardless of attempts left.
    ``name`` labels the ``retry_budget_exhausted`` journal record emitted
    when the budget runs out (give-up answers emit nothing: they are
    answers, not exhaustion).
    """
    if attempts < 1:
        raise ValueError("attempts must be >= 1")
    start = time.monotonic()
    for attempt in range(1, attempts + 1):
        try:
            return fn()
        except give_up_on:
            raise
        except retry_on as e:
            if attempt >= attempts:
                _note_exhausted(name, attempt, e, deadline=False)
                raise
            if deadline_s is not None and \
                    time.monotonic() - start >= deadline_s:
                _note_exhausted(name, attempt, e, deadline=True)
                raise
            try:
                from .. import metrics

                metrics.RETRIES.inc()
            except Exception:  # noqa: BLE001 — counting never blocks retry
                pass
            if on_retry is not None:
                on_retry(attempt, e)
            time.sleep(backoff_delay(attempt, base_delay, max_delay,
                                     jitter))
    raise AssertionError("unreachable")


def retrying(**retry_kwargs) -> Callable[[Callable[..., T]], Callable[..., T]]:
    """Decorator form: ``@retrying(attempts=5, base_delay=0.5)``."""
    def deco(fn: Callable[..., T]) -> Callable[..., T]:
        import functools

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            return call_with_retries(
                lambda: fn(*args, **kwargs), **retry_kwargs)
        return wrapped
    return deco


def iter_backoff(
    attempts: int,
    base_delay: float = 0.1,
    max_delay: float = 5.0,
    jitter: float = 0.5,
) -> Iterable[float]:
    """The bare delay schedule (for loops that retry inline)."""
    for attempt in range(1, attempts):
        yield backoff_delay(attempt, base_delay, max_delay, jitter)
