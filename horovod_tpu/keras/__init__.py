"""Keras API surface.

Parity: ``horovod/keras/__init__.py`` + the shared impl in
``horovod/_keras/`` — a ``DistributedOptimizer`` wrapper that averages
gradients across processes before ``apply_gradients``, plus the fit()-loop
callbacks (broadcast-on-start, metric averaging, LR warmup/schedule).

Built on :mod:`horovod_tpu.tensorflow` (native host data plane); works
with ``tf.keras`` (Keras 3's TF backend included) in eager training loops
and ``model.fit``.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from .. import tensorflow as hvd_tf

try:
    import tensorflow as tf
except ImportError as e:  # pragma: no cover
    raise ImportError("horovod_tpu.keras requires tensorflow") from e

Average = hvd_tf.Average
Sum = hvd_tf.Sum

init = hvd_tf.init
shutdown = hvd_tf.shutdown
is_initialized = hvd_tf.is_initialized
size = hvd_tf.size
rank = hvd_tf.rank
local_rank = hvd_tf.local_rank
local_size = hvd_tf.local_size
cross_rank = hvd_tf.cross_rank
cross_size = hvd_tf.cross_size
is_homogeneous = hvd_tf.is_homogeneous
allreduce = hvd_tf.allreduce
allgather = hvd_tf.allgather
broadcast = hvd_tf.broadcast
alltoall = hvd_tf.alltoall
reducescatter = hvd_tf.reducescatter
barrier = hvd_tf.barrier
join = hvd_tf.join
broadcast_object = hvd_tf.broadcast_object
allgather_object = hvd_tf.allgather_object
broadcast_variables = hvd_tf.broadcast_variables
mpi_built = hvd_tf.mpi_built
mpi_enabled = hvd_tf.mpi_enabled
mpi_threads_supported = hvd_tf.mpi_threads_supported
gloo_built = hvd_tf.gloo_built
gloo_enabled = hvd_tf.gloo_enabled
nccl_built = hvd_tf.nccl_built
ddl_built = hvd_tf.ddl_built
ccl_built = hvd_tf.ccl_built
cuda_built = hvd_tf.cuda_built
rocm_built = hvd_tf.rocm_built
start_timeline = hvd_tf.start_timeline
stop_timeline = hvd_tf.stop_timeline
Compression = hvd_tf.Compression
ProcessSet = hvd_tf.ProcessSet
add_process_set = hvd_tf.add_process_set
remove_process_set = hvd_tf.remove_process_set
global_process_set = hvd_tf.global_process_set


def DistributedOptimizer(optimizer, op: str = Average,
                         backward_passes_per_step: int = 1,
                         compression=None,
                         process_set=None):
    """Wrap a Keras optimizer: gradients are allreduce-averaged across
    processes before the update (reference: ``hvd.DistributedOptimizer``
    keras flavor). ``backward_passes_per_step > 1`` accumulates that many
    calls locally before one fused collective + update;
    ``compression=hvd.Compression.fp16/bf16`` halves the wire;
    ``process_set=`` scopes the averaging to a subset of processes.
    """
    compression = compression or hvd_tf.Compression.none
    base = type(optimizer)

    class _Distributed(base):  # type: ignore[valid-type, misc]
        _hvd_wrapped = True

        def _hvd_reset(self):
            """Drop local-accumulation state after an elastic failure (a
            step that died mid-flight leaves a partial accumulator)."""
            self._hvd_acc = None
            self._hvd_count = 0

        @staticmethod
        def _wire_keyed(gv):
            """Sort (grad, var) pairs by a STABLE per-variable key and
            return (keys, sorted_gv). Wire names derive from these keys,
            not positions: positional naming follows each rank's local
            list order, which is only rank-identical when the None-grad /
            accumulation history is — the exact data-dependent case the
            accumulation paths exist for. Duplicate names (rare; keras
            variable paths are unique) fall back to a shape/dtype
            tiebreak; a still-ambiguous pair raises rather than silently
            cross-pairing different variables across ranks (an
            occurrence-counter suffix would depend on each rank's LOCAL
            tie order — exactly the positional bug again)."""
            def base(v):
                return str(getattr(v, "path", None)
                           or getattr(v, "name", None) or "var")

            counts: dict = {}
            for _, v in gv:
                b = base(v)
                counts[b] = counts.get(b, 0) + 1
            keyed = []
            for g, v in gv:
                b = base(v)
                if counts[b] > 1:
                    b = f"{b}|{tuple(v.shape)}|{v.dtype}"
                keyed.append((b, g, v))
            keyed.sort(key=lambda t: t[0])
            keys = [k for k, _, _ in keyed]
            if len(set(keys)) != len(keys):
                dup = sorted({k for k in keys if keys.count(k) > 1})
                raise ValueError(
                    f"variables {dup} share a name AND shape/dtype — "
                    "cross-rank wire pairing would be ambiguous; give "
                    "the variables unique names")
            return keys, [(g, v) for _, g, v in keyed]

        def _reduce_and_apply(self, gv, name_prefix, extra=(),
                              reduce_op=None, divisor=None,
                              apply_args=(), apply_kwargs=None):
            """Exchange + decompress + apply — the shared wire tail of
            the per-step and flush paths. ``divisor`` post-scales a Sum
            exchange (the flush's global-pending mean). Wires are named
            by stable per-variable keys (see _wire_keyed) so the
            controller pairs the same VARIABLE across ranks regardless
            of each rank's local list order."""
            keys, gv = self._wire_keyed(gv)
            reduced_arrays = hvd_tf._reduce_arrays(
                [hvd_tf._np(g) for g, _ in gv], reduce_op or op,
                hvd_tf._ps_id(process_set), compression, name_prefix,
                names=keys)
            if divisor:
                reduced_arrays = [a / divisor for a in reduced_arrays]
            reduced = [
                (tf.cast(tf.convert_to_tensor(a), g.dtype), v)
                for a, (g, v) in zip(reduced_arrays, gv)
            ]
            return super().apply_gradients(reduced + list(extra),
                                           *apply_args,
                                           **(apply_kwargs or {}))

        def apply_gradients(self, grads_and_vars, *args, **kwargs):
            gv_all = list(grads_and_vars)
            # Unconnected/unused trainables yield g=None — exclude them
            # from the exchange (None has no dtype) and hand them to the
            # base optimizer untouched, as DistributedGradientTape does.
            gv = [(g, v) for g, v in gv_all if g is not None]
            none_pairs = [(g, v) for g, v in gv_all if g is None]
            eff = (process_set.size() if process_set is not None
                   else hvd_tf.size())
            if hvd_tf.size() <= 1 or eff <= 1 or not gv:
                return super().apply_gradients(gv_all, *args, **kwargs)
            self._hvd_count = getattr(self, "_hvd_count", 0) + 1
            if backward_passes_per_step > 1:
                # Accumulate KEYED BY VARIABLE, not position: the
                # None-grad pattern may vary across passes within one
                # window, and a positional zip would add gradients into
                # the wrong accumulator slots.
                acc = getattr(self, "_hvd_acc", None) or {}
                var_of = getattr(self, "_hvd_var_of", {})
                for g, v in gv:
                    t = tf.convert_to_tensor(g)
                    ref = v.ref()
                    acc[ref] = t if ref not in acc else acc[ref] + t
                    var_of[ref] = v
                self._hvd_var_of = var_of
                if self._hvd_count % backward_passes_per_step != 0:
                    self._hvd_acc = acc
                    return None
                self._hvd_acc = None
                gv = [(acc[ref] / backward_passes_per_step, var_of[ref])
                      for ref in acc]
            return self._reduce_and_apply(gv, "keras.grad", none_pairs,
                                          apply_args=args,
                                          apply_kwargs=kwargs)

        def _hvd_flush(self):
            """Apply a PARTIAL accumulation window (epoch end with batch
            count not divisible by backward_passes_per_step) instead of
            dropping it or straddling epochs.

            COLLECTIVE: every member must call at the same loop point
            (keras callbacks fire symmetrically — the estimator's
            epoch-end hook). Whether anything is pending is a LOCAL fact
            (uneven shards give ranks different batch counts), so the
            members first AGREE on the global pending-pass count; ranks
            with nothing pending contribute zeros, and the exchange sums
            then divides by that global count — the true mean over every
            pending microbatch, with no rank gating a collective on
            local state."""
            eff = (process_set.size() if process_set is not None
                   else hvd_tf.size())
            if hvd_tf.size() <= 1 or eff <= 1:
                return None
            acc = getattr(self, "_hvd_acc", None) or {}
            var_of = getattr(self, "_hvd_var_of", None) or {}
            pending = (self._hvd_count % backward_passes_per_step
                       if acc else 0)
            # Agree on the pending count AND which variables actually
            # accumulated THIS WINDOW on any rank: only those get an
            # update (zero contributions from ranks that missed one),
            # so a variable no rank touched keeps its per-step None-grad
            # semantics — applying a zero grad would let momentum /
            # weight decay drift it on every epoch-end flush.
            keys_hist, hist = self._wire_keyed(
                [(ref, v) for ref, v in var_of.items()])
            local_active = [k for k, (ref, _) in zip(keys_hist, hist)
                            if ref in acc]
            replies = hvd_tf._allgather_object_host(
                (pending, local_active), process_set=process_set)
            total = sum(p for p, _ in replies)
            if total == 0:
                return None
            active: set = set()
            for _, ks in replies:
                active.update(ks)
            unknown = active - set(keys_hist)
            if unknown:
                # A peer accumulated a variable this rank has never seen
                # — it cannot contribute zeros of the right shape; this
                # is the divergence the per-step path would also hit.
                raise RuntimeError(
                    "flush variable sets diverged across ranks: peers "
                    f"accumulated {sorted(unknown)} unknown to this rank "
                    f"(local history: {keys_hist})")
            if op not in (hvd_tf.Average, hvd_tf.Sum):
                raise ValueError(
                    f"flush supports op=Average/Sum, got {op!r}")
            self._hvd_acc = None
            self._hvd_count = 0
            gv = [(acc[ref] if ref in acc else tf.zeros_like(v), v)
                  for k, (ref, v) in zip(keys_hist, hist) if k in active]
            if op == hvd_tf.Sum:
                # Window rule is "sum over ranks of the per-rank window
                # mean": pre-divide the local accumulator by the LOCAL
                # pending count (zero-pending ranks hold zeros); a
                # 1/total postscale would shrink the tail update ~size()×
                # relative to every full window.
                gv = [(g / float(pending or 1), v) for g, v in gv]
                return self._reduce_and_apply(
                    gv, "keras.flush", reduce_op=hvd_tf.Sum)
            return self._reduce_and_apply(
                gv, "keras.flush", reduce_op=hvd_tf.Sum,
                divisor=float(total))

    _Distributed.__name__ = f"Distributed{base.__name__}"
    cfg = optimizer.get_config()
    return _Distributed.from_config(cfg)


class BroadcastGlobalVariablesCallback(tf.keras.callbacks.Callback):
    """Broadcast rank-0 weights to every process when training starts
    (reference: ``hvd.callbacks.BroadcastGlobalVariablesCallback``). An
    unbuilt model (e.g. a Sequential with no input shape) has no
    variables at ``on_train_begin`` — Keras builds it at the first train
    step — so the broadcast defers to the end of the first batch, the
    reference's own strategy for this case."""

    def __init__(self, root_rank: int = 0):
        super().__init__()
        self.root_rank = root_rank
        self._done = False

    def _broadcast(self):
        model_vars = list(self.model.trainable_variables
                          + self.model.non_trainable_variables)
        if hvd_tf.size() > 1:
            # Builtness is a LOCAL fact (rank 0 may have built/restored
            # the model before fit while peers are unbuilt); gating entry
            # to the exchange on it would let built ranks enter the
            # collectives below while unbuilt ranks skip — a negotiation
            # hang. Agree collectively first: proceed only once every
            # rank has model variables. Every rank reaches this point the
            # same number of times (keras fires callbacks symmetrically),
            # so the agreement collective itself always pairs up.
            built = hvd_tf._allgather_object_host(bool(model_vars))
            if not all(built):
                return
        elif not model_vars:
            # Unbuilt model. The optimizer may already own variables
            # (keras 3 creates `iterations` at construction), but
            # broadcasting those alone would mark the job done before the
            # model exists — keep deferring until the model has weights.
            return
        # Reference parity: optimizer slot variables (momentum, Adam m/v)
        # broadcast too — rank 0 may carry restored state the others lack.
        opt = getattr(self.model, "optimizer", None)
        if opt is not None and callable(getattr(opt, "build", None)) \
                and not getattr(opt, "built", True):
            # keras 3: force slot creation so every rank owns the same
            # variable set before the symmetric collectives below.
            try:
                opt.build(self.model.trainable_variables)
            except Exception:
                pass
        opt_vars = getattr(opt, "variables", None)
        if callable(opt_vars):  # keras 2 exposed it as a method
            opt_vars = opt_vars()
        opt_vars = list(opt_vars or [])
        if hvd_tf.size() > 1:
            # Ranks may disagree on the slot set (e.g. rank 0 restored
            # extra slots) — or on whether ANY optimizer variables exist
            # yet, so EVERY rank must join this exchange, empty list or
            # not (a local-emptiness gate would deadlock the others).
            # Broadcast is symmetric — every rank must enqueue the SAME
            # ops — so agree on the intersection first, ordered by rank
            # 0's listing. Keys disambiguate duplicate names by
            # occurrence.
            seen: dict = {}
            keys = []
            for v in opt_vars:
                base = getattr(v, "path", None) or getattr(v, "name", "var")
                n = seen.get(base, 0)
                seen[base] = n + 1
                keys.append((base, n))
            all_keys = hvd_tf._allgather_object_host(keys)
            common = set(all_keys[0])
            for ks in all_keys[1:]:
                common &= set(ks)
            order = {k: i for i, k in enumerate(all_keys[0])}
            opt_vars = [
                v for _, v in sorted(
                    (order[k], v)
                    for k, v in zip(keys, opt_vars) if k in common
                )
            ]
        hvd_tf.broadcast_variables(model_vars + opt_vars,
                                   root_rank=self.root_rank)
        self._done = True

    def on_train_begin(self, logs=None):
        self._broadcast()

    def on_train_batch_end(self, batch, logs=None):
        if not self._done:
            self._broadcast()


class MetricAverageCallback(tf.keras.callbacks.Callback):
    """Allreduce-average epoch metrics across processes (reference:
    ``hvd.callbacks.MetricAverageCallback``)."""

    def on_epoch_end(self, epoch, logs=None):
        if logs is None or hvd_tf.size() <= 1:
            return
        for k in sorted(logs):
            v = logs[k]
            if isinstance(v, (int, float, np.floating)):
                out = hvd_tf._world().allreduce(
                    np.asarray([v], np.float64),
                    name=f"metric.{epoch}.{k}", op=Average,
                )
                logs[k] = float(np.asarray(out)[0])


def _set_model_lr(model, lr: float) -> None:
    """Assign a scalar LR on the model's optimizer, failing with guidance
    when the optimizer was built with a LearningRateSchedule (keras's
    setter raises there — two schedulers fighting over the LR is a user
    error, not something to paper over)."""
    opt = model.optimizer
    if not hasattr(opt, "learning_rate"):
        return
    try:
        opt.learning_rate = lr
    except TypeError as e:
        raise TypeError(
            "the optimizer's learning_rate is a LearningRateSchedule and "
            "cannot be driven by an hvd LR callback; use one scheduling "
            "mechanism, not both"
        ) from e


class LearningRateScheduleCallback(tf.keras.callbacks.Callback):
    """Set LR to ``initial_lr * multiplier(epoch)`` between
    ``start_epoch`` and ``end_epoch`` (reference:
    ``hvd.callbacks.LearningRateScheduleCallback``; ``multiplier`` may be
    a callable or a constant). ``staircase=False`` with
    ``steps_per_epoch`` applies the multiplier per batch on fractional
    epochs (reference contract); ``momentum_correction`` is accepted for
    signature parity and ignored — keras optimizers own their momentum
    state."""

    def __init__(self, initial_lr: float, multiplier,
                 start_epoch: int = 0, end_epoch: int | None = None,
                 staircase: bool = True, steps_per_epoch: int | None = None,
                 momentum_correction: bool = True):
        super().__init__()
        del momentum_correction
        if not staircase and not steps_per_epoch:
            raise ValueError(
                "staircase=False needs steps_per_epoch to compute "
                "fractional epochs (reference contract)")
        self.initial_lr = initial_lr
        self.multiplier = (
            multiplier if callable(multiplier) else (lambda e: multiplier)
        )
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.steps_per_epoch = steps_per_epoch
        self._epoch = 0

    def _active(self, epoch) -> bool:
        if epoch < self.start_epoch:
            return False
        return self.end_epoch is None or epoch < self.end_epoch

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch
        if self._active(epoch):
            _set_model_lr(self.model,
                          self.initial_lr * float(self.multiplier(epoch)))

    def on_train_batch_begin(self, batch, logs=None):
        if self.staircase or not self.steps_per_epoch:
            return
        epoch = self._epoch + batch / float(self.steps_per_epoch)
        if self._active(epoch):
            _set_model_lr(self.model,
                          self.initial_lr * float(self.multiplier(epoch)))


class LearningRateWarmupCallback(tf.keras.callbacks.Callback):
    """Linearly ramp LR from lr/size to lr over warmup epochs (reference:
    ``hvd.callbacks.LearningRateWarmupCallback`` — the large-batch recipe's
    companion to lr scaling)."""

    def __init__(self, initial_lr: float, warmup_epochs: int = 5,
                 verbose: bool = False):
        super().__init__()
        self.initial_lr = initial_lr
        self.warmup_epochs = warmup_epochs
        self.verbose = verbose

    def _set_lr(self, lr: float):
        _set_model_lr(self.model, lr)

    def on_epoch_begin(self, epoch, logs=None):
        if epoch >= self.warmup_epochs:
            self._set_lr(self.initial_lr)
            return
        n = hvd_tf.size()
        frac = (epoch + 1) / max(1, self.warmup_epochs)
        lr = self.initial_lr * (1.0 / n + (1.0 - 1.0 / n) * frac)
        self._set_lr(lr)
        if self.verbose:
            print(f"hvd warmup: epoch {epoch} lr={lr:.6g}")


from . import callbacks  # noqa: E402,F401  (reference: hvd.callbacks.*)

__all__ = [
    "Average", "Sum", "init", "shutdown", "is_initialized", "size",
    "rank", "local_rank", "local_size", "cross_rank", "cross_size",
    "is_homogeneous", "allreduce", "allgather", "broadcast",
    "alltoall", "reducescatter", "barrier", "join",
    "broadcast_object", "allgather_object", "broadcast_variables",
    "mpi_built", "mpi_enabled", "mpi_threads_supported", "gloo_built",
    "gloo_enabled", "nccl_built", "ddl_built", "ccl_built",
    "cuda_built", "rocm_built", "start_timeline", "stop_timeline",
    "Compression", "ProcessSet", "add_process_set", "remove_process_set", "global_process_set",
    "DistributedOptimizer", "BroadcastGlobalVariablesCallback",
    "MetricAverageCallback", "LearningRateWarmupCallback",
    "LearningRateScheduleCallback", "callbacks",
]
