"""``hvd.callbacks.*`` namespace parity for the Keras surface.

The reference exposes its Keras callbacks as ``horovod.keras.callbacks``
(impl in ``horovod/_keras/callbacks.py``); here they live in the package
``__init__`` and this module re-exports them under the reference's
canonical path so ``hvd.callbacks.BroadcastGlobalVariablesCallback(0)``
works verbatim.
"""

from . import (  # noqa: F401
    BroadcastGlobalVariablesCallback,
    LearningRateScheduleCallback,
    LearningRateWarmupCallback,
    MetricAverageCallback,
)
