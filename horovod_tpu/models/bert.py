"""BERT encoder family — the framework's transformer benchmark model.

BASELINE config #3 is the reference's BERT-Large TF/Keras benchmark
(Horovod's second headline model alongside ResNet). TPU-first choices:
bfloat16 activations with float32 params/layernorm accumulation, attention
via the framework's own blockwise/flash kernels
(``horovod_tpu.ops.attention``), sequence dimension ready for the
sequence-parallel schemes in ``horovod_tpu.parallel.sequence`` (pass
``attention_fn=`` to swap in ring/Ulysses inside a sharded step).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..ops.attention import blockwise_attention_reference, flash_attention


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    dropout_rate: float = 0.1
    dtype: Any = jnp.bfloat16

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


BERT_BASE = BertConfig()
BERT_LARGE = BertConfig(
    hidden_size=1024, num_layers=24, num_heads=16, intermediate_size=4096
)
BERT_TINY = BertConfig(  # test-sized
    vocab_size=1024, hidden_size=64, num_layers=2, num_heads=4,
    intermediate_size=128, max_position_embeddings=128,
)


def default_attention(q, k, v, mask_bias, dtype):
    """[B, S, H, D] inputs; dense attention with an additive mask bias.

    Uses the blockwise oracle math (fp32 online softmax). ``mask_bias`` is
    [B, 1, 1, S] with 0 for visible and -1e30 for padding.
    """
    B, S, H, D = q.shape
    qt = q.transpose(0, 2, 1, 3)  # [B, H, S, D]
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    scale = 1.0 / (D ** 0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk", qt.astype(jnp.float32),
                   kt.astype(jnp.float32)) * scale
    s = s + mask_bias.astype(jnp.float32)
    p = jnp.exp(s - s.max(axis=-1, keepdims=True))
    p = p / p.sum(axis=-1, keepdims=True)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, vt.astype(jnp.float32))
    return out.transpose(0, 2, 1, 3).astype(dtype)


class SelfAttention(nn.Module):
    config: BertConfig
    attention_fn: Callable | None = None

    @nn.compact
    def __call__(self, x, mask_bias, deterministic: bool):
        cfg = self.config
        dense = partial(
            nn.DenseGeneral, dtype=cfg.dtype, param_dtype=jnp.float32
        )
        qkv_shape = (cfg.num_heads, cfg.head_dim)
        q = dense(features=qkv_shape, name="query")(x)
        k = dense(features=qkv_shape, name="key")(x)
        v = dense(features=qkv_shape, name="value")(x)
        if self.attention_fn is not None:
            out = self.attention_fn(q, k, v, mask_bias, cfg.dtype)
        else:
            out = default_attention(q, k, v, mask_bias, cfg.dtype)
        out = nn.DenseGeneral(
            features=cfg.hidden_size, axis=(-2, -1), dtype=cfg.dtype,
            param_dtype=jnp.float32, name="out",
        )(out)
        out = nn.Dropout(cfg.dropout_rate)(out, deterministic=deterministic)
        return out


class TransformerLayer(nn.Module):
    config: BertConfig
    attention_fn: Callable | None = None

    @nn.compact
    def __call__(self, x, mask_bias, deterministic: bool):
        cfg = self.config
        # Post-LN (original BERT): sublayer -> residual -> LayerNorm.
        attn = SelfAttention(cfg, self.attention_fn, name="attention")(
            x, mask_bias, deterministic
        )
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_attn")(x + attn)
        x = x.astype(cfg.dtype)
        h = nn.Dense(cfg.intermediate_size, dtype=cfg.dtype,
                     param_dtype=jnp.float32, name="mlp_in")(x)
        h = nn.gelu(h)
        h = nn.Dense(cfg.hidden_size, dtype=cfg.dtype,
                     param_dtype=jnp.float32, name="mlp_out")(h)
        h = nn.Dropout(cfg.dropout_rate)(h, deterministic=deterministic)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_mlp")(x + h)
        return x.astype(cfg.dtype)


class Bert(nn.Module):
    """BERT encoder with MLM head (tied embeddings).

    Call: ``model.apply(vars, input_ids, attention_mask, token_type_ids,
    train=...)`` → ``(sequence_output [B,S,E], mlm_logits [B,S,V])``.
    """

    config: BertConfig = BERT_BASE
    attention_fn: Callable | None = None
    # Rematerialize each transformer layer in backward (jax.checkpoint):
    # activations drop from O(L * tokens * hidden) to O(tokens * hidden),
    # buying batch size at ~+1/3 forward recompute — the standard TPU
    # HBM-for-FLOPs trade.
    remat: bool = False

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 train: bool = False, masked_positions=None):
        """``masked_positions`` [B, P]: when given, the MLM head runs only
        on those positions (logits [B, P, V]) — the reference BERT
        pretraining recipe (``max_predictions_per_seq``); computing the
        [B, S, V] logits for the ~85% unmasked positions is pure waste."""
        cfg = self.config
        B, S = input_ids.shape
        if attention_mask is None:
            attention_mask = jnp.ones((B, S), jnp.int32)
        if token_type_ids is None:
            token_type_ids = jnp.zeros((B, S), jnp.int32)

        tok_emb = nn.Embed(cfg.vocab_size, cfg.hidden_size,
                           param_dtype=jnp.float32, name="token_embeddings")
        x = tok_emb(input_ids)
        x = x + nn.Embed(
            cfg.max_position_embeddings, cfg.hidden_size,
            param_dtype=jnp.float32, name="position_embeddings",
        )(jnp.arange(S)[None, :])
        x = x + nn.Embed(
            cfg.type_vocab_size, cfg.hidden_size,
            param_dtype=jnp.float32, name="type_embeddings",
        )(token_type_ids)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_emb")(x)
        x = nn.Dropout(cfg.dropout_rate)(x, deterministic=not train)
        x = x.astype(cfg.dtype)

        # Additive mask bias [B, 1, 1, S]: 0 visible, -1e30 padding.
        mask_bias = (1.0 - attention_mask[:, None, None, :].astype(
            jnp.float32)) * -1e30

        layer_cls = (
            nn.remat(TransformerLayer, static_argnums=(2,))
            if self.remat
            else TransformerLayer
        )
        for i in range(cfg.num_layers):
            x = layer_cls(cfg, self.attention_fn, name=f"layer_{i}")(
                x, mask_bias, deterministic=not train
            )

        # MLM head with tied input embeddings. The [tokens, H] @ [H, V]
        # logits matmul is ~10% of model FLOPs — run it bf16-in/f32-accum
        # on the MXU (a full-f32 matmul runs at 1/4 rate and would be the
        # single biggest line in the profile).
        head_in = x
        if masked_positions is not None:
            head_in = jnp.take_along_axis(
                x, masked_positions[..., None], axis=1
            )
        h = nn.Dense(cfg.hidden_size, dtype=cfg.dtype,
                     param_dtype=jnp.float32, name="mlm_transform")(head_in)
        h = nn.gelu(h)
        h = nn.LayerNorm(dtype=jnp.float32, name="mlm_ln")(h)
        logits = jax.lax.dot_general(
            h.astype(cfg.dtype),
            tok_emb.embedding.astype(cfg.dtype),
            (((h.ndim - 1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        logits = logits + self.param(
            "mlm_bias", nn.initializers.zeros, (cfg.vocab_size,), jnp.float32
        )
        return x, logits


def mlm_loss(logits, labels, label_mask):
    """Masked-LM cross entropy: mean over positions where label_mask == 1."""
    import jax

    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    mask = label_mask.astype(jnp.float32)
    return -(ll * mask).sum() / jnp.maximum(mask.sum(), 1.0)


def flash_attention_fn(q, k, v, mask_bias, dtype, interpret: bool = False):
    """Adapter plugging the Pallas flash kernel into ``Bert`` for unpadded
    batches (mask_bias all-zero): [B, S, H, D] -> transpose -> kernel."""
    del mask_bias  # full-visibility batches only; padded path uses default
    out = flash_attention(
        q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3), causal=False, interpret=interpret,
    )
    return out.transpose(0, 2, 1, 3).astype(dtype)
