"""ResNet family (v1.5) — the framework's flagship benchmark model.

BASELINE configs #2/#5 (the reference's
``examples/pytorch/pytorch_imagenet_resnet50.py`` and the Horovod paper's
headline ResNet scaling results) train ResNet-50 data-parallel. TPU-first
choices: NHWC layout (channels minor for the MXU), bfloat16 compute with
float32 variables, 3x3 stride-2 in the bottleneck's middle conv (the v1.5
variant every benchmark uses).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class Bottleneck(nn.Module):
    filters: int
    strides: tuple[int, int] = (1, 1)
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1), use_bias=False)(x)
        y = self.norm()(y)
        y = nn.relu(y)
        # v1.5: stride lives on the 3x3, not the 1x1.
        y = self.conv(
            self.filters, (3, 3), strides=self.strides, use_bias=False,
            padding="SAME",
        )(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1), use_bias=False)(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), strides=self.strides, use_bias=False
            )(residual)
            residual = self.norm()(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, dtype=self.dtype)
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
        )
        x = x.astype(self.dtype)
        x = conv(
            self.num_filters, (7, 7), strides=(2, 2), use_bias=False,
            padding=[(3, 3), (3, 3)],
        )(x)
        x = norm()(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = Bottleneck(
                    self.num_filters * 2**i,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x


ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3])
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3])
ResNet152 = partial(ResNet, stage_sizes=[3, 8, 36, 3])
