"""LeNet-5-style convnet for the MNIST end-to-end slice.

The model behind BASELINE config #1 (the reference's
``examples/pytorch/pytorch_mnist.py`` trains the same shape of network: two
convs + two dense layers). Written in flax.linen; NHWC layout (TPU-native —
the MXU wants channels minor).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class LeNet(nn.Module):
    num_classes: int = 10

    @nn.compact
    def __call__(self, x):
        # x: (batch, 28, 28, 1)
        x = nn.Conv(32, (3, 3), padding="SAME")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (3, 3), padding="SAME")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128)(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes)(x)
        return x


def cross_entropy_loss(logits, labels, num_classes: int = 10):
    import jax.nn

    one_hot = jnp.eye(num_classes, dtype=logits.dtype)[labels]
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.sum(one_hot * logp, axis=-1))
