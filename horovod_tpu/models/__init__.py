from .lenet import LeNet  # noqa: F401
from .resnet import ResNet, ResNet50, ResNet101, ResNet152  # noqa: F401
from .bert import (  # noqa: F401
    BERT_BASE,
    BERT_LARGE,
    BERT_TINY,
    Bert,
    BertConfig,
    mlm_loss,
)
