from .lenet import LeNet  # noqa: F401
from .resnet import ResNet, ResNet50, ResNet101, ResNet152  # noqa: F401
