"""MXNet API surface.

Parity: ``horovod/mxnet/__init__.py`` — ``DistributedOptimizer`` (Module
API), ``DistributedTrainer`` (Gluon), ``broadcast_parameters`` — over the
native host data plane, like the torch/TF surfaces. MXNet is retired
upstream and absent from this image, so this surface is import-guarded
and exercised only for its guidance path here; the collective plumbing it
delegates to (NativeWorld) is the same battle-tested code the torch
surface rides.
"""

from __future__ import annotations

from typing import Any

import numpy as np

try:
    import mxnet as mx
except ImportError as e:  # pragma: no cover - mxnet absent in this image
    raise ImportError(
        "horovod_tpu.mxnet requires the 'mxnet' package (retired upstream; "
        "not installed here). Use horovod_tpu.torch, horovod_tpu.tensorflow "
        "or the JAX-native surface (import horovod_tpu) instead."
    ) from e

from ..ops.collective_ops import Average, Sum  # noqa: E402
from ..process_world import (  # noqa: E402
    local_rank,
    local_size,
    rank,
    size,
)

_initialized = False


def init() -> None:
    global _initialized
    _initialized = True


def shutdown() -> None:
    global _initialized
    from ..process_world import shutdown_native_world

    shutdown_native_world()
    _initialized = False


def _world():
    from ..parallel.hierarchical import _default_native_world

    return _default_native_world()


def allreduce(tensor, average: bool = True, name: str | None = None):
    """Allreduce an NDArray across processes (returns a new NDArray)."""
    if size() <= 1:
        return tensor.copy()
    out = np.asarray(_world().allreduce(
        tensor.asnumpy(), name=name, op=Average if average else Sum))
    return mx.nd.array(out.reshape(tensor.shape), dtype=tensor.dtype)


def broadcast_parameters(params, root_rank: int = 0) -> None:
    """Broadcast a Gluon ``ParameterDict`` / dict of NDArrays from root."""
    if size() <= 1:
        return
    items = params.items() if hasattr(params, "items") else params
    for name, p in sorted(items):
        arr = p.data() if hasattr(p, "data") else p
        out = np.asarray(_world().broadcast(
            arr.asnumpy(), root_rank, name=f"mx.bp.{name}"))
        arr[:] = mx.nd.array(out.reshape(arr.shape), dtype=arr.dtype)


class DistributedTrainer(mx.gluon.Trainer):
    """Gluon Trainer with cross-process gradient averaging (parity:
    ``hvd.DistributedTrainer``): gradients are allreduce-AVERAGED before
    each update (op=Average plays the role of the reference's
    grad-rescale + Sum)."""

    def _allreduce_grads(self):
        if size() <= 1:
            return
        w = _world()
        handles = []
        for i, param in enumerate(self._params):
            if param.grad_req != "null":
                for j, g in enumerate(param.list_grad()):
                    handles.append(
                        (g, w.allreduce_async_(
                            g.asnumpy(), name=f"mx.grad.{i}.{j}",
                            op=Average))
                    )
        for g, h in handles:
            out = np.asarray(w.synchronize(h))
            g[:] = mx.nd.array(out.reshape(g.shape), dtype=g.dtype)


def DistributedOptimizer(optimizer):
    """Wrap an mxnet optimizer: updates see allreduce-averaged gradients
    (Module API flavor)."""

    def _reduced(index, grad):
        if size() <= 1:
            return grad
        out = np.asarray(_world().allreduce(
            grad.asnumpy(), name=f"mx.opt.{index}", op=Average))
        return mx.nd.array(out.reshape(grad.shape), dtype=grad.dtype)

    class _Dist(type(optimizer)):  # type: ignore[misc]
        def update(self, index, weight, grad, state):
            super().update(index, weight, _reduced(index, grad), state)

        # fp16 training dispatches here WITHOUT calling update(); both
        # entry points must reduce (the reference wraps both).
        def update_multi_precision(self, index, weight, grad, state):
            super().update_multi_precision(
                index, weight, _reduced(index, grad), state)

    wrapped = _Dist.__new__(_Dist)
    wrapped.__dict__.update(optimizer.__dict__)
    return wrapped


__all__ = [
    "init", "shutdown", "size", "rank", "local_rank", "local_size",
    "allreduce", "broadcast_parameters", "DistributedTrainer",
    "DistributedOptimizer",
]
