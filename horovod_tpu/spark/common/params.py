"""Estimator parameter surface.

Parity: ``horovod/spark/common/params.py`` — the reference mirrors
Spark-ML's ``Params`` mixins (getters/setters per param). Re-designed as a
validated dataclass: the same knob set, without requiring pyspark to
import (the estimator must be constructible and unit-testable on a dev
box; pyspark only matters at ``fit(spark_df)`` time).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Sequence


@dataclasses.dataclass
class EstimatorParams:
    # Data columns (parity: setFeatureCols/setLabelCols).
    feature_cols: Sequence[str] = ("features",)
    label_cols: Sequence[str] = ("label",)
    # Training loop.
    batch_size: int = 32
    epochs: int = 1
    shuffle: bool = True
    seed: int = 0
    # Validation: a float in (0,1) = split fraction, or a column name whose
    # truthy rows are validation (parity: setValidation).
    validation: float | str | None = None
    # Gradient exchange (parity: setCompression /
    # setBackwardPassesPerStep on the reference estimators). compression
    # is a surface-appropriate Compression member (e.g.
    # horovod_tpu.torch.Compression.fp16) or None for none.
    compression: Any = None
    backward_passes_per_step: int = 1
    # Launch.
    num_proc: int | None = None
    verbose: int = 1
    run_id: str | None = None
    # Callbacks invoked with (epoch, metrics dict) on rank 0.
    callbacks: Sequence[Callable[[int, dict], None]] = ()

    def validate(self) -> None:
        if self.batch_size <= 0:
            raise ValueError(f"batch_size must be positive, got {self.batch_size}")
        if self.epochs <= 0:
            raise ValueError(f"epochs must be positive, got {self.epochs}")
        if isinstance(self.validation, float) and not (
            0.0 < self.validation < 1.0
        ):
            raise ValueError(
                f"validation fraction must be in (0,1), got {self.validation}"
            )
        if not self.feature_cols:
            raise ValueError("feature_cols must name at least one column")
        if not self.label_cols:
            raise ValueError("label_cols must name at least one column")
        if self.backward_passes_per_step < 1:
            raise ValueError(
                "backward_passes_per_step must be >= 1, got "
                f"{self.backward_passes_per_step}")


def merge_params(base: EstimatorParams, **overrides: Any) -> EstimatorParams:
    known = {f.name for f in dataclasses.fields(EstimatorParams)}
    bad = set(overrides) - known
    if bad:
        raise TypeError(
            f"unknown estimator param(s) {sorted(bad)}; valid: {sorted(known)}"
        )
    return dataclasses.replace(base, **overrides)
