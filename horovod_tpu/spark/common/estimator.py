"""Estimator/Model base classes — the Spark-ML-style ``.fit(df)`` flow.

Parity: ``horovod/spark/common/estimator.py`` (``HorovodEstimator`` /
``HorovodModel``). The reference's flow: validate params → materialize the
DataFrame to Parquet in the Store (Petastorm) → launch one training
process per executor with ``horovod.spark.run`` → collect the trained
model → return a Transformer. This re-design keeps that flow with two
substrates:

- **pyspark DataFrame** → Parquet via Spark writers, training launched as
  a barrier stage (``horovod_tpu.spark.run``), one process per executor.
- **pandas DataFrame** (dev/CI — no Spark needed) → Parquet shards via
  pyarrow, training runs in-process over the local device mesh (the same
  step function; DP over devices instead of processes).

Workers read their Parquet shard(s) round-robin by process id — the
Petastorm role, played by pyarrow.
"""

from __future__ import annotations

import pickle
from typing import Any, Sequence

import numpy as np

from .params import EstimatorParams, merge_params
from .store import Store


# -- data materialization (Petastorm role) -----------------------------------


def materialize_pandas(df, path: str, store: Store, num_shards: int) -> int:
    """Write a pandas DataFrame as ``num_shards`` Parquet shards. Returns
    the row count."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    store.makedirs(path)
    n = len(df)
    rows_per = max(1, (n + num_shards - 1) // num_shards)
    for i in range(num_shards):
        part = df.iloc[i * rows_per: (i + 1) * rows_per]
        table = pa.Table.from_pandas(part, preserve_index=False)
        pq.write_table(table, f"{path}/part-{i:05d}.parquet")
    return n


def materialize_spark(df, path: str, num_shards: int) -> int:
    """Write a Spark DataFrame as Parquet with ``num_shards`` partitions."""
    df = df.repartition(num_shards)
    df.write.mode("overwrite").parquet(path)
    return df.count()


def read_shard(path: str, store: Store, shard: int, num_shards: int,
               columns: Sequence[str]):
    """Read this worker's shard rows (files striped round-robin) as a dict
    of column -> stacked numpy array."""
    import pyarrow.parquet as pq

    files = [
        f for f in store.listdir(path)
        if f.endswith(".parquet") or f.startswith("part-")
    ]
    mine = [f for i, f in enumerate(sorted(files)) if i % num_shards == shard]
    cols: dict[str, list] = {c: [] for c in columns}
    for f in mine:
        table = pq.read_table(f"{path}/{f}", columns=list(columns))
        for c in columns:
            cols[c].extend(table.column(c).to_pylist())
    return {
        c: np.asarray(v) for c, v in cols.items()
    }


def train_val_split(data: dict, validation, seed: int):
    """Apply EstimatorParams.validation: a float in (0,1) splits rows off
    for validation (deterministic shuffle by seed); a string names a
    0/1 column whose truthy rows are validation; None -> no split."""
    cols = list(data)
    n = len(data[cols[0]])
    if validation is None:
        return data, None
    if isinstance(validation, str):
        mask = np.asarray(data[validation]).astype(bool)
        train = {c: data[c][~mask] for c in cols if c != validation}
        val = {c: data[c][mask] for c in cols if c != validation}
        return train, val
    idx = np.arange(n)
    np.random.RandomState(seed).shuffle(idx)
    n_val = max(1, int(n * float(validation)))
    val_idx, train_idx = idx[:n_val], idx[n_val:]
    return ({c: data[c][train_idx] for c in cols},
            {c: data[c][val_idx] for c in cols})


def batches(data: dict, batch_size: int, shuffle: bool, seed: int,
            drop_last: bool = True):
    """Minibatch iterator over a column dict (epoch order reshuffled by
    caller via seed)."""
    cols = list(data)
    n = len(data[cols[0]])
    idx = np.arange(n)
    if shuffle:
        np.random.RandomState(seed).shuffle(idx)
    stop = (n // batch_size) * batch_size if drop_last else n
    for s in range(0, stop, batch_size):
        take = idx[s: s + batch_size]
        yield {c: data[c][take] for c in cols}


# -- estimator / model -------------------------------------------------------


class Estimator:
    """Base estimator: ``.fit(df) -> Model`` (parity: HorovodEstimator).

    Subclasses implement ``_train(shard_fn, params) -> state`` and
    ``_make_model(state) -> Model``.
    """

    def __init__(self, store: Store | str, params: EstimatorParams | None
                 = None, **overrides: Any):
        self.store = Store.create(store) if isinstance(store, str) else store
        self.params = merge_params(params or EstimatorParams(), **overrides)

    # Spark-ML-style setters (parity: setEpochs/setBatchSize/...).
    def set(self, **overrides: Any) -> "Estimator":
        self.params = merge_params(self.params, **overrides)
        return self

    def fit(self, df) -> "Model":
        p = self.params
        p.validate()
        run_id = p.run_id or self.store.new_run_id()
        train_path = self.store.train_data_path(run_id)
        columns = list(p.feature_cols) + list(p.label_cols)

        is_spark = hasattr(df, "rdd")  # duck-type: pyspark DataFrame
        if is_spark:
            from .. import run as spark_run

            num_proc = p.num_proc or df.rdd.getNumPartitions()
            materialize_spark(df.select(*columns), train_path, num_proc)
            store, params = self.store, p
            train_fn = self._worker_fn()

            def task():
                import horovod_tpu as hvd

                hvd.init()
                shard = hvd.process_rank()
                data = read_shard(train_path, store, shard, num_proc,
                                  columns)
                return train_fn(data, params, shard)

            results = spark_run(task, num_proc=num_proc)
            state = results[0]
        else:
            # pandas path: shard only for IO symmetry; train in-process
            # over the local device mesh.
            import horovod_tpu as hvd

            hvd.init()
            materialize_pandas(df[columns], train_path, self.store, 1)
            data = read_shard(train_path, self.store, 0, 1, columns)
            state = self._worker_fn()(data, p, 0)

        # Persist the trained state AND the params in effect (parity:
        # checkpoint dir) — load() must rebuild the Model against the
        # fit-time configuration, not whatever the estimator holds later.
        # Callbacks are stripped first: they are live callables (lambdas,
        # bound methods) consumed during training, not persistable config.
        import dataclasses

        persistable = dataclasses.replace(p, callbacks=())
        self.store.write_bytes(
            self._final_ckpt(run_id),
            pickle.dumps({"state": state, "params": persistable}))
        return self._make_model(state, run_id, p)

    def _final_ckpt(self, run_id: str) -> str:
        return f"{self.store.checkpoint_path(run_id)}/final.pkl"

    def load(self, run_id: str) -> "Model":
        """Rebuild the trained Model from the store's checkpoint of a
        prior ``fit`` run (parity: reference estimators read trained
        models back from the Store; the estimator supplies the
        architecture/builders, the checkpoint supplies the state AND the
        fit-time params — a later reconfiguration of this estimator does
        not leak into the loaded Model)."""
        ckpt = self._final_ckpt(run_id)
        if not self.store.exists(ckpt):
            raise FileNotFoundError(
                f"no checkpoint for run {run_id!r} at {ckpt}")
        blob = pickle.loads(self.store.read_bytes(ckpt))
        return self._make_model(blob["state"], run_id, blob["params"])

    # -- subclass surface ----------------------------------------------------

    def _worker_fn(self):
        """Return a picklable fn(data_dict, params, shard) -> state."""
        raise NotImplementedError

    def _make_model(self, state, run_id: str, params) -> "Model":
        """Build the Model from trained ``state`` under the given
        ``params`` (fit passes the live params; load passes the
        checkpointed fit-time ones)."""
        raise NotImplementedError


class Model:
    """Trained-model transformer: ``.transform(df)`` adds predictions
    (parity: HorovodModel)."""

    def __init__(self, run_id: str, params: EstimatorParams):
        self.run_id = run_id
        self.params = params

    def predict(self, features: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def transform(self, df, output_col: str = "prediction"):
        p = self.params
        feature_col = p.feature_cols[0]
        if hasattr(df, "rdd"):  # pyspark
            predict = self.predict

            def map_partition(rows):
                import numpy as _np

                rows = list(rows)
                if not rows:
                    return
                feats = _np.asarray([r[feature_col] for r in rows])
                preds = predict(feats)
                for r, pr in zip(rows, preds):
                    d = r.asDict()
                    d[output_col] = pr.tolist() if hasattr(pr, "tolist") else pr
                    yield d
            return df.rdd.mapPartitions(map_partition).toDF()
        out = df.copy()
        feats = np.asarray(list(df[feature_col]))
        preds = np.asarray(self.predict(feats))
        out[output_col] = list(preds)
        return out
