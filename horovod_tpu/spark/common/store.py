"""Storage abstraction for Spark Estimators.

Parity: ``horovod/spark/common/store.py`` — the Store owns the directory
layout (train data, validation data, checkpoints, logs) that the estimator
materializes DataFrames into and workers read shards from. Re-designed on
``fsspec`` so one implementation covers local paths, ``hdfs://``,
``s3://``, ``gs://`` — instead of the reference's per-filesystem classes
(LocalStore/HDFSStore/S3Store remain as thin aliases for API parity).
"""

from __future__ import annotations

import os
import uuid
from typing import Any


class Store:
    """Directory layout + filesystem access for one training run-root."""

    def __init__(self, prefix_path: str):
        self.prefix_path = prefix_path.rstrip("/")

    @staticmethod
    def create(prefix_path: str) -> "Store":
        """Pick a Store for the path scheme (parity: ``Store.create``)."""
        if "://" in prefix_path and not prefix_path.startswith("file://"):
            return FilesystemStore(prefix_path)
        return LocalStore(prefix_path)

    # -- layout (parity: the reference's *_path accessors) -------------------

    def run_path(self, run_id: str) -> str:
        return f"{self.prefix_path}/runs/{run_id}"

    def train_data_path(self, run_id: str) -> str:
        return f"{self.run_path(run_id)}/train_data"

    def val_data_path(self, run_id: str) -> str:
        return f"{self.run_path(run_id)}/val_data"

    def checkpoint_path(self, run_id: str) -> str:
        return f"{self.run_path(run_id)}/checkpoints"

    def logs_path(self, run_id: str) -> str:
        return f"{self.run_path(run_id)}/logs"

    def new_run_id(self) -> str:
        return uuid.uuid4().hex[:16]

    # -- filesystem ops ------------------------------------------------------

    def makedirs(self, path: str) -> None:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def write_bytes(self, path: str, data: bytes) -> None:
        raise NotImplementedError

    def read_bytes(self, path: str) -> bytes:
        raise NotImplementedError

    def listdir(self, path: str) -> list[str]:
        raise NotImplementedError


class LocalStore(Store):
    """Plain local filesystem (parity: ``LocalStore``)."""

    def makedirs(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def write_bytes(self, path: str, data: bytes) -> None:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)

    def read_bytes(self, path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()

    def listdir(self, path: str) -> list[str]:
        return sorted(os.listdir(path)) if os.path.isdir(path) else []


class FilesystemStore(Store):
    """fsspec-backed store: hdfs://, s3://, gs://, ... one implementation
    where the reference ships one class per filesystem."""

    def __init__(self, prefix_path: str):
        super().__init__(prefix_path)
        import fsspec

        self._fs, _ = fsspec.core.url_to_fs(prefix_path)

    def makedirs(self, path: str) -> None:
        self._fs.makedirs(path, exist_ok=True)

    def exists(self, path: str) -> bool:
        return self._fs.exists(path)

    def write_bytes(self, path: str, data: bytes) -> None:
        with self._fs.open(path, "wb") as f:
            f.write(data)

    def read_bytes(self, path: str) -> bytes:
        with self._fs.open(path, "rb") as f:
            return f.read()

    def listdir(self, path: str) -> list[str]:
        if not self._fs.exists(path):
            return []
        return sorted(os.path.basename(p) for p in self._fs.ls(path))


# Reference-name aliases (the scheme-dispatch lives in Store.create).
HDFSStore = FilesystemStore
S3Store = FilesystemStore
GCSStore = FilesystemStore
