"""Spark integration: run framework jobs inside Spark executors.

Parity: ``horovod.spark.run()`` (SURVEY.md §3.5) — launch one framework
worker per Spark task in a barrier stage, driver hosting the rendezvous KV.
``run()`` is the launch substrate; the Estimator API lives in
``horovod_tpu.spark.jax`` (JaxEstimator — the TPU-native flavor),
``horovod_tpu.spark.keras`` (KerasEstimator), with the Store/params/
materialization machinery in ``horovod_tpu.spark.common``. pyspark is
optional — the estimators also fit pandas DataFrames (dev/CI path);
``run()`` without pyspark raises with guidance.
"""

from __future__ import annotations

import os
from typing import Callable

from ..runner.network import driver_addr, free_port


def _require_pyspark():
    try:
        import pyspark  # noqa: F401

        return pyspark
    except ImportError as e:
        raise ImportError(
            "horovod_tpu.spark requires the 'pyspark' package. Install "
            "pyspark or use the hvdrun launcher (horovod_tpu.runner) "
            "instead."
        ) from e


def run(fn: Callable, args=(), kwargs=None, num_proc: int | None = None,
        spark_context=None) -> list:
    """Run ``fn`` on ``num_proc`` Spark executors as one framework world.

    Parity: ``horovod.spark.run(fn, args, num_proc)``. Uses a barrier-mode
    mapPartitions stage so all workers start together; each task applies
    the launcher env contract, calls ``fn``, returns its result to the
    driver (rank order preserved).
    """
    _require_pyspark()
    from pyspark import SparkContext

    from ..runner.ray_spark_common import task_env  # shared env builder

    sc = spark_context or SparkContext.getOrCreate()
    n = num_proc or int(sc.defaultParallelism)
    from ..runner.http.kv_server import RendezvousServer

    from ..runner import secret as _secret

    os.environ.setdefault(_secret.ENV_KEY, _secret.make_secret_key())
    server = RendezvousServer()
    kv_port = server.start()
    kv_addr = driver_addr([])
    coord_port = free_port()
    native_port = free_port()
    kwargs = kwargs or {}

    # Captured by the task closure: executors have their own env, so the
    # job secret must ride the closure, not the driver's os.environ.
    job_secret = os.environ[_secret.ENV_KEY]

    def task(iterator):
        from pyspark import BarrierTaskContext

        ctx = BarrierTaskContext.get()
        rank = ctx.partitionId()
        os.environ["HOROVOD_SECRET_KEY"] = job_secret
        # 'self' sentinel: rank 0 runs on some executor node, not on the
        # driver — it must publish its own routable coordinator address via
        # the rendezvous KV (basics._exchange_coordinator_port).
        os.environ.update(
            task_env(rank, n, kv_addr, kv_port, "self", coord_port,
                     native_port=native_port)
        )
        ctx.barrier()
        yield rank, fn(*args, **kwargs)

    try:
        results = (
            sc.parallelize(range(n), n).barrier().mapPartitions(task).collect()
        )
        return [r for _, r in sorted(results)]
    finally:
        server.stop()
