"""KerasEstimator — fit a tf.keras model on a DataFrame.

Parity: ``horovod/spark/keras/KerasEstimator`` — model + optimizer +
loss compiled per worker, gradients averaged through
:mod:`horovod_tpu.keras`'s DistributedOptimizer, weights broadcast from
rank 0 at start. Requires tensorflow (import-guarded).
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

from ..common.estimator import (
    Estimator,
    Model,
    batches,
    train_val_split,
)
from ..common.params import EstimatorParams


def _require_tf():
    try:
        import tensorflow as tf  # noqa: F401

        return tf
    except ImportError as e:  # pragma: no cover
        raise ImportError(
            "horovod_tpu.spark.keras requires tensorflow; use "
            "horovod_tpu.spark.jax.JaxEstimator for the TF-free flavor"
        ) from e


class KerasEstimator(Estimator):
    def __init__(self, store, model_fn: Callable[[], Any],
                 optimizer_fn: Callable[[], Any], loss: str | Callable,
                 **overrides: Any):
        """``model_fn``/``optimizer_fn`` are zero-arg builders (keras
        objects are not reliably picklable; the reference serializes keras
        models with custom machinery — builders are the honest contract)."""
        _require_tf()
        super().__init__(store, **overrides)
        self.model_fn = model_fn
        self.optimizer_fn = optimizer_fn
        self.loss = loss

    def _worker_fn(self):
        model_fn, optimizer_fn, loss = (
            self.model_fn, self.optimizer_fn, self.loss,
        )

        def fn(data, p: EstimatorParams, shard: int):
            import tensorflow as tf

            import horovod_tpu.keras as hvdk

            hvdk.init()
            model = model_fn()
            opt = hvdk.DistributedOptimizer(
                optimizer_fn(), compression=p.compression,
                backward_passes_per_step=p.backward_passes_per_step)
            model.compile(optimizer=opt, loss=loss)
            x = np.asarray(list(data[p.feature_cols[0]]), np.float32)
            y = np.asarray(list(data[p.label_cols[0]]))
            train, val = train_val_split({"x": x, "y": y}, p.validation,
                                         p.seed)
            x, y = train["x"], train["y"]
            # Build + broadcast initial weights so all workers align.
            model(x[:1])
            if hvdk.size() > 1:
                hvdk.broadcast_variables(model.weights, root_rank=0)
            class _FlushTail(tf.keras.callbacks.Callback):
                # Partial bpps window at epoch end: apply it (collective
                # — callbacks fire symmetrically on every rank).
                def on_epoch_end(self, epoch, logs=None):
                    o = self.model.optimizer
                    if callable(getattr(o, "_hvd_flush", None)):
                        o._hvd_flush()

            history = model.fit(
                x, y, batch_size=p.batch_size, epochs=p.epochs,
                shuffle=p.shuffle, verbose=p.verbose if shard == 0 else 0,
                validation_data=((val["x"], val["y"])
                                 if val is not None else None),
                callbacks=[_FlushTail()],
            )
            return {
                "weights": [np.asarray(w) for w in model.get_weights()],
                "history": history.history,
            }

        return fn

    def _make_model(self, state, run_id: str, params) -> "KerasModel":
        return KerasModel(self.model_fn, state["weights"], run_id,
                          params, history=state["history"])


class KerasModel(Model):
    def __init__(self, model_fn, weights, run_id: str,
                 estimator_params: EstimatorParams, history=None):
        super().__init__(run_id, estimator_params)
        self.model_fn = model_fn
        self.weights = weights
        self.history = history or {}
        self._model = None

    def _materialize(self):
        if self._model is None:
            self._model = self.model_fn()
            x = np.zeros((1,) + tuple(np.shape(self.weights[0])[:0]))
            try:
                self._model.predict(
                    np.zeros((1, self.weights[0].shape[0]), np.float32),
                    verbose=0)
            except Exception:
                pass
            self._model.set_weights(self.weights)
        return self._model

    def predict(self, features: np.ndarray) -> np.ndarray:
        model = self._materialize()
        return np.asarray(model.predict(np.asarray(features, np.float32),
                                        verbose=0))
