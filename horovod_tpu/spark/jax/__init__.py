"""JaxEstimator — the TPU-native Spark estimator flavor.

Parity role: ``horovod/spark/keras/KerasEstimator`` +
``horovod/spark/torch/TorchEstimator`` (fit a framework model on a
DataFrame, get back a Transformer). The model here is a flax ``Module`` +
optax optimizer + loss fn; training runs the framework's
``DistributedOptimizer`` step over the device mesh (pandas/dev path) or
one process per executor (Spark barrier path), gradients averaged by the
framework either way.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import numpy as np

from ..common.estimator import (
    Estimator,
    Model,
    batches,
    train_val_split,
)
from ..common.params import EstimatorParams


def _default_loss(logits, labels):
    import jax
    import jax.numpy as jnp

    # Integer labels -> softmax CE; float labels -> MSE. Dtype inspection
    # only (works on tracers — never materialize a traced value).
    if jnp.issubdtype(jnp.result_type(labels), jnp.integer):
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        onehot = jax.nn.one_hot(labels, logits.shape[-1])
        return -jnp.mean(jnp.sum(onehot * logp, axis=-1))
    return jnp.mean((logits - labels) ** 2)


def _train_worker(model, optimizer, loss_fn, data, p: EstimatorParams,
                  shard: int):
    """The per-worker training loop (runs on Spark executors or locally).

    Serialization note: Spark ships this closure (and the flax module /
    optax transform it captures) to executors with cloudpickle — the same
    mechanism the reference relies on for estimator payloads.
    """
    import jax
    import jax.numpy as jnp
    import optax

    import horovod_tpu as hvd

    loss_fn = loss_fn or _default_loss

    feature_col = p.feature_cols[0]
    label_col = p.label_cols[0]
    x_all = np.asarray(list(data[feature_col]), np.float32)
    y_all = np.asarray(list(data[label_col]))
    train, val = train_val_split({"x": x_all, "y": y_all}, p.validation,
                                 p.seed)
    x_all, y_all = train["x"], train["y"]

    rng = jax.random.PRNGKey(p.seed)
    params = model.init(rng, jnp.asarray(x_all[:1]))["params"]
    opt_state = optimizer.init(params)
    nprocs = hvd.process_count()

    # Reference training shape: each process computes gradients on ITS
    # shard, gradients are allreduce-averaged across processes (native
    # host data plane), then every process applies the identical update.
    # Same-seed init already aligns weights; broadcast is the safety net.
    if nprocs > 1:
        params = jax.tree.map(
            lambda v: jnp.asarray(
                hvd.broadcast(np.asarray(v), root_rank=0)), params)

    @jax.jit
    def grad_step(params, x, y):
        def loss_of(pp):
            logits = model.apply({"params": pp}, x)
            return loss_fn(logits, y)

        return jax.value_and_grad(loss_of)(params)

    @jax.jit
    def apply_step(params, opt_state, grads):
        updates, new_opt = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_opt

    comp = p.compression
    bpps = p.backward_passes_per_step

    def average_grads(grads):
        leaves, treedef = jax.tree.flatten(grads)
        if comp is not None:
            # Wire compression (setCompression parity): cast to the wire
            # dtype around the host exchange, restore after.
            wires = [comp.compress(jnp.asarray(l)) for l in leaves]
            host = [np.asarray(w) for w, _ in wires]
            reduced = hvd.grouped_allreduce(host, op=hvd.Average)
            return jax.tree.unflatten(
                treedef,
                [jnp.asarray(comp.decompress(jnp.asarray(r), c)).astype(
                    l.dtype)
                 for r, (_, c), l in zip(reduced, wires, leaves)],
            )
        host = [np.asarray(l, np.float32) for l in leaves]
        reduced = hvd.grouped_allreduce(host, op=hvd.Average)
        return jax.tree.unflatten(
            treedef,
            [jnp.asarray(r).astype(l.dtype)
             for r, l in zip(reduced, leaves)],
        )

    def apply_accumulated(params, opt_state, acc, n_passes):
        g = jax.tree.map(lambda a: a / n_passes, acc)
        if nprocs > 1:
            g = average_grads(g)
        return apply_step(params, opt_state, g)

    history = []
    for epoch in range(p.epochs):
        losses = []
        acc, acc_n = None, 0
        for batch in batches({"x": x_all, "y": y_all}, p.batch_size,
                             p.shuffle, p.seed + epoch):
            loss, grads = grad_step(
                params, jnp.asarray(batch["x"]), jnp.asarray(batch["y"]))
            losses.append(float(loss))
            # Local accumulation (setBackwardPassesPerStep parity): one
            # exchange + update per bpps microbatches.
            acc = grads if acc is None else jax.tree.map(
                jnp.add, acc, grads)
            acc_n += 1
            if acc_n < bpps:
                continue
            params, opt_state = apply_accumulated(
                params, opt_state, acc, acc_n)
            acc, acc_n = None, 0
        if acc is not None:
            # Partial tail window: apply it (averaged over the passes it
            # actually holds) instead of dropping the work or straddling
            # epochs.
            params, opt_state = apply_accumulated(
                params, opt_state, acc, acc_n)
        epoch_loss = float(np.mean(losses)) if losses else float("nan")
        entry = {"epoch": epoch, "loss": epoch_loss}
        if val is not None:
            vloss = loss_fn(
                model.apply({"params": params}, jnp.asarray(val["x"])),
                jnp.asarray(val["y"]))
            entry["val_loss"] = float(vloss)
        history.append(entry)
        if shard == 0:
            for cb in p.callbacks:
                cb(epoch, history[-1])
            if p.verbose:
                print(f"[jax-estimator] epoch {epoch}: loss={epoch_loss:.4f}",
                      flush=True)
    return {
        "params": jax.tree.map(np.asarray, params),
        "history": history,
    }


class JaxEstimator(Estimator):
    """Fit a flax model on a DataFrame (parity: KerasEstimator/
    TorchEstimator, TPU-native flavor).

    Args: ``model`` (flax Module), ``optimizer`` (optax transform),
    ``loss`` (fn(logits, labels) -> scalar; default CE for int labels,
    MSE otherwise), plus :class:`EstimatorParams` knobs as kwargs.
    """

    def __init__(self, store, model, optimizer, loss: Callable | None = None,
                 **overrides: Any):
        super().__init__(store, **overrides)
        self.model = model
        self.optimizer = optimizer
        self.loss = loss

    def _worker_fn(self):
        model, optimizer, loss_fn = self.model, self.optimizer, self.loss

        def fn(data, p, shard):
            return _train_worker(model, optimizer, loss_fn, data, p, shard)

        return fn

    def _make_model(self, state, run_id: str, params) -> "JaxModel":
        return JaxModel(self.model, state["params"], run_id, params,
                        history=state["history"])


class JaxModel(Model):
    """Trained transformer: ``.transform(df)`` adds a prediction column;
    ``.predict(features)`` runs the flax model."""

    def __init__(self, model, params, run_id: str,
                 estimator_params: EstimatorParams, history=None):
        super().__init__(run_id, estimator_params)
        self.model = model
        self.model_params = params
        self.history = history or []

    def predict(self, features: np.ndarray) -> np.ndarray:
        import jax
        import jax.numpy as jnp

        logits = self.model.apply(
            {"params": self.model_params}, jnp.asarray(features, jnp.float32)
        )
        return np.asarray(logits)
