"""TorchEstimator — fit a PyTorch model on a DataFrame.

Parity: ``horovod/spark/torch/TorchEstimator`` (and the shape of
``spark/lightning``'s) — model + optimizer-factory + loss trained per
worker through :mod:`horovod_tpu.torch`'s native-runtime gradient
averaging, weights broadcast from rank 0 at start, Spark-ML style
``fit(df) -> Model -> transform(df)`` via the shared estimator machinery
(:mod:`horovod_tpu.spark.common`).
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

from ..common.estimator import (
    Estimator,
    Model,
    batches,
    train_val_split,
)
from ..common.params import EstimatorParams


def _require_torch():
    try:
        import torch  # noqa: F401

        return torch
    except ImportError as e:  # pragma: no cover
        raise ImportError(
            "horovod_tpu.spark.torch requires the 'torch' package; use "
            "horovod_tpu.spark.jax.JaxEstimator for the torch-free flavor"
        ) from e


def run_torch_epochs(net, opt, data, p: EstimatorParams, shard: int,
                     train_step, val_step=None, on_epoch_end=None,
                     sched=None, sched_interval: str = "epoch",
                     tag: str = "torch-estimator"):
    """Shared per-worker epoch scaffold for the torch-family estimators
    (plain torch and lightning): column extraction, train/val split,
    label-dtype inference, the minibatch loop with optional LR-scheduler
    stepping (per-``step`` or per-``epoch``), and history/callback/verbose
    bookkeeping on shard 0.

    ``train_step(batch, batch_idx) -> loss tensor`` runs between
    ``opt.zero_grad()`` and ``loss.backward(); opt.step()``;
    ``val_step(batch) -> loss tensor | None`` runs under ``no_grad`` (None
    skips the history column). Returns the history list.
    """
    import torch

    x_all = np.asarray(list(data[p.feature_cols[0]]), np.float32)
    y_all = np.asarray(list(data[p.label_cols[0]]))
    train, val = train_val_split({"x": x_all, "y": y_all},
                                 p.validation, p.seed)
    x_all, y_all = train["x"], train["y"]
    y_dtype = (torch.long if np.issubdtype(y_all.dtype, np.integer)
               else torch.float32)

    def to_batch(cols):
        return (torch.from_numpy(cols["x"]),
                torch.as_tensor(cols["y"], dtype=y_dtype))

    history = []
    for epoch in range(p.epochs):
        losses = []
        net.train()
        for i, cols in enumerate(
            batches({"x": x_all, "y": y_all}, p.batch_size,
                    p.shuffle, p.seed + epoch)
        ):
            opt.zero_grad()
            loss = train_step(to_batch(cols), i)
            loss.backward()
            before = getattr(opt, "update_count", None)
            opt.step()
            # Gate per-step schedulers on REAL updates: with
            # backward_passes_per_step > 1 most step() calls are
            # accumulate-only and must not advance the LR schedule.
            updated = (before is None
                       or getattr(opt, "update_count", None) != before)
            if sched is not None and sched_interval == "step" and updated:
                sched.step()
            losses.append(float(loss.detach()))
        if callable(getattr(opt, "flush_step", None)):
            # Partial tail accumulation window (batch count not divisible
            # by bpps): apply it now instead of dropping the work or
            # straddling epochs.
            opt.flush_step()
        if sched is not None and sched_interval != "step":
            sched.step()
        if on_epoch_end is not None:
            on_epoch_end()
        epoch_loss = float(np.mean(losses)) if losses else float("nan")
        entry = {"epoch": epoch, "loss": epoch_loss}
        if val is not None and val_step is not None:
            net.eval()
            with torch.no_grad():
                vout = val_step(to_batch(val))
            if vout is not None:
                entry["val_loss"] = float(vout)
        history.append(entry)
        if shard == 0:
            for cb in p.callbacks:
                cb(epoch, history[-1])
            if p.verbose:
                print(f"[{tag}] epoch {epoch}: loss={epoch_loss:.4f}",
                      flush=True)
    return history


class TorchEstimator(Estimator):
    """Args: ``model`` (nn.Module — deep-copied per worker),
    ``optimizer_fn`` (params -> torch optimizer), ``loss`` (fn(outputs,
    labels) -> scalar tensor), plus :class:`EstimatorParams` knobs."""

    def __init__(self, store, model, optimizer_fn: Callable,
                 loss: Callable | None = None, **overrides: Any):
        _require_torch()
        super().__init__(store, **overrides)
        self.model = model
        self.optimizer_fn = optimizer_fn
        self.loss = loss

    def _worker_fn(self):
        model, optimizer_fn, loss_fn = (
            self.model, self.optimizer_fn, self.loss,
        )

        def fn(data, p: EstimatorParams, shard: int):
            import copy

            import torch

            import horovod_tpu.torch as hvd

            hvd.init()
            net = copy.deepcopy(model)
            if loss_fn is None:
                loss = torch.nn.functional.mse_loss
            else:
                loss = loss_fn
            opt = hvd.DistributedOptimizer(
                optimizer_fn(net.parameters()),
                named_parameters=net.named_parameters(),
                compression=p.compression or hvd.Compression.none,
                backward_passes_per_step=p.backward_passes_per_step,
            )
            hvd.broadcast_parameters(net.state_dict(), root_rank=0)

            history = run_torch_epochs(
                net, opt, data, p, shard,
                train_step=lambda batch, i: loss(net(batch[0]), batch[1]),
                val_step=lambda batch: loss(net(batch[0]), batch[1]),
                tag="torch-estimator",
            )
            return {
                "state_dict": {
                    k: v.detach().cpu().numpy()
                    for k, v in net.state_dict().items()
                },
                "history": history,
            }

        return fn

    def _make_model(self, state, run_id: str, params) -> "TorchModel":
        return TorchModel(self.model, state["state_dict"], run_id,
                          params, history=state["history"])


class TorchModel(Model):
    def __init__(self, model, state_dict, run_id: str,
                 estimator_params: EstimatorParams, history=None):
        super().__init__(run_id, estimator_params)
        self.model = model
        self.state_dict_np = state_dict
        self.history = history or []
        self._net = None

    def _materialize(self):
        if self._net is None:
            import copy

            import torch

            self._net = copy.deepcopy(self.model)
            self._net.load_state_dict({
                k: torch.from_numpy(np.asarray(v))
                for k, v in self.state_dict_np.items()
            })
            self._net.eval()
        return self._net

    def predict(self, features: np.ndarray) -> np.ndarray:
        import torch

        net = self._materialize()
        with torch.no_grad():
            out = net(torch.from_numpy(np.asarray(features, np.float32)))
        return np.asarray(out)
