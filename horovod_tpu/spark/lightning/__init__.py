"""LightningEstimator — fit a LightningModule-style model on a DataFrame.

Parity: ``horovod/spark/lightning/TorchEstimator`` (+ ``remote.py``). The
reference trains a ``pytorch_lightning.LightningModule`` on Spark
executors by handing pl.Trainer an HorovodStrategy; here the trainer loop
is ours (the same worker loop as :mod:`horovod_tpu.spark.torch`, driven
through :mod:`horovod_tpu.torch`'s native-runtime gradient averaging), and
the model contract is the LightningModule *protocol*, duck-typed:

- ``training_step(batch, batch_idx) -> loss``  (required)
- ``configure_optimizers() -> optimizer | (opts, scheds) | {"optimizer":
  ..., "lr_scheduler": ...}``  (required)
- ``validation_step(batch, batch_idx) -> loss | {"val_loss": ...}``
  (optional — drives the validation history column)
- ``forward(x)`` for inference in the returned transformer
- ``on_train_epoch_end()`` hook (optional)

Because the contract is a protocol, an installed ``pytorch_lightning``
LightningModule satisfies it unmodified, and environments without
lightning (like CI here) can train any ``nn.Module`` subclass that
implements the three methods.
"""

from __future__ import annotations

from typing import Any

from ..common.estimator import Estimator
from ..common.params import EstimatorParams
from ..torch import TorchModel, _require_torch, run_torch_epochs


def _unwrap_scheduler(sched):
    """A scheduler slot may hold the scheduler itself or Lightning's
    lr_scheduler config dict ({"scheduler": ..., "interval": ...});
    returns (scheduler, interval) with interval defaulting to Lightning's
    default of per-epoch stepping."""
    if isinstance(sched, dict):
        interval = sched.get("interval", "epoch")
        if interval not in ("step", "epoch"):
            raise ValueError(
                f"lr_scheduler interval must be 'step' or 'epoch', got "
                f"{interval!r}"
            )
        return sched.get("scheduler"), interval
    return sched, "epoch"


def _split_optimizers(configured):
    """Normalize configure_optimizers()'s documented return forms to
    (optimizer, scheduler_or_None, interval): a bare optimizer, a dict
    ({"optimizer": ..., "lr_scheduler": ...}), a list/tuple of either, or
    the two-list form ([optimizers], [schedulers]). Multi-optimizer
    setups (GAN-style lists) take the first of each, matching the
    reference's single-optimizer Horovod strategy. ``None``/empty (the
    manual-optimization form) is rejected up front — this trainer loop
    drives the optimizer itself."""
    if configured is None or (
        isinstance(configured, (tuple, list)) and not configured
    ):
        raise TypeError(
            "configure_optimizers() returned nothing — Lightning's "
            "manual-optimization form is not supported by "
            "LightningEstimator, which drives the optimizer itself; "
            "return an optimizer (or dict/two-list form)"
        )
    if isinstance(configured, (tuple, list)):
        first = configured[0]
        if isinstance(first, (tuple, list)):  # ([opts], [scheds])
            sched, interval = None, "epoch"
            if len(configured) > 1 and configured[1]:
                sched, interval = _unwrap_scheduler(configured[1][0])
            return first[0], sched, interval
        # list of optimizers or list of config dicts
        configured = first
    if isinstance(configured, dict):
        if "optimizer" not in configured:
            raise TypeError(
                "configure_optimizers() returned a dict without an "
                f"'optimizer' key (got keys {sorted(configured)}); "
                "supported forms: optimizer, {'optimizer': ..., "
                "'lr_scheduler': ...}, or the two-list form"
            )
        sched, interval = _unwrap_scheduler(configured.get("lr_scheduler"))
        return configured["optimizer"], sched, interval
    return configured, None, "epoch"


def _scalar_loss(out):
    """training_step/validation_step may return a loss tensor or a dict
    with 'loss'/'val_loss'."""
    if isinstance(out, dict):
        for key in ("loss", "val_loss"):
            if key in out:
                return out[key]
        raise ValueError(
            f"step returned a dict without 'loss'/'val_loss': {list(out)}"
        )
    return out


class LightningEstimator(Estimator):
    """Args: ``model`` (LightningModule-protocol nn.Module — deep-copied
    per worker), plus :class:`EstimatorParams` knobs. The optimizer comes
    from the model's own ``configure_optimizers`` (the lightning
    contract), wrapped in :func:`horovod_tpu.torch.DistributedOptimizer`.
    """

    def __init__(self, store, model, **overrides: Any):
        _require_torch()
        super().__init__(store, **overrides)
        if not callable(getattr(model, "training_step", None)):
            raise TypeError(
                "LightningEstimator needs a model with training_step(batch,"
                " batch_idx); for plain nn.Module + external loss use "
                "horovod_tpu.spark.torch.TorchEstimator"
            )
        if not callable(getattr(model, "configure_optimizers", None)):
            raise TypeError(
                "LightningEstimator model must implement "
                "configure_optimizers()"
            )
        self.model = model

    def _worker_fn(self):
        model = self.model

        def fn(data, p: EstimatorParams, shard: int):
            import copy

            import horovod_tpu.torch as hvd

            hvd.init()
            net = copy.deepcopy(model)
            opt, sched, interval = _split_optimizers(
                net.configure_optimizers()
            )
            opt = hvd.DistributedOptimizer(
                opt, named_parameters=net.named_parameters(),
                compression=p.compression or hvd.Compression.none,
                backward_passes_per_step=p.backward_passes_per_step,
            )
            hvd.broadcast_parameters(net.state_dict(), root_rank=0)

            def val_step(batch):
                if not callable(getattr(net, "validation_step", None)):
                    return None
                # Lightning permits validation_step -> None (the base
                # class's no-op hook does exactly that): skip the history
                # column rather than crash mid-fit.
                vout = net.validation_step(batch, 0)
                return None if vout is None else _scalar_loss(vout)

            hook = getattr(net, "on_train_epoch_end", None)
            history = run_torch_epochs(
                net, opt, data, p, shard,
                train_step=lambda batch, i: _scalar_loss(
                    net.training_step(batch, i)
                ),
                val_step=val_step,
                on_epoch_end=hook if callable(hook) else None,
                sched=sched,
                sched_interval=interval,
                tag="lightning-estimator",
            )
            return {
                "state_dict": {
                    k: v.detach().cpu().numpy()
                    for k, v in net.state_dict().items()
                },
                "history": history,
            }

        return fn

    def _make_model(self, state, run_id: str, params) -> "LightningModel":
        return LightningModel(
            self.model,
            state["state_dict"],
            run_id,
            params,
            history=state["history"],
        )


class LightningModel(TorchModel):
    """Transformer returned by :meth:`LightningEstimator.fit` — inference
    through the module's ``forward``, state handling shared with
    :class:`horovod_tpu.spark.torch.TorchModel` (parity: TorchModel in
    ``horovod/spark/lightning``)."""
