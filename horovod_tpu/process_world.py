"""Process-level world facts shared by the host-framework surfaces.

``horovod_tpu.torch`` / ``.tensorflow`` / ``.keras`` all describe the same
world — one controller process per host, facts from the launcher env
contract (reference: one rank per accelerator process). One implementation
here so the env-var contract and teardown logic cannot drift between
surfaces.
"""

from __future__ import annotations

import os


def size() -> int:
    return int(os.environ.get("HOROVOD_NUM_PROCESSES", "1") or 1)


def rank() -> int:
    return int(os.environ.get("HOROVOD_PROCESS_ID", "0") or 0)


def local_rank() -> int:
    return int(os.environ.get("HOROVOD_LOCAL_RANK", "0") or 0)


def local_size() -> int:
    return int(os.environ.get("HOROVOD_LOCAL_SIZE", "1") or 1)


def cross_rank() -> int:
    return int(os.environ.get("HOROVOD_CROSS_RANK", "0") or 0)


def cross_size() -> int:
    return int(os.environ.get("HOROVOD_CROSS_SIZE", "1") or 1)


def is_homogeneous() -> bool:
    """True when every host contributes the same local size (parity:
    ``hvd.is_homogeneous``)."""
    return size() == local_size() * cross_size()


def shutdown_native_world() -> None:
    """Tear down the cached native host world (if any)."""
    from .parallel import hierarchical

    if hierarchical._host_world is not None:
        hierarchical._host_world.shutdown()
        hierarchical._host_world = None
