"""Process-level world facts shared by the host-framework surfaces.

``horovod_tpu.torch`` / ``.tensorflow`` / ``.keras`` all describe the same
world — one controller process per host, facts from the launcher env
contract (reference: one rank per accelerator process). One implementation
here so the env-var contract and teardown logic cannot drift between
surfaces.
"""

from __future__ import annotations

import os


def size() -> int:
    return int(os.environ.get("HOROVOD_NUM_PROCESSES", "1") or 1)


def rank() -> int:
    return int(os.environ.get("HOROVOD_PROCESS_ID", "0") or 0)


def local_rank() -> int:
    return int(os.environ.get("HOROVOD_LOCAL_RANK", "0") or 0)


def local_size() -> int:
    return int(os.environ.get("HOROVOD_LOCAL_SIZE", "1") or 1)


def cross_rank() -> int:
    return int(os.environ.get("HOROVOD_CROSS_RANK", "0") or 0)


def cross_size() -> int:
    return int(os.environ.get("HOROVOD_CROSS_SIZE", "1") or 1)


def is_homogeneous() -> bool:
    """True when every host contributes the same local size (parity:
    ``hvd.is_homogeneous``)."""
    return size() == local_size() * cross_size()


def shutdown_native_world() -> None:
    """Tear down the cached native host world (if any)."""
    from .parallel import hierarchical

    if hierarchical._host_world is not None:
        hierarchical._host_world.shutdown()
        hierarchical._host_world = None


# -- Process sets shared by the host-framework surfaces ----------------------
# (parity: horovod/common/process_sets.py; torch/TF/keras all see the same
# sets — the reference's sets are likewise framework-agnostic)


class ProcessSet:
    """A named subset of process ranks; host-surface collectives accept
    ``process_set=`` to run inside it (members only call — reference
    contract). ``process_set_id`` 0 is the global set; subset ids are
    resolved lazily PER NATIVE WORLD (an elastic restart recreates the
    world — ids must not dangle across it)."""

    def __init__(self, ranks, process_set_id: int = -1):
        self.ranks = sorted({int(r) for r in ranks})
        self.process_set_id = process_set_id

    def size(self) -> int:
        return len(self.ranks)

    def rank(self) -> int:
        """This process's rank WITHIN the set (raises for non-members)."""
        me = rank()
        if me not in self.ranks:
            raise ValueError(
                f"process {me} is not a member of set {self.ranks}")
        return self.ranks.index(me)

    def included(self) -> bool:
        return rank() in self.ranks

    def __repr__(self):
        return f"ProcessSet(id={self.process_set_id}, ranks={self.ranks})"


class _GlobalProcessSet(ProcessSet):
    """Lazy world set: rank list materializes from the live world size."""

    def __init__(self):
        self.process_set_id = 0

    @property
    def ranks(self):
        return list(range(size()))


global_process_set = _GlobalProcessSet()

_ps_registry: list = []  # creation order (the collective contract)


def add_process_set(ranks) -> ProcessSet:
    """Create a subset of ranks (collective: every process must call
    with the same sets in the same order; idempotent per rank list).
    Parity: ``hvd.add_process_set`` on the host surfaces."""
    ranks = sorted({int(r) for r in ranks})
    bad = [r for r in ranks if r < 0 or r >= size()]
    if bad:
        raise ValueError(f"ranks {bad} out of range for world size {size()}")
    ps = ProcessSet(ranks)
    _ps_registry.append(ps)
    if size() > 1:
        resolve_ps_id(ps)  # resolve against the live world now
    return ps


def remove_process_set(process_set) -> bool:
    """Drop a subset (parity: ``hvd.remove_process_set`` on the host
    surfaces). COLLECTIVE on EVERY process — members and non-members
    alike, exactly like ``add_process_set`` (the reference contract):
    registries must stay rank-identical or an elastic re-registration
    would assign diverging native ids. Returns False for the global set
    or an unknown/already-removed set.

    Python-level removal: the set leaves the registry, so later
    ``process_set=`` uses raise with guidance. The native-runtime id
    stays allocated — ids are never reused, and re-adding the identical
    rank list legitimately maps back to the same native set."""
    if process_set is None or getattr(process_set, "process_set_id", 0) == 0:
        return False
    key = None
    for i, ps in enumerate(_ps_registry):
        if ps is process_set:
            key = (i, tuple(ps.ranks))
            break
    if size() > 1:
        # Mirror add_process_set's collective stance: agree on WHAT is
        # being removed before touching the registry. A rank removing a
        # different set (or removing alone — this gather then stalls and
        # the inspector names it) diverges registries silently until the
        # next elastic re-registration assigns mismatched native ids;
        # fail at the call site instead.
        keys = allgather_object_host(key)
        if any(k != keys[0] for k in keys):
            raise RuntimeError(
                "remove_process_set is collective but ranks disagree on "
                f"the set being removed: {keys} (index, ranks) per rank")
    if key is None:
        return False
    del _ps_registry[key[0]]
    process_set.process_set_id = -1
    return True


def resolve_ps_id(process_set) -> int:
    """Native set id of ``process_set`` in the CURRENT world.

    Registration happens lazily per world, for ALL created sets in
    creation order — add_process_set is collective and ordered, so the
    native ids agree across ranks no matter which set a rank touches
    first, and a recreated (elastic) world re-registers cleanly instead
    of dangling old ids."""
    if process_set is None or process_set.process_set_id == 0:
        return 0
    if all(ps is not process_set for ps in _ps_registry):
        raise ValueError(
            f"process set {getattr(process_set, 'ranks', '?')} was removed "
            "(or never created via add_process_set)")
    from .parallel.hierarchical import _default_native_world

    w = _default_native_world()
    cache = getattr(w, "_host_ps_map", None)
    if cache is None:
        cache = w._host_ps_map = {}
    key = tuple(process_set.ranks)
    if key in cache:
        process_set.process_set_id = cache[key]
        return cache[key]
    for ps in _ps_registry:
        k = tuple(ps.ranks)
        if k not in cache:
            cache[k] = w.register_process_set(ps.ranks)
        ps.process_set_id = cache[k]
    # The registry-membership guard above guarantees `process_set` was
    # registered by the loop, so `key` is always in the cache here.
    return cache[key]


def _next_world_tag(w, kind: str, psid: int) -> str:
    """Per-WORLD, per-PROCESS-SET auto-name counter. Module-global
    counters would survive an elastic world re-formation in surviving
    processes while fresh workers start at zero; a per-world-but-shared
    counter would diverge the moment a subset op runs (members count it,
    non-members don't) — and the controller pairs ops BY NAME, so
    diverged counters deadlock the next exchange (same reasoning as the
    runtime's per-set _auto_name)."""
    tags = getattr(w, "_obj_tags", None)
    if tags is None:
        tags = w._obj_tags = {}
    n = tags.get((kind, psid), 0) + 1
    tags[(kind, psid)] = n
    scope = f"ps{psid}/" if psid else ""
    return f"{scope}host.{kind}.{n}"


def broadcast_object_host(obj, root_rank: int = 0, name: str | None = None,
                          process_set=None):
    """Pickle-broadcast an object from ``root_rank`` through the NATIVE
    host data plane (two-phase: size header then payload).

    This is the host-surface analog of ``functions.broadcast_object`` —
    which rides jax.distributed and silently no-ops in hvdrun worker
    processes (``jax.process_count()`` is 1 there). ``obj`` is only read
    on the root; other ranks may pass None. Callers on elastic
    re-rendezvous paths should pass a STABLE ``name`` (old and new
    workers' auto counters need not agree).
    """
    import pickle

    import numpy as np

    if size() <= 1:
        return obj
    from .parallel.hierarchical import _default_native_world

    w = _default_native_world()
    psid = resolve_ps_id(process_set)
    tag = name or _next_world_tag(w, "bobj", psid)
    if rank() == root_rank:
        payload = np.frombuffer(pickle.dumps(obj), np.uint8).copy()
    else:
        payload = np.zeros(0, np.uint8)
    n = int(np.asarray(
        w.broadcast(np.array([payload.size], np.int64), root_rank,
                    name=f"{tag}.sz", process_set_id=psid))[0])
    buf = np.zeros(n, np.uint8)
    if rank() == root_rank:
        buf[:] = payload
    out = np.asarray(w.broadcast(buf, root_rank, name=f"{tag}.data",
                                 process_set_id=psid))
    return pickle.loads(out.tobytes())


def allgather_object_host(obj, process_set=None,
                          name: str | None = None) -> list:
    """Gather one picklable object per process into a rank-ordered list
    on every member, through the NATIVE host data plane (reference:
    ``hvd.allgather_object``). Ragged sizes ride ``allgather_v``."""
    import pickle

    import numpy as np

    if size() <= 1:
        return [obj]
    from .parallel.hierarchical import _default_native_world

    w = _default_native_world()
    psid = resolve_ps_id(process_set)
    tag = name or _next_world_tag(w, "agobj", psid)
    payload = np.frombuffer(pickle.dumps(obj), np.uint8).copy()
    # allgather_v's internal size pre-exchange doubles as our split table
    # (return_sizes) — no separate size collective.
    data, sizes = w.allgather_v(payload, name=f"{tag}.data",
                                process_set_id=psid, return_sizes=True)
    data = np.asarray(data)
    out, off = [], 0
    for sz in sizes:
        out.append(pickle.loads(data[off:off + int(sz)].tobytes()))
        off += int(sz)
    return out


def adasum_pair_np(a, b):
    """Numpy Adasum pairwise rule (reference: adasum.h): each side shrunk
    by half its projection onto the other — scaling-invariant."""
    import numpy as np

    af = a.ravel().astype(np.float64)
    bf = b.ravel().astype(np.float64)
    dot = float(af @ bf)
    asq = float(af @ af)
    bsq = float(bf @ bf)
    a_scale = 1.0 - dot / (2.0 * asq) if asq > 0 else 0.0
    b_scale = 1.0 - dot / (2.0 * bsq) if bsq > 0 else 0.0
    return (a_scale * af + b_scale * bf).reshape(a.shape).astype(a.dtype)


def pairwise_tree(parts, pair):
    """Binary-tree reduction of a list by ``pair`` (odd leftovers carry
    to the next round — the reference's non-power-of-two handling). One
    control-flow implementation shared by the numpy (host) and jnp
    (compiled) Adasum regimes."""
    parts = list(parts)
    while len(parts) > 1:
        nxt = [pair(parts[i], parts[i + 1])
               for i in range(0, len(parts) - 1, 2)]
        if len(parts) % 2 == 1:
            nxt.append(parts[-1])
        parts = nxt
    return parts[0]


def adasum_tree_np(parts):
    return pairwise_tree(parts, adasum_pair_np)


def adasum_allreduce_host(x, name: str | None = None,
                          process_set=None):
    """Adasum-allreduce a host array across the process set: gather the
    per-rank contributions through the native plane, evaluate the
    pairwise tree locally (identical result on every member — the same
    gather-then-combine stance as the compiled regime's
    ops/adasum.py, traded against the reference's MPI recursive
    halving)."""
    import numpy as np

    if size() <= 1:
        return np.asarray(x)
    from .parallel.hierarchical import _default_native_world

    w = _default_native_world()
    psid = resolve_ps_id(process_set)
    tag = name or _next_world_tag(w, "adasum", psid)
    x = np.ascontiguousarray(x)
    gathered = np.asarray(
        w.allgather(x[None], name=tag, process_set_id=psid))
    members = w.process_set_size(psid)
    gathered = gathered.reshape((members,) + x.shape)
    return adasum_tree_np([gathered[i] for i in range(members)])
