"""Training→serving bridge: chaos-proven sub-second model hot-swap.

ROADMAP item 5 ("serve heavy traffic from millions of users"): the peer
replication plane (PR 7) already streams every rank's verified,
generation-fenced shard to the KV on each commit — but its only consumer
was recovery. This module adds the serving side of that wire:

1. **Publisher** (:func:`maybe_publish_model` / :func:`maybe_publish_record`
   — the hooks ``elastic/state.py`` calls at the end of every commit):
   mirror the commit's replica record to the KV ``modelstate`` scope
   (``PUT /modelstate/<rank>``, same wire format + sha256 + generation/
   driver-epoch fences as ``peerstate``). **Inert unless
   HOROVOD_SERVE_PUBLISH=1** — unset, the hooks return before touching
   anything, and a publish failure NEVER raises into the commit.
2. **Subscriber** (:class:`ModelSubscriber`): a read-only poll loop that
   pulls the scope into a local :class:`~horovod_tpu.peercheck.ReplicaPool`
   (same ``.prev`` rotation, so a half-landed commit wave completes from
   retained slots), filters integrity-condemned replicas, assembles the
   newest complete checksum-valid same-generation-lineage set via the
   SHARED math (``peercheck.assemble_records`` +
   ``checkpoint.assemble_full_params`` — byte-identical to what recovery
   would install), and hands the result to the server.
3. **RCU hot-swap** (:class:`ModelServer`): inference requests read ONE
   volatile reference (:meth:`ModelServer.current`) — no lock, no
   copy — while :meth:`ModelServer.install` flips the pointer under the
   writer lock. In-flight requests finish on the model they started
   with; new requests see the new one; a reader never observes a
   half-built model because the :class:`ServedModel` is fully
   constructed before the flip.

Robustness contract (the reason this module exists):

- **Never roll backward**: installs are (generation, step)-monotone; a
  zombie trainer's stale publish is fenced twice — at the KV (409) and
  again at install (``rejected{rollback}`` + ``publish_fenced``).
- **Never serve torn bytes**: every record re-verifies its sha256 at
  every hop (KV install gate, pool install, assembly), and the swapped
  set's :func:`~horovod_tpu.peercheck.replica_set_digest` proves the
  served weights byte-exact against the training commit.
- **Never go dark**: when training stops publishing (abort, resize,
  death) the server keeps serving last-good and says so honestly —
  ``hvd_serve_model_age_seconds`` rises, and past
  ``HOROVOD_SERVE_MAX_STALENESS`` a ``serve_degraded`` journal event
  latches (once per degradation, re-armed by the next install).
- **Never thrash**: a flapping trainer meets the min-dwell
  (``HOROVOD_SERVE_MIN_DWELL``) and the swap storm-breaker
  (``HOROVOD_SERVE_STORM_SWAPS`` per ``HOROVOD_SERVE_STORM_WINDOW``).

Chaos injection points: ``model.publish`` (commit-path publication),
``serve.fetch`` (subscriber poll), ``serve.swap`` (the install) — see
:mod:`horovod_tpu.faults`. The HTTP surface (stdlib inference server,
``GET /model`` on the KV) lives in ``runner/serving/`` and
``runner/http/kv_server.py``.

Module import is **stdlib-only** (jax enters lazily through
``checkpoint.assemble_full_params`` on the fsdp branch) so a serving
host needs no framework init to run the subscriber.
"""

from __future__ import annotations

import os
import pickle
import threading
import time
from typing import Any, Callable, Mapping

from . import faults
from . import metrics as _metrics
from . import peercheck
from .peercheck import MODELSTATE_SCOPE  # noqa: F401 — canonical re-export
from .utils.env import get_float, get_int
from .utils.logging import get_logger
from .utils.retry import call_with_retries


def publish_enabled() -> bool:
    """The bridge's master switch. Unset/0, every publish hook is a
    no-op before any client, import, or allocation — the bit-for-bit
    inertness contract the A/B test in tests/test_serving.py proves."""
    return os.environ.get("HOROVOD_SERVE_PUBLISH", "") == "1"


# ---------------------------------------------------------------------------
# Publisher — the training-side commit hook
# ---------------------------------------------------------------------------

class ModelPublisher:
    """Ships commit records to the KV ``modelstate`` scope.

    A dedicated short-timeout client (retries=1 — the publish rides the
    commit path and must never inherit the fat KV retry budget), fenced
    with the caller's generation view. Best-effort by contract: any
    failure degrades serving freshness (the subscriber keeps last-good),
    it never takes down training.
    """

    def __init__(self, client=None,
                 generation_fn: Callable[[], int] | None = None):
        self._client = client
        self._generation_fn = generation_fn or peercheck._env_generation
        self._log = get_logger()

    def client(self):
        if self._client is None:
            addr = os.environ.get("HOROVOD_RENDEZVOUS_ADDR", "")
            port = os.environ.get("HOROVOD_RENDEZVOUS_PORT", "")
            if not addr or not port:
                return None
            from .runner.http.kv_server import KVClient

            self._client = KVClient(
                addr, int(port),
                timeout=get_float("HOROVOD_SERVE_PUBLISH_TIMEOUT", 5.0),
                retries=1, generation_fn=self._generation_fn)
        return self._client

    def publish(self, payload: bytes, step: int, rank: int,
                world_size: int, has_params: bool) -> bool:
        """Encode + ship one commit record. Returns True when it landed.
        Never raises (the commit path calls this)."""
        from urllib.error import HTTPError

        record = peercheck.ReplicaRecord(
            rank=rank, step=step, generation=int(self._generation_fn()),
            world_size=world_size, payload=payload, has_params=has_params)
        blob = peercheck.encode_record(record)
        # SDC/chaos injection, one hit per publish: ``corrupt`` flips
        # bits in the ENCODED blob (digest already stamped — the KV's
        # install gate must 422 it with last-good left authoritative);
        # every other mode keeps its ``fire`` semantics.
        spec = (faults.active().get(faults.MODEL_PUBLISH)
                if faults.armed(faults.MODEL_PUBLISH) else None)
        if spec is not None and spec.mode == "corrupt":
            blob = faults.corrupt_payload(faults.MODEL_PUBLISH, blob)
        try:
            if spec is not None and spec.mode != "corrupt" and \
                    faults.fire(faults.MODEL_PUBLISH):
                raise faults.InjectedFault(
                    f"model publish dropped: rank {rank} step {step}")
            client = self.client()
            if client is None:
                return False
            client.put(MODELSTATE_SCOPE, str(rank), blob)
        except HTTPError as e:
            reason = "fenced" if e.code == 409 else "corrupt"
            try:
                _metrics.SERVE_REJECTED.labels(reason=reason).inc()
                _metrics.event(
                    "publish_fenced" if reason == "fenced"
                    else "model_published",
                    generation=record.generation, rank=rank, step=step,
                    shipped=False, http_status=e.code)
            except Exception:  # noqa: BLE001
                pass
            self._log.warning(
                "serving: publish of step %d rejected by the KV "
                "(HTTP %d): %s", step, e.code, e)
            return False
        except Exception as e:  # noqa: BLE001 — publish is best-effort
            self._log.warning(
                "serving: publish of step %d failed (%s); the serving "
                "tier keeps last-good until the next commit", step, e)
            return False
        try:
            _metrics.event(
                "model_published", generation=record.generation,
                rank=rank, step=step, bytes=len(blob), shipped=True,
                world_size=world_size)
        except Exception:  # noqa: BLE001
            pass
        return True


_publisher: ModelPublisher | None = None
_publisher_lock = threading.Lock()


def _get_publisher(generation_fn=None) -> ModelPublisher:
    global _publisher
    with _publisher_lock:
        if _publisher is None:
            _publisher = ModelPublisher(generation_fn=generation_fn)
        return _publisher


def maybe_publish_record(payload: bytes, step: int, rank: int,
                         world_size: int, has_params: bool,
                         generation_fn=None) -> bool:
    """The ``PeerShardedState.commit`` hook: mirror the already-pickled
    commit record (one shard row per rank, the exact bytes recovery
    would assemble) to the modelstate scope. Inert unless
    HOROVOD_SERVE_PUBLISH=1; never raises."""
    if not publish_enabled():
        return False
    try:
        return _get_publisher(generation_fn).publish(
            payload, step=step, rank=rank, world_size=world_size,
            has_params=has_params)
    except Exception:  # noqa: BLE001 — the commit path must not feel this
        return False


def maybe_publish_model(params_host, step: int) -> bool:
    """The monolithic (``TpuState.commit``) hook: publish the full host
    params as a single-record commit (rank 0, world 1 — the degenerate
    replica set). Only rank 0 publishes (every rank holds the same full
    copy under allreduce). Inert unless HOROVOD_SERVE_PUBLISH=1; never
    raises."""
    if not publish_enabled():
        return False
    try:
        if int(os.environ.get("HOROVOD_RANK", "0") or 0) != 0:
            return False
        payload = pickle.dumps({
            "params": params_host,
            "param_row": None,
            "param_layout": "full",
            "param_meta": None,
            "row": None,
            "layout": "none",
            "extras": {},
        })
        return _get_publisher().publish(
            payload, step=step, rank=0, world_size=1, has_params=True)
    except Exception:  # noqa: BLE001 — the commit path must not feel this
        return False


# ---------------------------------------------------------------------------
# The served model + RCU swap
# ---------------------------------------------------------------------------

class ServedModel:
    """One immutable, fully-assembled model the request path reads via a
    single reference — never mutated after construction (the RCU
    contract: readers holding it keep a consistent world forever)."""

    __slots__ = ("params", "generation", "step", "digest", "world_size",
                 "bytes", "installed_t", "installed_wall")

    def __init__(self, params, generation: int, step: int, digest: str,
                 world_size: int, nbytes: int, installed_t: float,
                 installed_wall: float):
        self.params = params
        self.generation = int(generation)
        self.step = int(step)
        self.digest = digest
        self.world_size = int(world_size)
        self.bytes = int(nbytes)
        self.installed_t = installed_t
        self.installed_wall = installed_wall

    def identity(self) -> tuple[int, int]:
        return (self.generation, self.step)

    def summary(self) -> dict:
        return {"generation": self.generation, "step": self.step,
                "digest": self.digest, "world_size": self.world_size,
                "bytes": self.bytes}


class ModelServer:
    """The serving tier's model holder: lock-free reads, fenced
    RCU-style installs, honest staleness.

    ``clock`` is injectable (monotonic seconds) so the dwell/storm/
    staleness machinery is testable without sleeping.
    """

    def __init__(self, clock: Callable[[], float] | None = None):
        self._clock = clock or time.monotonic
        self._swap_lock = threading.Lock()
        self._model: ServedModel | None = None  # the RCU pointer
        self._swap_times: list[float] = []  # storm-breaker window
        self._degraded = False  # serve_degraded latch
        self._log = get_logger()

    # -- the request path (zero locks) --------------------------------------

    def current(self) -> ServedModel | None:
        """The request path: ONE attribute read. CPython guarantees the
        reference assignment in :meth:`install` is atomic, so a reader
        sees either the old complete model or the new complete model —
        never a mixture (the 100-swap hammer in tests/test_serving.py
        asserts exactly this)."""
        return self._model

    # -- knobs ---------------------------------------------------------------

    @staticmethod
    def min_dwell() -> float:
        """Seconds a model must serve before the next swap (0 = off)."""
        return get_float("HOROVOD_SERVE_MIN_DWELL", 0.0)

    @staticmethod
    def storm_swaps() -> int:
        """Swaps allowed per storm window before the breaker trips
        (0 = off)."""
        return get_int("HOROVOD_SERVE_STORM_SWAPS", 0)

    @staticmethod
    def storm_window() -> float:
        return get_float("HOROVOD_SERVE_STORM_WINDOW", 10.0)

    @staticmethod
    def max_staleness() -> float:
        """The bounded-staleness SLO: model age (seconds since install)
        past which the tier declares itself degraded — while STILL
        serving last-good (degrade, never 500). 0 disables."""
        return get_float("HOROVOD_SERVE_MAX_STALENESS", 0.0)

    # -- the install path ----------------------------------------------------

    def _reject(self, reason: str, detail: str, **fields) -> bool:
        try:
            _metrics.SERVE_REJECTED.labels(reason=reason).inc()
            if reason == "rollback":
                _metrics.event("publish_fenced", reason=reason, **fields)
        except Exception:  # noqa: BLE001
            pass
        self._log.warning("serving: install rejected (%s): %s",
                          reason, detail)
        return False

    def install(self, params, generation: int, step: int, digest: str,
                world_size: int = 1, nbytes: int = 0) -> bool:
        """Atomically swap the served model. Returns True when the new
        model is now being served. Fences, in order:

        - **rollback**: (generation, step) below the served identity —
          a zombie trainer can never roll the fleet backward (same
          identity is a silent no-op: the subscriber re-assembling an
          unchanged commit is steady state, not an error);
        - **dwell**: the served model is younger than the min-dwell;
        - **storm**: the breaker tripped for this window.
        """
        t0 = time.perf_counter()
        if faults.fire(faults.SERVE_SWAP):
            return self._reject(
                "storm", f"swap dropped by fault injection at step {step}")
        with self._swap_lock:
            now = self._clock()
            old = self._model
            if old is not None:
                if (generation, step) < old.identity():
                    return self._reject(
                        "rollback",
                        f"({generation}, {step}) would roll back the "
                        f"served model {old.identity()}",
                        generation=generation, step=step,
                        served_generation=old.generation,
                        served_step=old.step)
                if (generation, step) == old.identity():
                    return False  # steady state: same commit re-assembled
                dwell = self.min_dwell()
                if dwell > 0 and now - old.installed_t < dwell:
                    return self._reject(
                        "dwell",
                        f"served model is {now - old.installed_t:.3f}s "
                        f"old < min dwell {dwell}s")
            limit = self.storm_swaps()
            if limit > 0:
                window = self.storm_window()
                self._swap_times = [t for t in self._swap_times
                                    if now - t < window]
                if len(self._swap_times) >= limit:
                    return self._reject(
                        "storm",
                        f"{len(self._swap_times)} swaps in the last "
                        f"{window}s (limit {limit})")
                self._swap_times.append(now)
            model = ServedModel(
                params, generation=generation, step=step, digest=digest,
                world_size=world_size, nbytes=nbytes, installed_t=now,
                installed_wall=time.time())
            self._model = model  # the RCU flip: one atomic reference set
            self._degraded = False  # fresh model: re-arm the SLO latch
        dt = time.perf_counter() - t0
        try:
            # The installed model is resident HBM on this host for as
            # long as it serves — and during the swap window BOTH the
            # old and new trees are live (RCU: readers may still hold
            # the old reference). Note the RESIDENT side here; the
            # transient double-buffer is what predict_footprint's
            # serve_staging term prices.
            from . import memory as _serve_memory

            _serve_memory.note_resident(
                "serving", nbytes or _serve_memory.tree_nbytes(params))
        except Exception:  # noqa: BLE001 — observability only
            pass
        try:
            _metrics.SERVE_SWAPS.inc()
            _metrics.SERVE_SWAP_SECONDS.observe(dt)
            _metrics.SERVE_MODEL_AGE.set(0.0)
            _metrics.event(
                "model_swapped", generation=generation, step=step,
                digest=digest, world_size=world_size, bytes=nbytes,
                swap_seconds=dt)
        except Exception:  # noqa: BLE001
            pass
        return True

    # -- staleness SLO -------------------------------------------------------

    def age_seconds(self) -> float | None:
        model = self._model
        if model is None:
            return None
        return max(0.0, self._clock() - model.installed_t)

    def tick_staleness(self) -> bool:
        """Refresh the age gauge and latch ``serve_degraded`` once per
        degradation episode (re-armed by the next install). Returns the
        current degraded verdict. Called by the subscriber on every poll
        — including failed ones, which is exactly when it matters."""
        age = self.age_seconds()
        if age is None:
            return False
        try:
            _metrics.SERVE_MODEL_AGE.set(age)
        except Exception:  # noqa: BLE001
            pass
        slo = self.max_staleness()
        if slo <= 0 or age <= slo:
            return False
        if not self._degraded:
            self._degraded = True
            model = self._model
            try:
                _metrics.event(
                    "serve_degraded", age_seconds=age, max_staleness=slo,
                    generation=model.generation, step=model.step)
            except Exception:  # noqa: BLE001
                pass
            self._log.warning(
                "serving: model age %.1fs exceeds the staleness SLO "
                "%.1fs; serving last-good (generation %d, step %d)",
                age, slo, model.generation, model.step)
        return True

    def health(self) -> dict:
        """The ``GET /model`` body of the inference server: status +
        identity + age — never raises, never 500s."""
        model = self._model
        age = self.age_seconds()
        degraded = self.tick_staleness()
        out = {
            "status": ("no_model" if model is None
                       else "degraded" if degraded else "ok"),
            "age_seconds": age,
            "model": None if model is None else model.summary(),
        }
        return out


# ---------------------------------------------------------------------------
# Subscriber — KV scope → assembled model → install
# ---------------------------------------------------------------------------

class ModelSubscriber:
    """Pulls the ``modelstate`` scope, assembles, installs.

    The pull side mirrors ``PeerReplicator.fetch_all``: every record
    lands in a local :class:`~horovod_tpu.peercheck.ReplicaPool` first
    (verify-then-rotate, ``.prev`` retained), so a commit wave the
    trainer half-landed before dying completes from the retained slots —
    the subscriber can assemble a model the KV alone no longer holds
    whole. Integrity-condemned replicas are filtered with the SAME
    condemned-range math as recovery (``peercheck.assemble_records``).
    """

    def __init__(self, server: ModelServer, client=None,
                 scope: str | None = None):
        self.server = server
        self._client = client
        self.scope = scope or os.environ.get(
            "HOROVOD_SERVE_SCOPE", MODELSTATE_SCOPE)
        self.pool = peercheck.ReplicaPool()
        self._quarantine: Mapping[str, Mapping] = {}
        self._log = get_logger()
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()

    def client(self):
        if self._client is None:
            addr = os.environ.get("HOROVOD_RENDEZVOUS_ADDR", "")
            port = os.environ.get("HOROVOD_RENDEZVOUS_PORT", "")
            if not addr or not port:
                return None
            from .runner.http.kv_server import KVClient

            self._client = KVClient(
                addr, int(port),
                timeout=get_float("HOROVOD_SERVE_FETCH_TIMEOUT", 5.0),
                retries=1)
        return self._client

    @staticmethod
    def poll_seconds() -> float:
        return get_float("HOROVOD_SERVE_POLL_SECONDS", 0.5)

    # -- one poll ------------------------------------------------------------

    def _fetch_records(self) -> list:
        """KV scope → verified records (pool-installed current slots +
        every retained slot), with bounded retry on the scope listing —
        an exhausted budget journals ``retry_budget_exhausted`` and
        degrades to whatever the pool already holds."""
        client = self.client()
        if client is None:
            return list(self.pool.records())
        if faults.fire(faults.SERVE_FETCH):
            raise faults.InjectedFault("serve fetch dropped")
        keys = call_with_retries(
            lambda: client.keys(self.scope),
            attempts=get_int("HOROVOD_SERVE_FETCH_RETRIES", 3),
            base_delay=0.05, name="serve.fetch")
        prevs: list = []
        for key in keys:
            try:
                blob = client.get(self.scope, key)
                if blob is None:
                    continue
                if key.endswith(peercheck.PREV_SUFFIX):
                    # The KV's retained slots complete a half-landed
                    # wave for a FRESH subscriber too — read, verify,
                    # but never pool-install (that would rotate the
                    # pool's own current slots away).
                    prevs.append(peercheck.decode_record(blob, verify=True))
                else:
                    self.pool.install(blob)
            except peercheck.ReplicaCorruptError as e:
                self._log.error(
                    "serving: record %r failed verification: %s", key, e)
            except Exception as e:  # noqa: BLE001 — per-key best-effort
                self._log.debug(
                    "serving: record %r fetch failed: %s", key, e)
        return list(self.pool.records()) + prevs

    def _refresh_quarantine(self, client) -> Mapping[str, Mapping]:
        """Best-effort integrity view, caching the last good answer —
        an unreachable server must not un-condemn anything."""
        if client is None:
            return self._quarantine
        try:
            view = client.integrity_view()
            quarantine = view.get("quarantined")
            if isinstance(quarantine, Mapping):
                self._quarantine = quarantine
        except Exception:  # noqa: BLE001 — keep the cached view
            pass
        return self._quarantine

    def poll_once(self) -> bool:
        """One subscribe→assemble→install cycle. Returns True when a NEW
        model was installed. Any failure leaves the served model alone
        (serve last-good) and still ticks the staleness SLO."""
        installed = False
        try:
            records = self._fetch_records()
            client = self._client  # whatever _fetch_records resolved
            quarantine = self._refresh_quarantine(client)
            generation = None
            if client is not None:
                try:
                    generation = int(client.world_version())
                except Exception:  # noqa: BLE001
                    generation = None
            if generation is None:
                generation = max(
                    (r.generation for r in records), default=0)
            members = peercheck.assemble_records(
                records, generation, quarantine=quarantine,
                log=self._log)
            current = self.server.current()
            if (current is not None
                    and (members[0].generation, members[0].step)
                    <= current.identity()):
                return False  # nothing newer: steady state, not a swap
            from . import checkpoint as _checkpoint

            payloads = [pickle.loads(r.payload) for r in members]
            params, _template = _checkpoint.assemble_full_params(payloads)
            installed = self.server.install(
                params,
                generation=members[0].generation,
                step=members[0].step,
                digest=peercheck.replica_set_digest(members),
                world_size=members[0].world_size,
                nbytes=sum(len(r.payload) for r in members))
        except peercheck.ReplicaUnavailableError as e:
            self._log.debug("serving: no assemblable model yet: %s", e)
        except Exception as e:  # noqa: BLE001 — the loop must survive
            self._log.warning("serving: poll failed (%s); serving "
                              "last-good", e)
        finally:
            self.server.tick_staleness()
        return installed

    # -- the loop ------------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="hvd-serve-subscriber", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            self.poll_once()
            self._stop.wait(self.poll_seconds())

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


def reset_for_testing() -> None:
    """Drop the cached publisher singleton (tests re-point the KV)."""
    global _publisher
    with _publisher_lock:
        _publisher = None
