"""Peer-redundant in-memory checkpoints: the replication plane under the
recovery ladder's ``peer`` rung.

The ladder's bottom rung — durable-storage restore — costs minutes of
goodput at pod scale, yet the *common* failure is one preempted host. The
ZeRO-1 layout (PR 4) makes the fix cheap: each rank owns only ~1/n of the
optimizer state, and shard ownership is a pure function of the world size
(``unshard_opt_state`` / ``reshard_opt_state`` are host math), so K peers
holding a rank's shard replica let the survivors re-materialize a departed
rank's state **without ever touching storage**. This module is that plane:

1. **Wire format** (:func:`encode_record` / :func:`decode_record`): one
   self-verifying record per rank per commit — a JSON header (rank, step,
   generation, world size, payload sha256 — the shared digest from
   ``checkpoint.payload_digest``) followed by the opaque payload bytes. A
   torn write, a bit flip, or a half-received body fails verification and
   is rejected at install time, so no pool slot is ever half-written.
2. **Replica pool** (:class:`ReplicaPool`): the bounded in-memory store a
   peer holds replicas in — last good commit per rank plus a ``.prev``
   slot, rotated through ``checkpoint.rotate_slots`` (the same rotation
   contract as the durable ``.prev`` file). Records are verified before
   install; a bad record leaves the previous good one in place.
3. **Replicator** (:class:`PeerReplicator`): on each elastic commit,
   publishes the rank's owned-shard snapshot to the generation-fenced
   ``PUT /peerstate/<rank>`` KV route (a zombie's stale shard bounces off
   the fence and can never poison the pool) and pulls its K ring
   neighbors' records (``HOROVOD_PEERCHECK_REPLICAS``) into the local
   pool. Memory cost of the plane ≈ K/n of the optimizer state per rank.
4. **Assembly** (:meth:`PeerReplicator.assemble`): the recovery side —
   collect the newest *complete, checksum-valid, same-generation-lineage*
   replica set (every rank of the recorded world present at one
   ``(generation, step)``, each record verifying, the generation an
   ancestor of the current one). Any gap or mismatch raises
   :class:`ReplicaUnavailableError`, which the elastic ladder converts
   into a fall-through to the durable rung.

The elastic integration (shard extraction, ``restore_peer``, the
``PeerShardedState`` flavor with 1/n shard-local commits) lives in
``horovod_tpu/elastic/state.py``; the ladder rung itself in
``elastic/runner.py``. This module is **stdlib-only** (no jax) so the KV
server — which verifies records at install time on the driver, before any
framework init — can import it.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Mapping

from . import faults
from . import metrics as _metrics
from .utils.env import get_float, get_int
from .utils.logging import get_logger

#: KV scope replica records publish to (``PUT /peerstate/<rank>``).
PEERSTATE_SCOPE = "peerstate"

#: KV scope the training→serving bridge publishes commit records to
#: (``PUT /modelstate/<rank>``, same wire format and fences as
#: ``peerstate`` — see :mod:`horovod_tpu.serving`). A separate scope so
#: the serving tier's retention/consumption never races recovery's.
MODELSTATE_SCOPE = "modelstate"

#: Suffix of the retained-previous slot (both pool- and server-side).
PREV_SUFFIX = ".prev"

_MAGIC = "HVDPEER1"


def replica_count() -> int:
    """K: how many ring-neighbor ranks hold each rank's shard replica."""
    return max(1, get_int("HOROVOD_PEERCHECK_REPLICAS", 1))


def max_record_bytes() -> int:
    """Server-side backstop on one replica record's wire size."""
    return get_int("HOROVOD_PEERCHECK_MAX_BYTES", 256 << 20)


def retention_depth() -> int:
    """How many ``.prev`` generations each slot rotation retains (pool-
    and server-side alike). The historical default is 1 (current +
    ``.prev``). An armed integrity plane keeps 2: its vote lags the
    condemned commit by up to one full commit (heartbeat cadence +
    driver tick), so both the condemned commit AND the one a racing rank
    lands meanwhile can be quarantined — assembly still needs one clean
    complete group underneath. Unarmed, nothing changes (inertness)."""
    from . import integrity

    return get_int("HOROVOD_PEER_RETAIN",
                   2 if integrity.enabled() else 1)


class ReplicaCorruptError(ValueError):
    """A replica record failed decoding or checksum verification."""


class ReplicaUnavailableError(RuntimeError):
    """No complete, checksum-valid, same-generation-lineage replica set —
    the peer rung must fall through to the durable rung."""


def mesh_coords_of(rank: int, mesh_shape) -> tuple[int, int] | None:
    """The 2-D ``(batch, model)`` mesh coordinates of a flat rank —
    ``(r // model, r % model)``, the placement contract of
    ``parallel.mesh.mesh_2d`` — or None when no (valid) shape is
    given. Provenance only: replica identity stays keyed by flat rank
    (the row layout is mesh-shape independent), the coords let an
    operator read WHICH axis a missing record sat on."""
    if mesh_shape is None:
        return None
    try:
        b, m = (int(v) for v in mesh_shape)
    except (TypeError, ValueError):
        return None
    if b < 1 or m < 1 or not 0 <= int(rank) < b * m:
        return None
    return (int(rank) // m, int(rank) % m)


class ReplicaRecord:
    """One rank's shard snapshot at one commit, plus its provenance."""

    __slots__ = ("rank", "step", "generation", "world_size", "has_params",
                 "mesh_coords", "payload")

    def __init__(self, rank: int, step: int, generation: int,
                 world_size: int, payload: bytes, has_params: bool = False,
                 mesh_coords=None):
        self.rank = int(rank)
        self.step = int(step)
        self.generation = int(generation)
        self.world_size = int(world_size)
        self.has_params = bool(has_params)
        self.mesh_coords = (None if mesh_coords is None
                            else tuple(int(v) for v in mesh_coords))
        self.payload = payload

    def group(self) -> tuple[int, int]:
        """The commit identity records are matched across ranks by."""
        return (self.generation, self.step)

    def summary(self) -> dict:
        out = {"rank": self.rank, "step": self.step,
               "generation": self.generation,
               "world_size": self.world_size,
               "bytes": len(self.payload)}
        if self.mesh_coords is not None:
            out["mesh_coords"] = list(self.mesh_coords)
        return out


def encode_record(record: ReplicaRecord) -> bytes:
    """Wire form: one JSON header line, then the raw payload bytes. The
    header carries the payload's sha256 (the shared checksum from
    ``checkpoint.payload_digest``) so any holder — peer pool, KV server,
    assembling survivor — verifies the same digest."""
    from .checkpoint import payload_digest

    header = json.dumps({
        "magic": _MAGIC,
        "rank": record.rank,
        "step": record.step,
        "generation": record.generation,
        "world_size": record.world_size,
        "has_params": record.has_params,
        # Omitted entirely when None: records from flat-mesh jobs stay
        # byte-identical to the pre-mesh wire form.
        **({"mesh_coords": list(record.mesh_coords)}
           if record.mesh_coords is not None else {}),
        "sha256": payload_digest(record.payload),
        "bytes": len(record.payload),
    }, sort_keys=True).encode()
    return header + b"\n" + record.payload


def decode_record(blob: bytes, verify: bool = True) -> ReplicaRecord:
    """Parse and (by default) checksum-verify a wire record. Raises
    :class:`ReplicaCorruptError` on any malformation — a torn header, a
    short payload, a digest mismatch."""
    from .checkpoint import payload_digest

    nl = blob.find(b"\n")
    if nl < 0:
        raise ReplicaCorruptError("replica record has no header line")
    try:
        header = json.loads(blob[:nl])
    except (ValueError, UnicodeDecodeError) as e:
        raise ReplicaCorruptError(f"replica header unparseable: {e}") from e
    if not isinstance(header, dict) or header.get("magic") != _MAGIC:
        raise ReplicaCorruptError("replica header has no magic")
    payload = blob[nl + 1:]
    try:
        declared = int(header["bytes"])
        record = ReplicaRecord(
            rank=header["rank"], step=header["step"],
            generation=header["generation"],
            world_size=header["world_size"], payload=payload,
            has_params=header.get("has_params", False),
            mesh_coords=header.get("mesh_coords"),
        )
    except (KeyError, TypeError, ValueError) as e:
        raise ReplicaCorruptError(f"replica header incomplete: {e}") from e
    if len(payload) != declared:
        raise ReplicaCorruptError(
            f"replica payload truncated: {len(payload)} of {declared} bytes")
    if verify:
        if faults.fire(faults.PEER_VERIFY):
            raise ReplicaCorruptError(
                "replica checksum mismatch (injected corruption)")
        if payload_digest(payload) != header["sha256"]:
            raise ReplicaCorruptError(
                f"replica payload for rank {record.rank} failed its "
                "checksum (torn/corrupted write)")
    return record


def verify_wire(blob: bytes) -> str | None:
    """Install-time gate used by the KV server: None when ``blob`` is a
    complete, checksum-valid record, else the rejection reason. Never
    raises — the server must answer, not die."""
    try:
        decode_record(blob, verify=True)
        return None
    except ReplicaCorruptError as e:
        return str(e)
    except Exception as e:  # noqa: BLE001 — any failure is a rejection
        return f"replica record unreadable: {e}"


def replica_set_digest(records) -> str:
    """One hex digest identifying a complete replica set's BYTES: the
    sha256 over each member's ``rank:payload_digest`` line, rank order.
    The serving tier stamps every hot-swapped model with it and the KV
    server's ``GET /model`` health view recomputes it from the stored
    records — equality proves the served weights are byte-exact against
    the training-side commit they claim to be."""
    import hashlib

    from .checkpoint import payload_digest

    h = hashlib.sha256()
    for rec in sorted(records, key=lambda r: r.rank):
        h.update(f"{rec.rank}:{payload_digest(rec.payload)}\n".encode())
    return h.hexdigest()


class ReplicaPool:
    """Bounded in-memory replica store: last good record per rank plus a
    ``.prev`` slot, rotated through the shared
    ``checkpoint.rotate_slots`` helper (the durable file rotation's
    mapping flavor). Records are verified BEFORE rotation, so a corrupt
    install attempt leaves both slots untouched."""

    def __init__(self):
        self._lock = threading.Lock()
        self._slots: dict[str, ReplicaRecord] = {}
        try:
            # The memory observatory polls the pool's host-memory bytes
            # live (hvd_hbm_bytes{kind="peer_pool"}): replicas arrive
            # from peers outside any local noting call site.
            from . import memory

            memory.get_observatory().register_supplier(
                "peer_pool", self.nbytes)
        except Exception:  # noqa: BLE001 — observability is best-effort
            pass

    def nbytes(self) -> int:
        """Total encoded payload bytes resident in the pool (both
        slots)."""
        with self._lock:
            return sum(len(r.payload) for r in self._slots.values())

    def install(self, blob_or_record) -> ReplicaRecord:
        """Verify + rotate one record in. Raises
        :class:`ReplicaCorruptError` (pool untouched) on a bad record."""
        from .checkpoint import rotate_slots

        if isinstance(blob_or_record, ReplicaRecord):
            record = blob_or_record
        else:
            record = decode_record(blob_or_record, verify=True)
        with self._lock:
            existing = self._slots.get(str(record.rank))
            if existing is not None and existing.group() == record.group():
                # Same commit re-offered (neighbor pull after our own
                # install): keep the slot, don't rotate prev away.
                return existing
            rotate_slots(self._slots, str(record.rank), record,
                         prev_suffix=PREV_SUFFIX, depth=retention_depth())
            count = len(self._slots)
        try:
            _metrics.PEER_POOL_REPLICAS.set(count)
        except Exception:  # noqa: BLE001 — observability is best-effort
            pass
        return record

    def records(self) -> list[ReplicaRecord]:
        with self._lock:
            return list(self._slots.values())

    def get(self, rank: int, prev: bool = False) -> ReplicaRecord | None:
        key = f"{rank}{PREV_SUFFIX}" if prev else str(rank)
        with self._lock:
            return self._slots.get(key)

    def clear(self) -> None:
        with self._lock:
            self._slots.clear()

    def summary(self) -> dict:
        """Flight-recorder view: what this rank's pool holds right now
        (rides every flight dump, including abort-consume)."""
        with self._lock:
            slots = dict(self._slots)
        return {
            "replicas": {k: r.summary() for k, r in sorted(slots.items())},
            "count": len(slots),
        }


def _env_generation() -> int:
    """The generation replica records are stamped with: the elastic
    worker context's JOINED generation when one exists (the same source
    the heartbeat/abort clients fence with), else the launcher env."""
    from .runner.elastic import worker as elastic_worker

    ctx = elastic_worker._context
    if ctx is not None:
        return ctx.joined_version
    try:
        return int(os.environ.get("HOROVOD_WORLD_VERSION", "0") or 0)
    except ValueError:
        return 0


class PeerReplicator:
    """The per-rank replication agent: publish-own-shard on commit, hold
    K neighbors' replicas in memory, assemble complete sets on recovery.

    ``client`` is anything with the ``KVClient`` surface (``put`` /
    ``get`` / ``keys``); by default a dedicated short-timeout
    generation-fenced client is built from the launcher env (the
    replication PUT rides the commit path and must never inherit the fat
    KV retry budget). ``rank`` / ``world_size_fn`` are injectable for
    single-controller tests; elastic workers derive both from the env
    contract.
    """

    def __init__(self, client=None, k: int | None = None,
                 rank: int | None = None,
                 world_size_fn: Callable[[], int] | None = None,
                 generation_fn: Callable[[], int] | None = None):
        self._client = client
        self._k = k
        self._rank = rank
        self._world_size_fn = world_size_fn
        self._generation_fn = generation_fn or _env_generation
        self.pool = ReplicaPool()
        self._log = get_logger()
        global _active
        _active = self

    # -- world facts ---------------------------------------------------------

    @property
    def rank(self) -> int:
        if self._rank is not None:
            return self._rank
        return int(os.environ.get("HOROVOD_RANK", "0") or 0)

    def world_size(self) -> int:
        if self._world_size_fn is not None:
            return int(self._world_size_fn())
        return int(os.environ.get("HOROVOD_NUM_PROCESSES", "1") or 1)

    @property
    def k(self) -> int:
        return self._k if self._k is not None else replica_count()

    def generation(self) -> int:
        return int(self._generation_fn())

    def _mesh_shape(self) -> tuple[int, int] | None:
        """The configured 2-D mesh shape fitted to THIS replicator's
        world, for record provenance. Best-effort: any failure (no
        config, non-dividing axis) degrades to None — coords are
        diagnostic, never load-bearing."""
        try:
            from .parallel.mesh import resolve_mesh_shape

            shape = resolve_mesh_shape()
            if shape is None:
                return None
            b, m = shape
            n = self.world_size()
            if b == -1:
                if m < 1 or n % m != 0:
                    return None
                b = n // m
            return (b, m) if b * m == n else None
        except Exception:  # noqa: BLE001
            return None

    def repoint(self) -> None:
        """Drop the cached KV client so the next replicate/fetch builds
        a fresh one from the launcher env — called by the worker's
        endpoint re-resolution after a driver crash-restart takeover, so
        the very next commit re-publishes this rank's replica to the
        successor's (empty) peerstate scope and the peer rung re-arms
        with zero durable reads."""
        self._client = None

    def client(self):
        if self._client is None:
            addr = os.environ.get("HOROVOD_RENDEZVOUS_ADDR", "")
            port = os.environ.get("HOROVOD_RENDEZVOUS_PORT", "")
            if not addr or not port:
                return None
            from .runner.http.kv_server import KVClient

            self._client = KVClient(
                addr, int(port),
                timeout=get_float("HOROVOD_PEERCHECK_TIMEOUT", 5.0),
                retries=1, generation_fn=self._generation_fn)
        return self._client

    # -- publish (the commit hook) -------------------------------------------

    def replicate(self, payload: bytes, step: int,
                  has_params: bool = False) -> bool:
        """Publish this rank's shard snapshot for one commit and refresh
        the local pool with the K ring neighbors' records. Best-effort by
        contract: a replication failure degrades the peer rung (recovery
        falls through to durable), it never takes down training. Returns
        True when the record landed on the KV."""
        t0 = time.perf_counter()
        record = ReplicaRecord(
            rank=self.rank, step=step, generation=self.generation(),
            world_size=self.world_size(), payload=payload,
            has_params=has_params,
            mesh_coords=mesh_coords_of(self.rank, self._mesh_shape()))
        blob = encode_record(record)
        # SDC injection point: peer.corrupt flips bits in the ENCODED
        # wire blob (header digest already computed) — a bit-flip on the
        # wire, which the server's install-time verification must reject
        # (422) with the previous good replica left authoritative. The
        # local pool below installs the pre-encoding record, exactly as
        # a real wire flip would leave it.
        blob = faults.corrupt_payload(faults.PEER_CORRUPT, blob)
        shipped = False
        try:
            if faults.fire(faults.PEER_REPLICATE):
                raise faults.InjectedFault(
                    f"peer replication dropped: rank {record.rank} "
                    f"step {step}")
            client = self.client()
            if client is not None:
                client.put(PEERSTATE_SCOPE, str(record.rank), blob)
                shipped = True
            self.pool.install(record)
            self._pull_neighbors(client)
        except Exception as e:  # noqa: BLE001 — replication is best-effort
            self._log.warning(
                "peercheck: replication of step %d failed (%s); the peer "
                "recovery rung degrades to durable until the next commit",
                step, e)
        dt = time.perf_counter() - t0
        try:
            _metrics.PEER_REPLICATION_BYTES.observe(len(blob))
            _metrics.PEER_REPLICATION_SECONDS.observe(dt)
            _metrics.CHECKPOINT_SECONDS.observe(dt, kind="save", rung="peer")
            _metrics.event(
                "peer_replicate", generation=record.generation,
                rank=record.rank, step=step, bytes=len(blob),
                world_size=record.world_size, shipped=shipped)
        except Exception:  # noqa: BLE001
            pass
        return shipped

    def _pull_neighbors(self, client) -> None:
        """Hold the K ring predecessors' records in this rank's in-memory
        pool (replica placement: rank r's shard lives on ranks r+1..r+K
        mod n — every single-host failure leaves K live holders)."""
        if client is None:
            return
        n = self.world_size()
        if n <= 1:
            return
        me = self.rank
        for i in range(1, min(self.k, n - 1) + 1):
            neighbor = (me - i) % n  # we HOLD our predecessors' shards
            try:
                blob = client.get(PEERSTATE_SCOPE, str(neighbor))
                if blob is not None:
                    self.pool.install(blob)
            except Exception as e:  # noqa: BLE001 — best-effort
                self._log.debug(
                    "peercheck: neighbor %d pull failed: %s", neighbor, e)

    # -- assemble (the recovery side) ----------------------------------------

    def fetch_all(self) -> list[ReplicaRecord]:
        """Every decodable record visible to this rank: the local pool
        plus the KV's ``peerstate`` scope (current + ``.prev`` slots).
        Corrupt records are dropped here; completeness is judged in
        :meth:`assemble`."""
        records: list[ReplicaRecord] = list(self.pool.records())
        client = self.client()
        if client is not None:
            try:
                keys = client.keys(PEERSTATE_SCOPE)
            except Exception as e:  # noqa: BLE001
                self._log.warning(
                    "peercheck: cannot list the peerstate scope (%s)", e)
                keys = []
            for key in keys:
                try:
                    blob = client.get(PEERSTATE_SCOPE, key)
                    if blob is not None:
                        records.append(decode_record(blob, verify=True))
                except ReplicaCorruptError as e:
                    self._log.error(
                        "peercheck: replica %r failed verification: %s",
                        key, e)
                except Exception as e:  # noqa: BLE001
                    self._log.debug(
                        "peercheck: replica %r fetch failed: %s", key, e)
        return records

    def latest_step(self, before_generation: int) -> int:
        """The highest commit step recorded by any PRIOR generation
        (``record.generation < before_generation``) — the world-synced
        baseline ranks re-align their commit counters to at every world
        formation. Restricting to prior generations makes the read
        race-free: the server's fence rejects further writes from them
        the moment the generation bumps, so every rank of the new
        generation computes the same maximum no matter how the formation
        interleaves with peers' first commits. Returns 0 when nothing
        qualifies (fresh job, or a stall-only re-join of the SAME
        generation — where every survivor's counter is already
        aligned)."""
        steps = [r.step for r in self.fetch_all()
                 if r.generation < before_generation]
        return max(steps, default=0)

    def quarantined(self) -> Mapping[str, Mapping]:
        """The server's integrity-quarantine map (rank →
        ``{generation, step, host}``), consulted at assembly time so a
        vote-condemned rank's records are dropped from the LOCAL pool
        too (the KV-side eviction cannot reach copies already pulled).
        Empty when the voting plane is unarmed (the inertness contract:
        no extra request), no server is reachable, or nothing is
        quarantined. Best-effort: an unreachable server degrades to no
        filter — exactly the pre-voting behavior."""
        from . import integrity

        if not integrity.enabled():
            return {}
        client = self.client()
        if client is None:
            return {}
        try:
            view = client.integrity_view()
            quarantine = view.get("quarantined")
            return quarantine if isinstance(quarantine, Mapping) else {}
        except Exception as e:  # noqa: BLE001 — filter is best-effort
            self._log.warning(
                "peercheck: cannot read the integrity quarantine (%s); "
                "assembling unfiltered", e)
            return {}

    def assemble(self,
                 current_generation: int | None = None
                 ) -> list[ReplicaRecord]:
        """The newest complete, checksum-valid, same-generation-lineage
        replica set: for some ``(generation, step)`` with ``generation``
        an ancestor of (≤) the current generation, one verified record
        per rank of that commit's world, all agreeing on the world size.
        Returns the records sorted by rank; raises
        :class:`ReplicaUnavailableError` with the gap/mismatch detail
        otherwise (the ladder's cue to fall through to durable)."""
        if current_generation is None:
            current_generation = self.generation()
        return assemble_records(self.fetch_all(), current_generation,
                                quarantine=self.quarantined(),
                                log=self._log)


def assemble_records(records, current_generation: int,
                     quarantine: Mapping | None = None,
                     log=None) -> list[ReplicaRecord]:
    """The pure assembly math, shared by the recovery rung
    (:meth:`PeerReplicator.assemble`) and the serving tier
    (``horovod_tpu/serving.py`` — the read-only subscriber reuses the
    same pool/filter semantics): find the newest ``(generation, step)``
    group with one record per rank of an agreed world, the generation an
    ancestor of (≤) ``current_generation``, and NO member inside an
    integrity-quarantine entry's condemned range.

    A group whose commit identity any in-world rank's condemned range
    covers is skipped OUTRIGHT, never "completed" from other ranks'
    records or ``.prev`` slots — assembling around the tombstone would
    install a wave the vote proved was corrupted mid-flight. Raises
    :class:`ReplicaUnavailableError` naming every rejected group."""
    quarantine = quarantine or {}
    groups: dict[tuple[int, int], dict[int, ReplicaRecord]] = {}
    dropped: dict[tuple[int, int], set[int]] = {}
    for record in records:
        if record.generation > current_generation:
            continue  # not our lineage: a fenced-off future/foreign gen
        entry = quarantine.get(str(record.rank))
        if entry is not None and _condemned(record, entry):
            # The integrity vote named this rank's replica state
            # divergent at (generation, step): every record it
            # committed from that point on is suspect — including
            # the copies already pulled into a LOCAL pool before the
            # vote landed (self-consistent checksums; eviction on the
            # KV cannot reach them). Remembering the condemned
            # (group, rank) — instead of silently dropping the record —
            # lets the completeness pass below refuse to complete the
            # group from .prev slots.
            if log is not None:
                log.error(
                    "peercheck: dropping replica of rank %d at (gen %d, "
                    "step %d) — integrity-quarantined since (gen %s, "
                    "step %s)", record.rank, record.generation,
                    record.step, entry.get("generation"),
                    entry.get("step"))
            dropped.setdefault(record.group(), set()).add(record.rank)
            continue
        slot = groups.setdefault(record.group(), {})
        held = slot.get(record.rank)
        if held is None or len(record.payload) >= len(held.payload):
            slot[record.rank] = record
    if not groups and not dropped:
        raise ReplicaUnavailableError(
            "no replica records visible (pool empty, peerstate scope "
            "empty or unreachable)")
    reasons: list[str] = []
    for group_key in sorted(set(groups) | set(dropped), reverse=True):
        generation, step = group_key
        members = groups.get(group_key, {})
        sizes = {r.world_size for r in members.values()}
        if len(sizes) > 1:
            reasons.append(
                f"(gen {generation}, step {step}): inconsistent world "
                f"sizes {sorted(sizes)}")
            continue
        world = sizes.pop() if sizes else 0
        condemned_here = sorted(
            r for r in dropped.get(group_key, ())
            if world == 0 or r < world)
        if condemned_here:
            # The vote condemned an in-world member of THIS commit wave:
            # the whole group is suspect, even if .prev slots of other
            # ranks could formally complete it — refuse, fall back to an
            # older clean group (or raise).
            reasons.append(
                f"(gen {generation}, step {step}): ranks "
                f"{condemned_here} integrity-quarantined (condemned "
                "range covers this commit)")
            continue
        missing = sorted(set(range(world)) - set(members))
        if missing:
            reasons.append(
                f"(gen {generation}, step {step}): missing ranks "
                f"{missing} of {world}")
            continue
        return [members[r] for r in range(world)]
    raise ReplicaUnavailableError(
        "no complete replica set: " + "; ".join(reasons))


def _condemned(record: ReplicaRecord, entry: Mapping) -> bool:
    """True when ``record`` falls inside a quarantine entry's condemned
    range: from the back-dated start (``from_generation``/``from_step``
    — the vote's own group when no back-date applies) through the
    generation the vote fired in. A later generation's records are a
    DIFFERENT owner of the reused rank id (the re-formed world) and pass
    — matching the KV fence, which lifts on the first
    strictly-newer-generation write.

    Fails CLOSED on a malformed entry: a quarantine record exists for
    this rank but its range is unreadable — treating the replica as
    clean would assemble around the tombstone, so the whole rank's
    history is suspect until a readable entry (or a newer generation)
    says otherwise."""
    try:
        fence_gen = int(entry.get("generation", -1))
        start_gen = int(entry.get("from_generation", fence_gen))
        start_step = int(entry.get("from_step", entry.get("step", 0)))
    except (TypeError, ValueError):
        return True
    return (record.generation <= fence_gen
            and (record.generation, record.step)
            >= (start_gen, start_step))


_active: PeerReplicator | None = None


def active_replicator() -> PeerReplicator | None:
    """The process's most recently constructed replicator (the flight
    recorder reads the pool state through this)."""
    return _active


def pool_summary() -> Mapping[str, Any] | None:
    """Replica-pool state for flight-record dumps, or None when no
    replicator exists in this process. Never raises."""
    try:
        rep = active_replicator()
        return None if rep is None else rep.pool.summary()
    except Exception:  # noqa: BLE001 — postmortems are best-effort
        return None


def reset_for_testing() -> None:
    global _active
    _active = None
