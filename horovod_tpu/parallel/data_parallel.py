"""Data-parallel training step factory — Horovod's core capability, compiled.

The reference's training contract (SURVEY.md §4.2): forward/backward runs
per-replica, per-parameter gradients are allreduce-averaged by the
background runtime, then the optimizer applies them. The compiled
equivalent builds the whole step as one SPMD program: batch sharded over the
``hvd`` axis, parameters replicated, gradients averaged by the
DistributedOptimizer *inside* the program (one fused AllReduce HLO per
bucket over ICI), optimizer update replicated. XLA overlaps the gradient
allreduce with remaining backprop where dataflow allows — the compiled
analog of Horovod's comm/compute overlap.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
from jax.sharding import PartitionSpec as P


class _StallWatchedStep:
    """Default-on stall watch for factory-built train steps.

    The reference's stall inspector watches EVERYTHING submitted,
    unconditionally (``stall_inspector.cc``); requiring users to call
    ``hvd.fetch`` themselves left the exact user the inspector exists
    for — a vanilla training loop hanging inside jit — unwatched. Every
    Kth call (``HOROVOD_STALL_CHECK_STEPS``, default 50; <=0 disables)
    the step's results route through :func:`horovod_tpu.stall.fetch`:
    a local inspector ticket plus, in multi-controller worlds, the
    cross-rank ``stallwatch/<name>`` announcement that NAMES a diverged
    rank. Between check steps the call is a passthrough, so the watch
    costs one pipeline drain per K steps.

    Attribute access delegates to the wrapped callable, so jit surfaces
    (``lower``, ``clear_cache`` — which ``tune_step_fusion`` requires)
    keep working.
    """

    def __init__(self, fn, name_prefix: str):
        from ..utils.env import get_int

        self._fn = fn
        self._prefix = name_prefix
        self._every = get_int("HOROVOD_STALL_CHECK_STEPS", 50)
        self._calls = 0
        self._trace_calls = 0

    @staticmethod
    def _cross_rank_available() -> bool:
        """True when the cross-rank stallwatch can ride a host plane
        this deployment actually has: an already-formed native world, or
        the launcher env contract that makes one formable. NOT cached
        and NEVER forms the world itself — a jax.distributed job that
        deliberately skips the host plane must not have one spun up (or
        crash on a missing rendezvous) as a side effect of the watch."""
        import os

        from . import hierarchical

        return (hierarchical._host_world is not None
                or bool(os.environ.get("HOROVOD_NATIVE_PORT"))
                or bool(os.environ.get("HOROVOD_RENDEZVOUS_ADDR")))

    def _step_number(self, cross_rank: bool) -> int:
        """Watch-step counter. In multi-controller worlds the stallwatch
        wire name must be RANK-IDENTICAL, and a process-local counter
        diverges across elastic re-formations (a survivor has called the
        step N times, a fresh worker 0) — so the counter lives on the
        native world object, which every member recreates together at
        each (re-)formation."""
        from ..process_world import size as _psize

        if cross_rank and _psize() > 1:
            from .hierarchical import _default_native_world

            w = _default_native_world()
            n = getattr(w, "_stepwatch_n", 0) + 1
            w._stepwatch_n = n
            return n
        self._calls += 1
        return self._calls

    @staticmethod
    def _tuning_live() -> bool:
        """True while ANY transparent autotune warmup window is live in
        this process — not just one wrapping our own callable: a co-step
        (built mid-warmup, returned unwrapped) must also defer its drain
        or it biases the first tuner's samples."""
        from ..autotune import _active_tuner

        return bool(_active_tuner and _active_tuner[0]._hvd_tuning)

    def __call__(self, *args, **kwargs):
        from ..autotune import _poison_error, warmup_aborted

        if warmup_aborted():
            # A mid-warmup autotune abort poisons EVERY factory step in
            # the process, not just the tuner's wrapper: co-built steps
            # and steps built post-abort pass through maybe_autotune_step
            # bare, but all of them route through this wrapper — and all
            # of them would trace collective sequences that may diverge
            # from peers that pinned the broadcast winner.
            raise _poison_error()
        from .. import tracing

        tuning = self._tuning_live()
        watch_due = False
        cross = False
        n = 0
        if self._every > 0 and not tuning:
            cross = self._cross_rank_available()
            n = self._step_number(cross)
            watch_due = n % self._every == 0
        tracer = tracing.get_tracer()
        # Every call opens a step record in the flight-recorder ring
        # (cheap: one dict append; un-synced steps time only the async
        # dispatch). Every HOROVOD_TRACE_SAMPLE-th call OF THIS WRAPPER
        # additionally blocks on the results — real step wall time — and
        # ships its spans to the rendezvous KV for the cross-rank merge.
        # The sampling counter is per-wrapper, not the shared tracer
        # counter: two interleaved factory steps (train + eval) sharing
        # one process counter could alias one of them out of sampling
        # forever. Sampling defers while an autotune warmup is live,
        # exactly like the stall watch: the pipeline drain would bias
        # the tuner's samples.
        self._trace_calls += 1
        try:
            from .. import faults

            if faults.fire(faults.MEMORY_PRESSURE):
                # drop = synthetic device OOM at the step boundary: the
                # deterministic injector behind the memory observatory's
                # forensics tests (caught and dumped just below, exactly
                # like a real RESOURCE_EXHAUSTED out of the jitted call).
                raise RuntimeError(
                    "RESOURCE_EXHAUSTED: injected memory pressure "
                    "(fault point memory.pressure)")
            with tracer.step_scope(self._prefix) as rec:
                sample = tracing.sample_every()
                sample_due = (not tuning and sample > 0
                              and self._trace_calls % sample == 0)
                if watch_due:
                    import jax

                    from ..stall import watch

                    # The announcement precedes the DISPATCH: on backends
                    # that execute synchronously (CPU) a diverged peer hangs
                    # this rank inside the jitted call itself, before any
                    # post-hoc fetch could announce.
                    with watch(name=f"{self._prefix}.{n}",
                               cross_rank=cross):
                        out = self._fn(*args, **kwargs)
                        out = jax.block_until_ready(out)
                    rec.synced = True
                else:
                    out = self._fn(*args, **kwargs)
                    if sample_due:
                        import jax

                        out = jax.block_until_ready(out)
                        rec.synced = True
                rec.ship = sample_due and rec.synced
        except Exception as exc:
            # The factory step boundary is the OOM forensics consumer:
            # a RESOURCE_EXHAUSTED surfacing here dumps a memory flight
            # record naming the top resident leaves and the
            # predicted-vs-measured delta, then re-raises untouched
            # (recovery policy belongs to the elastic loop, not here).
            try:
                from .. import memory

                if memory.is_oom_error(exc):
                    memory.dump_oom_record(exc, step=self._prefix)
            except Exception:  # noqa: BLE001 — forensics must not
                pass  # mask the original failure
            raise
        return out

    @property
    def _hvd_unwatched(self):
        """The bare step callable — timing loops (tune_step_fusion) use
        this so a watch step's pipeline drain cannot bias a candidate."""
        return self._fn

    def __getattr__(self, item):
        if item == "_fn":  # guard: lookup before __init__ must not recurse
            raise AttributeError(item)
        return getattr(self._fn, item)


def _resolve_mesh_axis(mesh, axis_name, hierarchical):
    """Shared factory plumbing: resolve (mesh, axis_name) from the
    explicit arguments, the ``hierarchical`` request, or the env flag
    (``HOROVOD_HIERARCHICAL_ALLREDUCE``). See :func:`make_train_step`
    for the argument contract."""
    from .. import basics

    from_env = hierarchical is None
    if from_env:
        cfg = basics._state.config
        hierarchical = bool(cfg and cfg.hierarchical_allreduce)
    if hierarchical and mesh is not None:
        if not from_env:
            raise ValueError(
                "pass either hierarchical=... or mesh=, not both (an "
                "explicit mesh defines its own axes)"
            )
        # Env flag + explicit mesh: the explicit mesh wins, loudly.
        from ..utils.logging import get_logger

        get_logger().warning(
            "HOROVOD_HIERARCHICAL_ALLREDUCE is set but the step factory "
            "got an explicit mesh; using the explicit mesh (flat reduction)"
        )
        hierarchical = False
    if hierarchical:
        from .hierarchical import HIERARCHICAL_AXES, hierarchical_mesh

        factors = (hierarchical if isinstance(hierarchical, tuple)
                   else (None, None))
        mesh = hierarchical_mesh(*factors)
        axis_name = HIERARCHICAL_AXES
    if mesh is None:
        mesh = basics.global_mesh()
    if axis_name is None:
        axis_name = basics.global_axis_name()
    return mesh, axis_name


class DeferredParams:
    """Handle over the sharded step's updated-parameter allgather (the
    ``deferred_param_gather=True`` eager path).

    The gather program is already DISPATCHED when the handle is returned
    — jax's async dispatch runs the collective while the host does other
    work between steps (data loading, metrics, checkpoint bookkeeping).
    Touch :attr:`params` (or pass the handle straight back into the step)
    to use the gathered tree; :meth:`block_until_ready` waits explicitly.
    """

    def __init__(self, params):
        self._params = params

    @property
    def params(self):
        return self._params

    def block_until_ready(self):
        jax.block_until_ready(self._params)
        return self._params


def _sharded_spec_of(optimizer):
    """The optimizer's ReduceSpec when it was built with
    ``sync_mode='sharded'``, else None."""
    from ..optimizer import reduce_spec_of

    spec = reduce_spec_of(optimizer)
    if spec is not None and getattr(spec, "sync_mode", None) == "sharded":
        return spec
    return None


def _fsdp_spec_of(optimizer):
    """The optimizer's ReduceSpec when it was built with
    ``sync_mode='fsdp'``, else None."""
    from ..optimizer import reduce_spec_of

    spec = reduce_spec_of(optimizer)
    if spec is not None and getattr(spec, "sync_mode", None) == "fsdp":
        return spec
    return None


def _check_flat_axis(axis_name, what: str, sync_mode: str = "sharded"):
    from ..exceptions import SyncModeIneligibleError
    from .mesh import MESH2D_AXES

    if (isinstance(axis_name, (tuple, list))
            and tuple(axis_name) == MESH2D_AXES):
        # The (batch, model) tuple is a flat-rank factorization, not the
        # hierarchical (cross, local) composition — ZeRO-1 reduces over
        # it in flat order (batch major), so the ownership map is intact.
        return
    if not isinstance(axis_name, str):
        raise SyncModeIneligibleError(
            f"sync_mode='{sync_mode}' does not compose with the "
            f"hierarchical (cross, local) mesh in {what}; use the flat "
            f"axis (the two-level reduction already reduce-scatters its "
            f"local leg"
            + (" — and the fsdp shard ownership map is defined over ONE "
               "world axis" if sync_mode == "fsdp" else "")
            + "). For ICI x DCN hierarchy WITH this sync mode, set "
            "HOROVOD_COMMS_PLANNER: the planner's two_level schedule "
            "composes the same legs per bucket on the flat axis "
            "(ops/comms_planner.py)")


def _planner_autotune_candidates():
    """The comms planner's algorithm axis for the transparent tuner —
    non-None only when ``HOROVOD_COMMS_PLANNER=auto`` and more than one
    algorithm is eligible for this world (``comms_planner
    .autotune_candidates``). Guarded: the factories must build even
    when the planner cannot introspect the world yet."""
    try:
        from ..ops.comms_planner import autotune_candidates

        return autotune_candidates()
    except Exception:  # noqa: BLE001
        return None


def shard_state(tree, mesh=None, axis_name: str | None = None):
    """Place a stacked sharded optimizer state (leading world axis, from
    ``hvd.init_sharded_state`` / a sharded optimizer's ``init``) on the
    mesh, sharded along that axis — so each rank holds only its 1/n of
    the state. The sharded counterpart of :func:`replicate`.

    On a 2-D ``(batch, model)`` mesh the leading world axis splits over
    BOTH mesh axes; the default placement is the fsdp row order
    (``("model", "batch")`` — row ``m*batch + b`` on device ``(b, m)``,
    per ``ops.fusion.shard_ownership_2d``). Pass
    ``axis_name=("batch", "model")`` for the ZeRO-1 flat-order layout."""
    from jax.sharding import NamedSharding

    from .. import basics
    from .mesh import MESH2D_ROW_AXES, is_mesh_2d

    if mesh is None:
        mesh = basics.global_mesh()
    if axis_name is None:
        axis_name = (MESH2D_ROW_AXES if is_mesh_2d(mesh)
                     else basics.global_axis_name())
    sharding = NamedSharding(mesh, P(axis_name))
    return jax.tree.map(partial(jax.device_put, device=sharding), tree)


def _record_mesh_axes(sizes: dict) -> None:
    try:
        from .. import metrics

        for axis, v in sizes.items():
            metrics.MESH_AXIS_SIZE.set(int(v), axis=axis)
    except Exception:  # noqa: BLE001 — instrumentation is best-effort
        pass


def _resolve_mesh_2d(mesh, hierarchical):
    """The 2-D ``(batch, model)`` mesh this factory call compiles
    against, or None for the flat 1-D wire. Precedence: an explicit 2-D
    ``mesh=`` argument > ``HOROVOD_MESH_SHAPE`` > an autotune mesh-shape
    pin. With none of the three (the default) this returns None and the
    factory takes the pre-mesh code path byte for byte — the knob-unset
    inertness contract."""
    from .mesh import is_mesh_2d, mesh_2d, resolve_mesh_shape

    if mesh is not None:
        return mesh if is_mesh_2d(mesh) else None
    shape = resolve_mesh_shape()
    if shape is None:
        return None
    hier = hierarchical
    if hier is None:
        from .. import basics

        cfg = basics._state.config
        hier = bool(cfg and cfg.hierarchical_allreduce)
    if hier:
        raise ValueError(
            "HOROVOD_MESH_SHAPE does not compose with the hierarchical "
            "(cross, local) allreduce: the 2-D (batch, model) mesh "
            "already places each collective leg on its link class "
            "(model on ICI, batch across). Unset one of the two knobs "
            "(docs/perf.md, '2-D mesh' guard table)")
    return mesh_2d(*shape)


def make_train_step(
    loss_fn: Callable[..., Any],
    optimizer,
    mesh=None,
    axis_name: str | None = None,
    donate: bool = True,
    loss_is_averaged: bool = True,
    hierarchical: bool | tuple | None = None,
    deferred_param_gather: bool = False,
):
    """Build a jitted SPMD train step.

    Args:
      loss_fn: ``loss_fn(params, batch) -> scalar`` (per-shard mean loss).
      optimizer: an optax GradientTransformation — wrap with
        ``hvd.DistributedOptimizer`` for gradient averaging; a bare
        optimizer yields single-replica behavior (grads NOT synced).
      mesh: defaults to the global 1-D 'hvd' mesh from ``init()``.
      axis_name: collective axis (defaults to the global axis).
      loss_is_averaged: if True the reported loss is pmean'd across shards.
      hierarchical: two-level (cross, local) sharding — the consumer of
        ``HOROVOD_HIERARCHICAL_ALLREDUCE`` (reference:
        ``NCCLHierarchicalAllreduce``). None → follow the env flag; True →
        mesh from host topology; a ``(cross, local)`` tuple → explicit
        factors. The DistributedOptimizer then reduces gradients
        reduce-scatter(ICI) → allreduce(DCN) → allgather(ICI).
      deferred_param_gather: sharded sync mode only — split the step into
        a core program (reduce-scatter + shard update, returning the
        updated parameter SHARDS) and a separate allgather program whose
        dispatched result rides a :class:`DeferredParams` handle; the
        gather runs while the host does between-step work. The returned
        step accepts either a full params pytree or the previous call's
        handle.

    Returns:
      ``step(params, opt_state, batch) -> (params, opt_state, loss)``,
      compiled; ``batch`` is sharded along its leading axis, params/opt_state
      replicated. A ``sync_mode='sharded'`` DistributedOptimizer switches
      the program to ZeRO-1 form: per-bucket reduce-scatter, shard-local
      inner update (opt_state is the STACKED sharded layout from the
      optimizer's ``init`` — place it with :func:`shard_state`), and an
      allgather of the updated parameter shards issued off the gradient
      critical path.
    """
    spec = _sharded_spec_of(optimizer)
    fsdp_spec = _fsdp_spec_of(optimizer)
    mesh2d = _resolve_mesh_2d(mesh, hierarchical)
    if mesh2d is not None:
        return _make_mesh2d_train_step(
            loss_fn, optimizer, spec, fsdp_spec, mesh2d, donate,
            loss_is_averaged, deferred_param_gather)
    mesh, axis_name = _resolve_mesh_axis(mesh, axis_name, hierarchical)
    from ..exceptions import SyncModeIneligibleError

    if deferred_param_gather and fsdp_spec is not None:
        raise SyncModeIneligibleError(
            "deferred_param_gather does not apply to sync_mode='fsdp': "
            "fsdp has NO trailing parameter allgather to defer — the "
            "shard-local update writes back to the resident shard, and "
            "the next forward's per-segment gathers are the only "
            "re-materialization")
    if deferred_param_gather and spec is None:
        raise ValueError(
            "deferred_param_gather requires a DistributedOptimizer built "
            "with sync_mode='sharded' (there is no parameter allgather to "
            "defer in allreduce mode)")
    if fsdp_spec is not None:
        _check_flat_axis(axis_name, "make_train_step", "fsdp")
        return _make_fsdp_train_step(
            loss_fn, fsdp_spec, mesh, axis_name, donate, loss_is_averaged)
    if spec is not None:
        _check_flat_axis(axis_name, "make_train_step")
        return _make_sharded_train_step(
            loss_fn, spec, mesh, axis_name, donate, loss_is_averaged,
            deferred_param_gather)
    return _make_allreduce_train_step(
        loss_fn, optimizer, mesh, axis_name, donate, loss_is_averaged)


def _make_allreduce_train_step(loss_fn, optimizer, mesh, axis_name,
                               donate, loss_is_averaged):
    """The monolithic (allreduce-mode) program — replicated params and
    opt_state, batch sharded over ``axis_name`` (a flat axis, the
    hierarchical (cross, local) tuple, or the 2-D (batch, model) tuple:
    the optimizer's allreduce resolves the bound axis form at trace
    time and takes the matching two-level composition for tuples)."""
    import optax

    def spmd_step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, new_opt_state = optimizer.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        if loss_is_averaged:
            loss = jax.lax.pmean(loss, axis_name)
        return new_params, new_opt_state, loss

    sharded = jax.shard_map(
        spmd_step,
        mesh=mesh,
        in_specs=(P(), P(), P(axis_name)),
        out_specs=(P(), P(), P()),
        check_vma=False,
    )
    donate_argnums = (0, 1) if donate else ()
    from ..autotune import maybe_autotune_step

    # Layering: stall watch OUTSIDE the autotuner OUTSIDE the jit — the
    # tuner owns re-tracing (clear_cache) and the watch defers while a
    # tuning window is live so its pipeline drain cannot bias a sample.
    return _StallWatchedStep(
        maybe_autotune_step(
            jax.jit(sharded, donate_argnums=donate_argnums),
            algorithm_candidates=_planner_autotune_candidates()),
        "train_step")


def _make_sharded_train_step(loss_fn, spec, mesh, axis_name, donate,
                             loss_is_averaged, deferred_param_gather):
    """The sync_mode='sharded' program for :func:`make_train_step`:
    reduce-scatter per bucket → inner update on the locally owned shard
    (opt_state sharded over the axis, leading world dim stripped inside)
    → allgather of the UPDATED PARAMETER shards. With
    ``deferred_param_gather`` the allgather compiles as its own program
    whose dispatch rides a :class:`DeferredParams` handle."""
    from ..autotune import maybe_autotune_step
    from ..optimizer import sharded_step_update

    def spmd_step(params, opt_state, batch):
        local_state = jax.tree.map(lambda a: a[0], opt_state)
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        new_params, new_local = sharded_step_update(
            spec, grads, local_state, params, axis_name=axis_name,
            gather=not deferred_param_gather)
        out_state = jax.tree.map(lambda a: a[None], new_local)
        if deferred_param_gather:
            # Updated params are still SHARDS here; stack them on the
            # world axis for the separate gather program.
            new_params = jax.tree.map(lambda a: a[None], new_params)
        if loss_is_averaged:
            loss = jax.lax.pmean(loss, axis_name)
        return new_params, out_state, loss

    donate_argnums = (0, 1) if donate else ()
    if not deferred_param_gather:
        sharded = jax.shard_map(
            spmd_step,
            mesh=mesh,
            in_specs=(P(), P(axis_name), P(axis_name)),
            out_specs=(P(), P(axis_name), P()),
            check_vma=False,
        )
        return _StallWatchedStep(
            maybe_autotune_step(
                jax.jit(sharded, donate_argnums=donate_argnums),
                algorithm_candidates=_planner_autotune_candidates()),
            "train_step")

    core = jax.jit(
        jax.shard_map(
            spmd_step,
            mesh=mesh,
            in_specs=(P(), P(axis_name), P(axis_name)),
            out_specs=(P(axis_name), P(axis_name), P()),
            check_vma=False,
        ),
        donate_argnums=donate_argnums,
    )
    gather_prog: dict = {}
    int8 = getattr(spec.compression, "marker", None) == "int8"

    def step(params, opt_state, batch):
        if isinstance(params, DeferredParams):
            params = params.params
        templates = jax.tree.map(
            lambda p: jax.ShapeDtypeStruct(p.shape, p.dtype), params)
        shards, new_state, loss = core(params, opt_state, batch)
        gj = gather_prog.get("jit")
        if gj is None:
            from ..optimizer import _gather_param_shards, _known_size

            n = _known_size(spec.process_set)

            def gather_spmd(stacked, counter=None):
                local = jax.tree.map(lambda a: a[0], stacked)
                # The core already advanced the counter; this step's
                # quantization salt is the PRE-increment value, matching
                # the non-deferred path's rounding exactly.
                salt = counter[0] - 1 if int8 else None
                return _gather_param_shards(
                    local, templates, spec.compression, axis_name, n,
                    spec.fusion_threshold_bytes, spec.num_groups,
                    quant_salt=salt)

            in_specs = ((P(axis_name), P(axis_name)) if int8
                        else (P(axis_name),))
            gj = gather_prog["jit"] = jax.jit(
                jax.shard_map(
                    gather_spmd,
                    mesh=mesh,
                    in_specs=in_specs,
                    out_specs=P(),
                    check_vma=False,
                ),
                # Donate only the shards: the int8 counter rides the
                # live optimizer state.
                donate_argnums=(0,) if donate else (),
            )
        args = (shards, new_state.counter) if int8 else (shards,)
        from .. import tracing

        # Host-visible half of the sharded wire: the updated-parameter
        # allgather dispatch (the program itself runs async while the
        # host does between-step work; the span times the dispatch and
        # marks WHERE the gather sat relative to the step).
        with tracing.span("param_allgather", "collective",
                          args={"deferred": True}):
            deferred = gj(*args)
        return DeferredParams(deferred), new_state, loss

    # No transparent autotune here: the wrapper owns two programs and the
    # tuner's clear_cache contract assumes one jitted callable.
    return _StallWatchedStep(step, "train_step")


def _make_fsdp_train_step(loss_fn, spec, mesh, axis_name, donate,
                          loss_is_averaged, num_segments=None,
                          name_prefix: str = "train_step"):
    """The sync_mode='fsdp' program (ZeRO-3): parameters arrive as a
    :class:`param_sharding.ShardedParams` of stacked ``(world, shard)``
    rows sharded over the axis — each rank resident-holds ~1/n of the
    model. Per segment, the forward allgathers the segment's parameters
    just in time (independent HLOs: XLA overlaps segment k+1's gather
    with segment k's compute), the backward emits the segment's gradient
    reduce-scatter inside backprop (the gather boundary's custom-vjp),
    and the shard-local inner update writes back to the resident shard
    with no trailing allgather.

    ``step(sharded_params, opt_state, batch) -> (sharded_params,
    opt_state, loss)`` — build the resident layout with
    ``hvd.shard_params(params)`` + ``shard_state``, and the stacked
    optimizer state with the fsdp optimizer's ``init``.
    """
    import optax

    from ..autotune import maybe_autotune_step
    from ..optimizer import _SaltState, _known_size
    from .param_sharding import ShardedParams, gather_params

    int8 = getattr(spec.compression, "marker", None) == "int8"
    n = _known_size(spec.process_set)
    if n is None:
        raise ValueError(
            "sync_mode='fsdp' needs a known process-set size at step-build "
            "time (init() first)")

    def spmd_step(sharded_params, opt_state, batch):
        if not isinstance(sharded_params, ShardedParams):
            # SyncModeIneligibleError: this is a static-config
            # eligibility fact, and the sync-mode sweep's skip net
            # (autotune.tune_step_sync_mode) skips exactly this class —
            # a builder that feeds replicated params must skip the fsdp
            # candidate, not abort the sweep.
            from ..exceptions import SyncModeIneligibleError

            raise SyncModeIneligibleError(
                "the fsdp train step takes resident ShardedParams (build "
                "with hvd.shard_params(params) and place with "
                f"shard_state), got {type(sharded_params).__name__}")
        meta = sharded_params.meta
        # Strip the leading world axis: inside the shard_map each rank
        # sees its own (1, s) row of every leaf.
        shards = jax.tree.unflatten(
            meta.treedef, [a[0] for a in sharded_params.rows])
        local_state = jax.tree.map(lambda a: a[0], opt_state)
        if int8:
            inner_local, salt = local_state.inner_state, local_state.counter
        else:
            inner_local, salt = local_state, None

        def loss_of(sh):
            full = gather_params(sh, meta, spec, axis_name, n, salt=salt,
                                 num_segments=num_segments)
            return loss_fn(full, batch)

        # Gradients arrive ALREADY reduce-scattered to the shard domain:
        # each segment boundary's backward emitted its reducescatter
        # inside backprop and its cotangent IS the owned (s,) slice.
        loss, grad_shards = jax.value_and_grad(loss_of)(shards)
        updates, new_inner = spec.inner.update(grad_shards, inner_local,
                                               shards)
        new_shards = optax.apply_updates(shards, updates)
        new_local = _SaltState(new_inner, salt + 1) if int8 else new_inner
        new_rows = ShardedParams(
            [a[None] for a in jax.tree.leaves(new_shards)], meta)
        new_state = jax.tree.map(lambda a: a[None], new_local)
        if loss_is_averaged:
            loss = jax.lax.pmean(loss, axis_name)
        return new_rows, new_state, loss

    sharded = jax.shard_map(
        spmd_step,
        mesh=mesh,
        in_specs=(P(axis_name), P(axis_name), P(axis_name)),
        out_specs=(P(axis_name), P(axis_name), P()),
        check_vma=False,
    )
    donate_argnums = (0, 1) if donate else ()
    return _StallWatchedStep(
        maybe_autotune_step(
            jax.jit(sharded, donate_argnums=donate_argnums),
            algorithm_candidates=_planner_autotune_candidates()),
        name_prefix)


def _make_mesh2d_train_step(loss_fn, optimizer, spec, fsdp_spec, mesh2d,
                            donate, loss_is_averaged,
                            deferred_param_gather):
    """Dispatch a factory call onto the 2-D ``(batch, model)`` mesh:
    fsdp takes the two-leg wire (:func:`_make_fsdp_train_step_2d`),
    ZeRO-1 reduces over the flat-rank axis tuple, and the monolithic
    mode takes the two-level allreduce composition (model leg on ICI,
    batch leg across). Guard table: expert_set x model and the deferred
    parameter gather are unsupported compositions."""
    from ..exceptions import SyncModeIneligibleError
    from ..optimizer import reduce_spec_of
    from .mesh import MESH2D_AXES, mesh_axis_sizes

    sizes = mesh_axis_sizes(mesh2d)
    _record_mesh_axes(sizes)
    any_spec = fsdp_spec or spec or reduce_spec_of(optimizer)
    if any_spec is not None and getattr(any_spec, "expert_set", None):
        raise SyncModeIneligibleError(
            "expert_set x model is an unsupported mesh composition: the "
            "expert alltoall already owns the intra-host links the "
            "model axis would claim, and the expert-partitioned "
            "reduction is defined over the flat world. Run MoE jobs "
            "without HOROVOD_MESH_SHAPE (docs/perf.md, '2-D mesh' "
            "guard table)")
    if deferred_param_gather:
        raise SyncModeIneligibleError(
            "deferred_param_gather x model is an unsupported mesh "
            "composition: the deferred allgather program is built over "
            "the flat axis (docs/perf.md, '2-D mesh' guard table)")
    if fsdp_spec is not None:
        return _make_fsdp_train_step_2d(
            loss_fn, fsdp_spec, mesh2d, donate, loss_is_averaged)
    if spec is not None:
        return _make_sharded_train_step(
            loss_fn, spec, mesh2d, MESH2D_AXES, donate, loss_is_averaged,
            False)
    return _make_allreduce_train_step(
        loss_fn, optimizer, mesh2d, MESH2D_AXES, donate, loss_is_averaged)


def _make_fsdp_train_step_2d(loss_fn, spec, mesh2d, donate,
                             loss_is_averaged, num_segments=None,
                             name_prefix: str = "train_step"):
    """The sync_mode='fsdp' program on the 2-D ``(batch, model)`` mesh.

    The resident layout is byte-identical to the flat wire — the same
    :class:`param_sharding.ShardedParams` stacked ``(world, shard)``
    rows, ``world = batch*model`` (``ops.fusion.shard_ownership_2d``) —
    but the rows place over BOTH mesh axes in model-major order
    (``P(("model", "batch"))``: row ``m*batch + b`` on device
    ``(b, m)``), and each per-segment collective splits into two legs:
    the batch leg rides the existing bucketed RS/AG machinery over the
    long hops, the model leg is a plain ICI all_gather/psum_scatter XLA
    schedules on the shortest links (:func:`param_sharding
    .gather_params_2d`). The batch slice shards over both axes in flat
    rank order, so the loss trajectory matches the 1-D fsdp run to
    reduction-order noise while 1/model of the gather bytes leave the
    slow links.
    """
    import optax

    from ..autotune import maybe_autotune_step
    from ..optimizer import _SaltState, _known_size
    from .mesh import MESH2D_AXES, MESH2D_ROW_AXES, mesh_axis_sizes
    from .param_sharding import ShardedParams, gather_params_2d

    int8 = getattr(spec.compression, "marker", None) == "int8"
    sizes = mesh_axis_sizes(mesh2d)
    b, m = sizes["batch"], sizes["model"]
    n = _known_size(spec.process_set)
    if n is None:
        raise ValueError(
            "sync_mode='fsdp' needs a known process-set size at step-build "
            "time (init() first)")
    if n != b * m:
        raise ValueError(
            f"mesh {b}x{m} does not cover the process set of {n} rank(s)")

    def spmd_step(sharded_params, opt_state, batch):
        if not isinstance(sharded_params, ShardedParams):
            from ..exceptions import SyncModeIneligibleError

            raise SyncModeIneligibleError(
                "the fsdp train step takes resident ShardedParams (build "
                "with hvd.shard_params(params) and place with "
                f"shard_state), got {type(sharded_params).__name__}")
        meta = sharded_params.meta
        # Inside the shard_map each device sees its own (1, s) row of
        # every leaf — row m*batch + b under the model-major placement.
        shards = jax.tree.unflatten(
            meta.treedef, [a[0] for a in sharded_params.rows])
        local_state = jax.tree.map(lambda a: a[0], opt_state)
        if int8:
            inner_local, salt = local_state.inner_state, local_state.counter
        else:
            inner_local, salt = local_state, None

        def loss_of(sh):
            full = gather_params_2d(sh, meta, spec, b, m, salt=salt,
                                    num_segments=num_segments)
            return loss_fn(full, batch)

        # Gradients arrive ALREADY reduce-scattered to the shard domain:
        # each segment boundary's backward emitted its model-leg
        # psum_scatter and batch-leg reducescatter inside backprop and
        # its cotangent IS the owned (s,) slice.
        loss, grad_shards = jax.value_and_grad(loss_of)(shards)
        updates, new_inner = spec.inner.update(grad_shards, inner_local,
                                               shards)
        new_shards = optax.apply_updates(shards, updates)
        new_local = _SaltState(new_inner, salt + 1) if int8 else new_inner
        new_rows = ShardedParams(
            [a[None] for a in jax.tree.leaves(new_shards)], meta)
        new_state = jax.tree.map(lambda a: a[None], new_local)
        if loss_is_averaged:
            loss = jax.lax.pmean(loss, MESH2D_AXES)
        return new_rows, new_state, loss

    sharded = jax.shard_map(
        spmd_step,
        mesh=mesh2d,
        in_specs=(P(MESH2D_ROW_AXES), P(MESH2D_ROW_AXES), P(MESH2D_AXES)),
        out_specs=(P(MESH2D_ROW_AXES), P(MESH2D_ROW_AXES), P()),
        check_vma=False,
    )
    donate_argnums = (0, 1) if donate else ()
    return _StallWatchedStep(
        maybe_autotune_step(
            jax.jit(sharded, donate_argnums=donate_argnums),
            algorithm_candidates=_planner_autotune_candidates()),
        name_prefix)


def _segment_sync(leaves, seg_index, spec, axis_name, salt):
    """Identity-forward / reduce-backward boundary for ONE segment.

    The forward pass returns the segment's leaves unchanged; the
    custom-vjp backward reduces the segment's COTANGENTS through the
    exact wire the DistributedOptimizer was built with (op, compression,
    scaling, bucketing — via ``optimizer._reduce_grads``). Because the
    boundary sits inside the differentiated function, the collective is
    emitted at the point in the backward pass where this segment's
    gradients finish accumulating — for late-layer segments that is
    EARLY in the backward, so XLA's latency-hiding scheduler can overlap
    the transfer with the remaining layers' backward compute.

    ``salt`` (the int8 stochastic-rounding step counter) rides the
    forward as a residual rather than a closure: custom-vjp rules must
    not close over tracers, and its cotangent is the usual float0
    placeholder for integer primals.

    In the SHARDED sync mode the boundary's backward emits the segment's
    reduce-scatter instead (still inside the backward pass, so it still
    overlaps backward compute); the cotangent contract forces full
    primal shapes, so each reduced shard rides a zero background at its
    owner offset (``optimizer._embed_shards``) and the step extracts the
    shards afterwards (``optimizer._local_shards`` — exact, since
    non-owned positions are zeros it never reads).
    """
    import numpy as np

    from ..optimizer import _known_size, _reduce_grads
    from ..profiler import annotate_collective

    sharded_mode = getattr(spec, "sync_mode", "allreduce") == "sharded"

    def reduce_cts(cts, s):
        with annotate_collective(f"overlap.segment{seg_index}"):
            if sharded_mode:
                from ..optimizer import _embed_shards, _reducescatter_grads

                n = _known_size(spec.process_set)
                shards = _reducescatter_grads(
                    list(cts),
                    spec.op,
                    axis_name,
                    spec.compression,
                    spec.prescale_factor,
                    spec.postscale_factor,
                    spec.fusion_threshold_bytes,
                    spec.num_groups,
                    world_size=n,
                    quant_salt=s,
                    issue_reversed=True,
                )
                return _embed_shards(shards, list(cts), axis_name, n)
            return _reduce_grads(
                list(cts),
                spec.op,
                axis_name,
                spec.compression,
                spec.prescale_factor,
                spec.postscale_factor,
                spec.fusion_threshold_bytes,
                spec.num_groups,
                world_size=_known_size(spec.process_set),
                quant_salt=s,
                issue_reversed=True,
            )

    if salt is None:

        @jax.custom_vjp
        def ident(ls):
            return list(ls)

        def fwd(ls):
            return list(ls), None

        def bwd(_, cts):
            return (reduce_cts(cts, None),)

        ident.defvjp(fwd, bwd)
        return ident(list(leaves))

    @jax.custom_vjp
    def ident_salted(ls, s):
        return list(ls)

    def fwd_salted(ls, s):
        return list(ls), s

    def bwd_salted(s, cts):
        return (reduce_cts(cts, s),
                np.zeros(np.shape(s), jax.dtypes.float0))

    ident_salted.defvjp(fwd_salted, bwd_salted)
    return ident_salted(list(leaves), salt)


def overlap_gradient_sync(
    params,
    spec,
    axis_name=None,
    num_segments: int | None = None,
    salt=None,
):
    """Wrap a parameter pytree so its gradients are reduced SEGMENT BY
    SEGMENT inside the backward pass — the communication-overlap
    scheduler's core primitive.

    The pytree's leaves are split into K contiguous byte-balanced
    segments (``ops.fusion.segment_leaves`` — layer order, so the last
    segment's gradients materialize first during backprop) and each
    segment gets an identity-forward / reduce-backward custom-vjp
    boundary. Differentiating through the wrapped tree yields gradients
    that are ALREADY reduced, with each segment's collective issued at
    the point its gradients finish accumulating instead of after a
    global post-backward barrier.

    Must be applied INSIDE the differentiated function::

        spec = hvd.reduce_spec_of(dist_optimizer)

        def loss_of(p):
            return loss_fn(hvd.overlap_gradient_sync(p, spec), batch)

        loss, grads = jax.value_and_grad(loss_of)(params)  # reduced
        updates, st = spec.inner.update(grads, inner_state, params)

    Args:
      params: the parameter pytree being differentiated.
      spec: a :class:`horovod_tpu.optimizer.ReduceSpec` (from
        ``reduce_spec_of``) naming the wire to issue per segment.
      axis_name: collective axis (name or hierarchical ``(cross,
        local)`` tuple); defaults to the trace-time resolution for the
        spec's process set, exactly like the DistributedOptimizer.
      num_segments: segment count K; defaults to the autotuned /
        ``HOROVOD_OVERLAP_SEGMENTS`` value
        (``ops.fusion.overlap_segments``). K=1 degenerates to the
        monolithic single-boundary reduction.
      salt: optional int8 stochastic-rounding step counter (see
        ``ops.quantization._sround``).
    """
    from ..ops.fusion import overlap_segments, segment_leaves

    if axis_name is None:
        from ..ops.collective_ops import _effective_traced_axis

        axis_name = (_effective_traced_axis(spec.process_set)
                     or spec.process_set.axis_name)
    k = num_segments if num_segments is not None else overlap_segments()
    leaves, treedef = jax.tree.flatten(params)
    # Note the FULL leaf layout before segmentation: the per-segment
    # wires below note only their subsets, and the model-guided autotune
    # predictor prices candidates against the whole flush.
    import jax.numpy as jnp

    from ..ops.fusion import _note_leaf_sizes

    _note_leaf_sizes([jnp.asarray(l) for l in leaves])
    new_leaves = list(leaves)
    for si, idx in enumerate(segment_leaves(leaves, k)):
        synced = _segment_sync(
            [leaves[i] for i in idx], si, spec, axis_name, salt)
        for i, s in zip(idx, synced):
            new_leaves[i] = s
    return jax.tree.unflatten(treedef, new_leaves)


def make_overlapped_train_step(
    loss_fn: Callable[..., Any],
    optimizer,
    mesh=None,
    axis_name: str | None = None,
    donate: bool = True,
    loss_is_averaged: bool = True,
    hierarchical: bool | tuple | None = None,
    num_segments: int | None = None,
):
    """Build a jitted SPMD train step whose gradient allreduces OVERLAP
    the backward pass — the compiled realization of Horovod's headline
    optimization (the reference's background thread starts reducing
    early-ready gradients while later layers still differentiate).

    Same contract as :func:`make_train_step`, with two differences:

    - ``optimizer`` MUST be a ``hvd.DistributedOptimizer``-wrapped
      transformation: its attached :class:`ReduceSpec` tells the
      scheduler which wire (op / compression / scaling / bucketing) to
      issue per segment, and the step applies the bare inner optimizer
      to the already-reduced gradients.
    - ``num_segments`` fixes the segment count K; by default it follows
      the autotuned decision (the transparent tuner gains a joint
      (threshold, segments) grid under ``HOROVOD_AUTOTUNE=1``) or
      ``HOROVOD_OVERLAP_SEGMENTS``.

    The parameter pytree is split into K contiguous byte-balanced
    segments (reverse-topological issue: during backward the LAST
    segment's gradients materialize first, and its collective is
    emitted right there), so ICI/DCN transfer of segment *i* runs
    concurrently with backward compute of segments *< i* instead of
    serializing after the full backward. Hierarchical (cross, local)
    meshes compose per segment: each segment's buckets take the
    two-level reduce-scatter → cross-allreduce → allgather form,
    including the int8-compressed exchange.
    """
    import optax

    from ..optimizer import _SaltState, reduce_spec_of

    spec = reduce_spec_of(optimizer)
    if spec is None:
        raise ValueError(
            "make_overlapped_train_step requires a DistributedOptimizer-"
            "wrapped optimizer (its ReduceSpec tells the scheduler which "
            "wire to issue per segment); got a bare transformation")
    if spec.backward_passes_per_step != 1:
        raise ValueError(
            "the overlap scheduler does not compose with "
            "backward_passes_per_step > 1: accumulation defers the "
            "reduction to every k-th microstep, so most steps have no "
            "communication to overlap — use make_train_step")
    int8 = getattr(spec.compression, "marker", None) == "int8"
    sharded_mode = getattr(spec, "sync_mode", "allreduce") == "sharded"
    mesh2d = _resolve_mesh_2d(mesh, hierarchical)
    if mesh2d is not None:
        if getattr(spec, "sync_mode", "allreduce") != "fsdp":
            from ..exceptions import SyncModeIneligibleError

            raise SyncModeIneligibleError(
                "the overlap scheduler on a 2-D (batch, model) mesh is "
                "only defined for sync_mode='fsdp' (whose gather "
                "boundaries ARE the overlap machinery); allreduce/"
                "sharded overlapped steps run on the flat axis — unset "
                "HOROVOD_MESH_SHAPE or use make_train_step "
                "(docs/perf.md, '2-D mesh' guard table)")
        from .mesh import mesh_axis_sizes

        _record_mesh_axes(mesh_axis_sizes(mesh2d))
        return _make_fsdp_train_step_2d(
            loss_fn, spec, mesh2d, donate, loss_is_averaged,
            num_segments=num_segments,
            name_prefix="overlapped_train_step")
    mesh, axis_name = _resolve_mesh_axis(mesh, axis_name, hierarchical)
    if getattr(spec, "sync_mode", "allreduce") == "fsdp":
        # fsdp's gather boundaries ARE the overlap machinery: each
        # segment's reduce-scatter already rides a custom-vjp backward
        # inside backprop, and the per-segment forward gathers prefetch
        # against neighboring compute — the overlapped factory is the
        # same program, with the requested segment count honored.
        _check_flat_axis(axis_name, "make_overlapped_train_step", "fsdp")
        return _make_fsdp_train_step(
            loss_fn, spec, mesh, axis_name, donate, loss_is_averaged,
            num_segments=num_segments, name_prefix="overlapped_train_step")
    if sharded_mode:
        _check_flat_axis(axis_name, "make_overlapped_train_step")

    def spmd_step(params, opt_state, batch):
        from ..ops.collective_ops import _effective_traced_axis

        effective = (_effective_traced_axis(spec.process_set)
                     or spec.process_set.axis_name)
        if sharded_mode:
            local_state = jax.tree.map(lambda a: a[0], opt_state)
            salt = local_state.counter if int8 else None
        elif int8:
            inner_state, salt = opt_state.inner_state, opt_state.counter
        else:
            inner_state, salt = opt_state, None

        def loss_of(p):
            synced = overlap_gradient_sync(
                p, spec, axis_name=effective,
                num_segments=num_segments, salt=salt)
            return loss_fn(synced, batch)

        loss, grads = jax.value_and_grad(loss_of)(params)
        if sharded_mode:
            # Gradients arrive reduce-SCATTERED: each segment boundary's
            # backward emitted its reducescatter inside backprop and
            # placed this rank's shard on a zero background; slice the
            # shards back out, update only the owned shard, and gather
            # the updated PARAMETER shards — off the gradient path.
            from ..optimizer import _known_size, _local_shards
            from ..optimizer import sharded_step_update

            grad_shards = _local_shards(
                grads, effective, _known_size(spec.process_set))
            new_params, new_local = sharded_step_update(
                spec, grad_shards, local_state, params,
                axis_name=effective, grads_are_shards=True)
            new_state = jax.tree.map(lambda a: a[None], new_local)
            if loss_is_averaged:
                loss = jax.lax.pmean(loss, axis_name)
            return new_params, new_state, loss
        # Gradients arrive REDUCED (the segment collectives ran inside
        # the backward), so the bare inner optimizer applies them. Each
        # leaf's update depends only on its own reduced gradient, so in
        # the compiled program segment i's update can proceed while
        # segment i-1 is still reducing — the monolithic path's global
        # post-backward barrier (one concat depending on every gradient)
        # does not exist here.
        updates, new_inner = spec.inner.update(grads, inner_state, params)
        new_params = optax.apply_updates(params, updates)
        new_state = _SaltState(new_inner, salt + 1) if int8 else new_inner
        if loss_is_averaged:
            loss = jax.lax.pmean(loss, axis_name)
        return new_params, new_state, loss

    opt_spec = P(axis_name) if sharded_mode else P()
    sharded = jax.shard_map(
        spmd_step,
        mesh=mesh,
        in_specs=(P(), opt_spec, P(axis_name)),
        out_specs=(P(), opt_spec, P()),
        check_vma=False,
    )
    donate_argnums = (0, 1) if donate else ()
    from ..autotune import DEFAULT_SEGMENT_CANDIDATES, maybe_autotune_step

    # The transparent tuner gains the segments axis only when K floats;
    # an explicit num_segments is the user's decision, threshold-only.
    seg_cands = (None if num_segments is not None
                 else DEFAULT_SEGMENT_CANDIDATES)
    return _StallWatchedStep(
        maybe_autotune_step(
            jax.jit(sharded, donate_argnums=donate_argnums),
            segment_candidates=seg_cands,
            algorithm_candidates=_planner_autotune_candidates()),
        "overlapped_train_step")


def shard_batch(batch, mesh=None, axis_name: str | None = None):
    """Place a host batch on the mesh, sharded along the leading axis.

    On a 2-D ``(batch, model)`` mesh the leading dim splits over BOTH
    axes in flat rank order (``("batch", "model")`` — rank ``b*model+m``
    gets the same rows it would on the flat 1-D mesh)."""
    from jax.sharding import NamedSharding

    from .. import basics
    from .mesh import MESH2D_AXES, is_mesh_2d

    if mesh is None:
        mesh = basics.global_mesh()
    if axis_name is None:
        axis_name = (MESH2D_AXES if is_mesh_2d(mesh)
                     else basics.global_axis_name())
    sharding = NamedSharding(mesh, P(axis_name))
    return jax.tree.map(partial(jax.device_put, device=sharding), batch)


def replicate(tree, mesh=None):
    """Place params/opt_state replicated over the mesh.

    Always copies: the result owns fresh buffers, so donating it to a
    jitted step (``donate_argnums``) can never invalidate the caller's
    source arrays. ``jax.device_put`` alone aliases the source into shard 0
    of the replicated array (even with ``may_alias=False``), and a donated
    step then silently deletes the original tree; the explicit ``jnp.copy``
    breaks that alias.
    """
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    from .. import basics

    if mesh is None:
        mesh = basics.global_mesh()
    sharding = NamedSharding(mesh, P())

    def _copy_put(leaf):
        leaf = jnp.copy(leaf) if isinstance(leaf, jax.Array) else leaf
        return jax.device_put(leaf, sharding)

    return jax.tree.map(_copy_put, tree)


def make_elastic_train_step(
    loss_fn: Callable[..., Any],
    optimizer,
    mesh=None,
    axis_name: str | None = None,
):
    """Build a train step for ELASTIC multi-process worlds.

    Elastic workers run without ``jax.distributed`` (its coordination
    client aborts survivors on peer death — see docs/elastic.md), so the
    world is two-level: each process's LOCAL devices form a compiled DP
    mesh, and gradients cross processes on the native host data plane
    (which re-forms in-process after failures). This factory compiles the
    local leg (shard_map + local pmean) and performs the cross leg with a
    fused host allreduce each step — the two-level composition of
    ``host_hierarchical_allreduce`` specialized for training.

    Returns ``step(params, opt_state, batch) -> (params, opt_state,
    loss)`` where ``batch`` is this PROCESS's shard (leading dim divisible
    by the local device count). The world size may change between calls
    (the native world re-forms lazily); gradients always average over the
    processes currently in the world.
    """
    import numpy as np
    import jax.numpy as jnp
    import optax

    from .. import basics

    from ..exceptions import SyncModeIneligibleError

    if _sharded_spec_of(optimizer) is not None:
        raise SyncModeIneligibleError(
            "make_elastic_train_step does not support sync_mode='sharded' "
            "(its cross-process leg reduces on the host plane, outside the "
            "compiled shard domain); build the compiled step with "
            "make_train_step and let hvd.elastic.TpuState(...,"
            "sharded_optimizer=...) re-shard state across world changes")
    if _fsdp_spec_of(optimizer) is not None:
        raise SyncModeIneligibleError(
            "make_elastic_train_step does not support sync_mode='fsdp' "
            "(its cross-process leg reduces on the host plane, outside "
            "the compiled shard domain where the per-segment parameter "
            "gathers live); build the compiled step with make_train_step "
            "and let hvd.elastic.PeerShardedState re-shard the resident "
            "parameter and optimizer shards across world changes")
    mesh = mesh or basics.global_mesh()
    axis = axis_name or basics.global_axis_name()

    def local_grads(params, batch):
        def loss_of(p):
            return loss_fn(p, batch)

        loss, grads = jax.value_and_grad(loss_of)(params)
        # Local-device mean: the ICI-compiled leg.
        grads = jax.tree.map(lambda g: jax.lax.pmean(g, axis), grads)
        return jax.lax.pmean(loss, axis), grads

    grad_step = jax.jit(
        jax.shard_map(
            local_grads,
            mesh=mesh,
            in_specs=(P(), P(axis)),
            out_specs=(P(), P()),
            check_vma=False,
        )
    )

    @jax.jit
    def apply_step(params, opt_state, grads):
        updates, new_opt = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_opt

    def step(params, opt_state, batch):
        import os

        from .. import tracing

        from .. import attribution

        # The elastic step's phases ARE host-separable (compiled local
        # leg, host collective leg, compiled apply), so each gets a real
        # span — the per-phase breakdown the cross-rank timeline merges
        # and the attribution plane decomposes. Names come from the one
        # shared vocabulary (attribution.PHASE_SPAN_NAMES) so bench's
        # phase lane and this step cannot drift.
        with tracing.span(attribution.SPAN_FORWARD_BACKWARD,
                          attribution.CAT_PHASE):
            loss, grads = grad_step(params, batch)
        nprocs = int(os.environ.get("HOROVOD_NUM_PROCESSES", "1") or 1)
        if nprocs > 1 and jax.process_count() == 1:
            # Cross-process leg: fused host allreduce through the native
            # runtime (negotiation + response cache + ring). Failures
            # surface as HorovodInternalError for the elastic retry loop.
            # Skipped when jax.distributed spans the processes — the
            # compiled pmean is already global there.
            #
            # Weighted by each process's LOCAL device count: unequal hosts
            # (4-chip next to 8-chip) must not get equal votes — the cross
            # result is sum(local_mean * n_local) / sum(n_local), the true
            # mean over every device. The loss rides the same fused
            # reduction so every process sees the GLOBAL loss (divergent
            # local losses driving control flow would desynchronize the
            # next collective). Accumulation dtype per leaf: f64 stays
            # f64; f32/bf16/f16 accumulate in f32 and cast back.
            from ..ops.collective_ops import Sum, grouped_allreduce

            with tracing.span(attribution.SPAN_COLLECTIVE,
                              attribution.CAT_COLLECTIVE,
                              args={"plane": "host"}):
                n_local = float(mesh.size)
                leaves, treedef = jax.tree.flatten(grads)
                acc = [np.float64 if np.asarray(l).dtype == np.float64
                       else np.float32 for l in leaves]
                f32_idx = [i for i, a in enumerate(acc) if a == np.float32]
                f64_idx = [i for i, a in enumerate(acc) if a == np.float64]
                # count + loss join the f32 group.
                f32_payload = [np.asarray(leaves[i], np.float32) * n_local
                               for i in f32_idx]
                f32_payload.append(np.asarray([float(loss)], np.float32)
                                   * n_local)
                f32_payload.append(np.asarray([n_local], np.float32))
                red32 = grouped_allreduce(f32_payload, op=Sum)
                total_n = float(np.asarray(red32[-1])[0])
                global_loss = float(np.asarray(red32[-2])[0]) / total_n
                out = list(leaves)
                for i, r in zip(f32_idx, red32[:-2]):
                    out[i] = jnp.asarray(
                        np.asarray(r) / total_n).astype(leaves[i].dtype)
                if f64_idx:
                    red64 = grouped_allreduce(
                        [np.asarray(leaves[i], np.float64) * n_local
                         for i in f64_idx], op=Sum)
                    for i, r in zip(f64_idx, red64):
                        out[i] = jnp.asarray(
                            np.asarray(r) / total_n).astype(leaves[i].dtype)
                grads = jax.tree.unflatten(treedef, out)
                loss = jnp.asarray(global_loss, jnp.float32)
        with tracing.span(attribution.SPAN_OPTIMIZER_UPDATE,
                          attribution.CAT_PHASE):
            params, opt_state = apply_step(params, opt_state, grads)
        return params, opt_state, loss

    return _StallWatchedStep(step, "elastic_train_step")
