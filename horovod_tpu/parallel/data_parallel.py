"""Data-parallel training step factory — Horovod's core capability, compiled.

The reference's training contract (SURVEY.md §4.2): forward/backward runs
per-replica, per-parameter gradients are allreduce-averaged by the
background runtime, then the optimizer applies them. The compiled
equivalent builds the whole step as one SPMD program: batch sharded over the
``hvd`` axis, parameters replicated, gradients averaged by the
DistributedOptimizer *inside* the program (one fused AllReduce HLO per
bucket over ICI), optimizer update replicated. XLA overlaps the gradient
allreduce with remaining backprop where dataflow allows — the compiled
analog of Horovod's comm/compute overlap.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
from jax.sharding import PartitionSpec as P


def make_train_step(
    loss_fn: Callable[..., Any],
    optimizer,
    mesh=None,
    axis_name: str | None = None,
    donate: bool = True,
    loss_is_averaged: bool = True,
    hierarchical: bool | tuple | None = None,
):
    """Build a jitted SPMD train step.

    Args:
      loss_fn: ``loss_fn(params, batch) -> scalar`` (per-shard mean loss).
      optimizer: an optax GradientTransformation — wrap with
        ``hvd.DistributedOptimizer`` for gradient averaging; a bare
        optimizer yields single-replica behavior (grads NOT synced).
      mesh: defaults to the global 1-D 'hvd' mesh from ``init()``.
      axis_name: collective axis (defaults to the global axis).
      loss_is_averaged: if True the reported loss is pmean'd across shards.
      hierarchical: two-level (cross, local) sharding — the consumer of
        ``HOROVOD_HIERARCHICAL_ALLREDUCE`` (reference:
        ``NCCLHierarchicalAllreduce``). None → follow the env flag; True →
        mesh from host topology; a ``(cross, local)`` tuple → explicit
        factors. The DistributedOptimizer then reduces gradients
        reduce-scatter(ICI) → allreduce(DCN) → allgather(ICI).

    Returns:
      ``step(params, opt_state, batch) -> (params, opt_state, loss)``,
      compiled; ``batch`` is sharded along its leading axis, params/opt_state
      replicated.
    """
    import optax

    from .. import basics

    from_env = hierarchical is None
    if from_env:
        cfg = basics._state.config
        hierarchical = bool(cfg and cfg.hierarchical_allreduce)
    if hierarchical and mesh is not None:
        if not from_env:
            raise ValueError(
                "pass either hierarchical=... or mesh=, not both (an "
                "explicit mesh defines its own axes)"
            )
        # Env flag + explicit mesh: the explicit mesh wins, loudly.
        from ..utils.logging import get_logger

        get_logger().warning(
            "HOROVOD_HIERARCHICAL_ALLREDUCE is set but make_train_step got "
            "an explicit mesh; using the explicit mesh (flat reduction)"
        )
        hierarchical = False
    if hierarchical:
        from .hierarchical import HIERARCHICAL_AXES, hierarchical_mesh

        factors = hierarchical if isinstance(hierarchical, tuple) else (None, None)
        mesh = hierarchical_mesh(*factors)
        axis_name = HIERARCHICAL_AXES
    if mesh is None:
        mesh = basics.global_mesh()
    if axis_name is None:
        axis_name = basics.global_axis_name()

    def spmd_step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, new_opt_state = optimizer.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        if loss_is_averaged:
            loss = jax.lax.pmean(loss, axis_name)
        return new_params, new_opt_state, loss

    sharded = jax.shard_map(
        spmd_step,
        mesh=mesh,
        in_specs=(P(), P(), P(axis_name)),
        out_specs=(P(), P(), P()),
        check_vma=False,
    )
    donate_argnums = (0, 1) if donate else ()
    return jax.jit(sharded, donate_argnums=donate_argnums)


def shard_batch(batch, mesh=None, axis_name: str | None = None):
    """Place a host batch on the mesh, sharded along the leading axis."""
    from jax.sharding import NamedSharding

    from .. import basics

    if mesh is None:
        mesh = basics.global_mesh()
    if axis_name is None:
        axis_name = basics.global_axis_name()
    sharding = NamedSharding(mesh, P(axis_name))
    return jax.tree.map(partial(jax.device_put, device=sharding), batch)


def replicate(tree, mesh=None):
    """Place params/opt_state replicated over the mesh.

    Always copies: the result owns fresh buffers, so donating it to a
    jitted step (``donate_argnums``) can never invalidate the caller's
    source arrays. ``jax.device_put`` alone aliases the source into shard 0
    of the replicated array (even with ``may_alias=False``), and a donated
    step then silently deletes the original tree; the explicit ``jnp.copy``
    breaks that alias.
    """
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    from .. import basics

    if mesh is None:
        mesh = basics.global_mesh()
    sharding = NamedSharding(mesh, P())

    def _copy_put(leaf):
        leaf = jnp.copy(leaf) if isinstance(leaf, jax.Array) else leaf
        return jax.device_put(leaf, sharding)

    return jax.tree.map(_copy_put, tree)
