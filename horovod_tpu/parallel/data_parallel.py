"""Data-parallel training step factory — Horovod's core capability, compiled.

The reference's training contract (SURVEY.md §4.2): forward/backward runs
per-replica, per-parameter gradients are allreduce-averaged by the
background runtime, then the optimizer applies them. The compiled
equivalent builds the whole step as one SPMD program: batch sharded over the
``hvd`` axis, parameters replicated, gradients averaged by the
DistributedOptimizer *inside* the program (one fused AllReduce HLO per
bucket over ICI), optimizer update replicated. XLA overlaps the gradient
allreduce with remaining backprop where dataflow allows — the compiled
analog of Horovod's comm/compute overlap.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
from jax.sharding import PartitionSpec as P


class _StallWatchedStep:
    """Default-on stall watch for factory-built train steps.

    The reference's stall inspector watches EVERYTHING submitted,
    unconditionally (``stall_inspector.cc``); requiring users to call
    ``hvd.fetch`` themselves left the exact user the inspector exists
    for — a vanilla training loop hanging inside jit — unwatched. Every
    Kth call (``HOROVOD_STALL_CHECK_STEPS``, default 50; <=0 disables)
    the step's results route through :func:`horovod_tpu.stall.fetch`:
    a local inspector ticket plus, in multi-controller worlds, the
    cross-rank ``stallwatch/<name>`` announcement that NAMES a diverged
    rank. Between check steps the call is a passthrough, so the watch
    costs one pipeline drain per K steps.

    Attribute access delegates to the wrapped callable, so jit surfaces
    (``lower``, ``clear_cache`` — which ``tune_step_fusion`` requires)
    keep working.
    """

    def __init__(self, fn, name_prefix: str):
        from ..utils.env import get_int

        self._fn = fn
        self._prefix = name_prefix
        self._every = get_int("HOROVOD_STALL_CHECK_STEPS", 50)
        self._calls = 0

    @staticmethod
    def _cross_rank_available() -> bool:
        """True when the cross-rank stallwatch can ride a host plane
        this deployment actually has: an already-formed native world, or
        the launcher env contract that makes one formable. NOT cached
        and NEVER forms the world itself — a jax.distributed job that
        deliberately skips the host plane must not have one spun up (or
        crash on a missing rendezvous) as a side effect of the watch."""
        import os

        from . import hierarchical

        return (hierarchical._host_world is not None
                or bool(os.environ.get("HOROVOD_NATIVE_PORT"))
                or bool(os.environ.get("HOROVOD_RENDEZVOUS_ADDR")))

    def _step_number(self, cross_rank: bool) -> int:
        """Watch-step counter. In multi-controller worlds the stallwatch
        wire name must be RANK-IDENTICAL, and a process-local counter
        diverges across elastic re-formations (a survivor has called the
        step N times, a fresh worker 0) — so the counter lives on the
        native world object, which every member recreates together at
        each (re-)formation."""
        from ..process_world import size as _psize

        if cross_rank and _psize() > 1:
            from .hierarchical import _default_native_world

            w = _default_native_world()
            n = getattr(w, "_stepwatch_n", 0) + 1
            w._stepwatch_n = n
            return n
        self._calls += 1
        return self._calls

    @staticmethod
    def _tuning_live() -> bool:
        """True while ANY transparent autotune warmup window is live in
        this process — not just one wrapping our own callable: a co-step
        (built mid-warmup, returned unwrapped) must also defer its drain
        or it biases the first tuner's samples."""
        from ..autotune import _active_tuner

        return bool(_active_tuner and _active_tuner[0]._hvd_tuning)

    def __call__(self, *args, **kwargs):
        if self._every > 0 and not self._tuning_live():
            cross = self._cross_rank_available()
            n = self._step_number(cross)
            if n % self._every == 0:
                import jax

                from ..stall import watch

                # The announcement precedes the DISPATCH: on backends
                # that execute synchronously (CPU) a diverged peer hangs
                # this rank inside the jitted call itself, before any
                # post-hoc fetch could announce.
                with watch(name=f"{self._prefix}.{n}", cross_rank=cross):
                    out = self._fn(*args, **kwargs)
                    out = jax.block_until_ready(out)
                return out
        return self._fn(*args, **kwargs)

    @property
    def _hvd_unwatched(self):
        """The bare step callable — timing loops (tune_step_fusion) use
        this so a watch step's pipeline drain cannot bias a candidate."""
        return self._fn

    def __getattr__(self, item):
        if item == "_fn":  # guard: lookup before __init__ must not recurse
            raise AttributeError(item)
        return getattr(self._fn, item)


def make_train_step(
    loss_fn: Callable[..., Any],
    optimizer,
    mesh=None,
    axis_name: str | None = None,
    donate: bool = True,
    loss_is_averaged: bool = True,
    hierarchical: bool | tuple | None = None,
):
    """Build a jitted SPMD train step.

    Args:
      loss_fn: ``loss_fn(params, batch) -> scalar`` (per-shard mean loss).
      optimizer: an optax GradientTransformation — wrap with
        ``hvd.DistributedOptimizer`` for gradient averaging; a bare
        optimizer yields single-replica behavior (grads NOT synced).
      mesh: defaults to the global 1-D 'hvd' mesh from ``init()``.
      axis_name: collective axis (defaults to the global axis).
      loss_is_averaged: if True the reported loss is pmean'd across shards.
      hierarchical: two-level (cross, local) sharding — the consumer of
        ``HOROVOD_HIERARCHICAL_ALLREDUCE`` (reference:
        ``NCCLHierarchicalAllreduce``). None → follow the env flag; True →
        mesh from host topology; a ``(cross, local)`` tuple → explicit
        factors. The DistributedOptimizer then reduces gradients
        reduce-scatter(ICI) → allreduce(DCN) → allgather(ICI).

    Returns:
      ``step(params, opt_state, batch) -> (params, opt_state, loss)``,
      compiled; ``batch`` is sharded along its leading axis, params/opt_state
      replicated.
    """
    import optax

    from .. import basics

    from_env = hierarchical is None
    if from_env:
        cfg = basics._state.config
        hierarchical = bool(cfg and cfg.hierarchical_allreduce)
    if hierarchical and mesh is not None:
        if not from_env:
            raise ValueError(
                "pass either hierarchical=... or mesh=, not both (an "
                "explicit mesh defines its own axes)"
            )
        # Env flag + explicit mesh: the explicit mesh wins, loudly.
        from ..utils.logging import get_logger

        get_logger().warning(
            "HOROVOD_HIERARCHICAL_ALLREDUCE is set but make_train_step got "
            "an explicit mesh; using the explicit mesh (flat reduction)"
        )
        hierarchical = False
    if hierarchical:
        from .hierarchical import HIERARCHICAL_AXES, hierarchical_mesh

        factors = hierarchical if isinstance(hierarchical, tuple) else (None, None)
        mesh = hierarchical_mesh(*factors)
        axis_name = HIERARCHICAL_AXES
    if mesh is None:
        mesh = basics.global_mesh()
    if axis_name is None:
        axis_name = basics.global_axis_name()

    def spmd_step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, new_opt_state = optimizer.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        if loss_is_averaged:
            loss = jax.lax.pmean(loss, axis_name)
        return new_params, new_opt_state, loss

    sharded = jax.shard_map(
        spmd_step,
        mesh=mesh,
        in_specs=(P(), P(), P(axis_name)),
        out_specs=(P(), P(), P()),
        check_vma=False,
    )
    donate_argnums = (0, 1) if donate else ()
    from ..autotune import maybe_autotune_step

    # Layering: stall watch OUTSIDE the autotuner OUTSIDE the jit — the
    # tuner owns re-tracing (clear_cache) and the watch defers while a
    # tuning window is live so its pipeline drain cannot bias a sample.
    return _StallWatchedStep(
        maybe_autotune_step(jax.jit(sharded, donate_argnums=donate_argnums)),
        "train_step")


def shard_batch(batch, mesh=None, axis_name: str | None = None):
    """Place a host batch on the mesh, sharded along the leading axis."""
    from jax.sharding import NamedSharding

    from .. import basics

    if mesh is None:
        mesh = basics.global_mesh()
    if axis_name is None:
        axis_name = basics.global_axis_name()
    sharding = NamedSharding(mesh, P(axis_name))
    return jax.tree.map(partial(jax.device_put, device=sharding), batch)


def replicate(tree, mesh=None):
    """Place params/opt_state replicated over the mesh.

    Always copies: the result owns fresh buffers, so donating it to a
    jitted step (``donate_argnums``) can never invalidate the caller's
    source arrays. ``jax.device_put`` alone aliases the source into shard 0
    of the replicated array (even with ``may_alias=False``), and a donated
    step then silently deletes the original tree; the explicit ``jnp.copy``
    breaks that alias.
    """
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    from .. import basics

    if mesh is None:
        mesh = basics.global_mesh()
    sharding = NamedSharding(mesh, P())

    def _copy_put(leaf):
        leaf = jnp.copy(leaf) if isinstance(leaf, jax.Array) else leaf
        return jax.device_put(leaf, sharding)

    return jax.tree.map(_copy_put, tree)


def make_elastic_train_step(
    loss_fn: Callable[..., Any],
    optimizer,
    mesh=None,
    axis_name: str | None = None,
):
    """Build a train step for ELASTIC multi-process worlds.

    Elastic workers run without ``jax.distributed`` (its coordination
    client aborts survivors on peer death — see docs/elastic.md), so the
    world is two-level: each process's LOCAL devices form a compiled DP
    mesh, and gradients cross processes on the native host data plane
    (which re-forms in-process after failures). This factory compiles the
    local leg (shard_map + local pmean) and performs the cross leg with a
    fused host allreduce each step — the two-level composition of
    ``host_hierarchical_allreduce`` specialized for training.

    Returns ``step(params, opt_state, batch) -> (params, opt_state,
    loss)`` where ``batch`` is this PROCESS's shard (leading dim divisible
    by the local device count). The world size may change between calls
    (the native world re-forms lazily); gradients always average over the
    processes currently in the world.
    """
    import numpy as np
    import jax.numpy as jnp
    import optax

    from .. import basics

    mesh = mesh or basics.global_mesh()
    axis = axis_name or basics.global_axis_name()

    def local_grads(params, batch):
        def loss_of(p):
            return loss_fn(p, batch)

        loss, grads = jax.value_and_grad(loss_of)(params)
        # Local-device mean: the ICI-compiled leg.
        grads = jax.tree.map(lambda g: jax.lax.pmean(g, axis), grads)
        return jax.lax.pmean(loss, axis), grads

    grad_step = jax.jit(
        jax.shard_map(
            local_grads,
            mesh=mesh,
            in_specs=(P(), P(axis)),
            out_specs=(P(), P()),
            check_vma=False,
        )
    )

    @jax.jit
    def apply_step(params, opt_state, grads):
        updates, new_opt = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_opt

    def step(params, opt_state, batch):
        import os

        loss, grads = grad_step(params, batch)
        nprocs = int(os.environ.get("HOROVOD_NUM_PROCESSES", "1") or 1)
        if nprocs > 1 and jax.process_count() == 1:
            # Cross-process leg: fused host allreduce through the native
            # runtime (negotiation + response cache + ring). Failures
            # surface as HorovodInternalError for the elastic retry loop.
            # Skipped when jax.distributed spans the processes — the
            # compiled pmean is already global there.
            #
            # Weighted by each process's LOCAL device count: unequal hosts
            # (4-chip next to 8-chip) must not get equal votes — the cross
            # result is sum(local_mean * n_local) / sum(n_local), the true
            # mean over every device. The loss rides the same fused
            # reduction so every process sees the GLOBAL loss (divergent
            # local losses driving control flow would desynchronize the
            # next collective). Accumulation dtype per leaf: f64 stays
            # f64; f32/bf16/f16 accumulate in f32 and cast back.
            from ..ops.collective_ops import Sum, grouped_allreduce

            n_local = float(mesh.size)
            leaves, treedef = jax.tree.flatten(grads)
            acc = [np.float64 if np.asarray(l).dtype == np.float64
                   else np.float32 for l in leaves]
            f32_idx = [i for i, a in enumerate(acc) if a == np.float32]
            f64_idx = [i for i, a in enumerate(acc) if a == np.float64]
            # count + loss join the f32 group.
            f32_payload = [np.asarray(leaves[i], np.float32) * n_local
                           for i in f32_idx]
            f32_payload.append(np.asarray([float(loss)], np.float32)
                               * n_local)
            f32_payload.append(np.asarray([n_local], np.float32))
            red32 = grouped_allreduce(f32_payload, op=Sum)
            total_n = float(np.asarray(red32[-1])[0])
            global_loss = float(np.asarray(red32[-2])[0]) / total_n
            out = list(leaves)
            for i, r in zip(f32_idx, red32[:-2]):
                out[i] = jnp.asarray(
                    np.asarray(r) / total_n).astype(leaves[i].dtype)
            if f64_idx:
                red64 = grouped_allreduce(
                    [np.asarray(leaves[i], np.float64) * n_local
                     for i in f64_idx], op=Sum)
                for i, r in zip(f64_idx, red64):
                    out[i] = jnp.asarray(
                        np.asarray(r) / total_n).astype(leaves[i].dtype)
            grads = jax.tree.unflatten(treedef, out)
            loss = jnp.asarray(global_loss, jnp.float32)
        params, opt_state = apply_step(params, opt_state, grads)
        return params, opt_state, loss

    return _StallWatchedStep(step, "elastic_train_step")
