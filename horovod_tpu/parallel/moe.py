"""Expert-parallel MoE dispatch on the device mesh.

The reference added the alltoall collective for MoE-style workloads but
ships no MoE layer (SURVEY.md §3.6: "only the collective primitive
exists"); this module goes one step beyond parity with the TPU-idiomatic
expert-parallel layer built on this framework's collectives: one expert
per device, top-1 routing, capacity-factor dispatch buffers (static
shapes — the GShard/Switch recipe, because XLA cannot do ragged
exchange), and ONE ``lax.all_to_all`` HLO out plus one back, riding ICI.

``examples/jax_moe_expert_parallel.py`` drives this layer end-to-end and
verifies it against a dense oracle; ``__graft_entry__.dryrun_multichip``
exercises the one-HLO dispatch on the virtual multi-chip mesh.

Beyond the demo layer, expert parallelism is a first-class sync path
(:func:`make_expert_parallel_moe_step`): experts shard one-per-rank
across a ``process_sets`` subgroup pattern (data-parallel across the
``world/E`` copies — :func:`process_sets.expert_partition`), and three
performance planes ride the dispatch/combine alltoall wire:

- **quantization** — ``HOROVOD_MOE_COMPRESSION=int8`` sends the token
  payload through the EQuARX blockwise-int8 exchange
  (``ops/quantization.int8_alltoall_rows``; the occupancy mask rides the
  f32 side channel exactly — routing never quantizes);
- **overlap** — the dispatch alltoalls software-pipeline against expert
  FFN compute (``ops/fusion.pipeline_interleave``): segment ``i+1``'s
  exchange is emitted before segment ``i``'s FFN, so XLA's
  latency-hiding scheduler runs them concurrently (jaxpr-asserted in
  tests/test_moe_parallel.py; reverse-mode AD reverses program order, so
  the combine transposes interleave with the backward for free);
- **planner** — the dispatch bucket is priced per-algorithm by the
  comms planner's ``alltoall`` vocabulary (flat vs the two_level
  ICI×DCN staged form, ``ops/comms_planner.two_level_alltoall``), with
  the ``HOROVOD_COMMS_PLANNER``-unset path bit-for-bit identical to the
  flat emission.

``faults.MOE_DISPATCH`` (``moe.dispatch``) is the canonical MoE chaos
injector on this wire; docs/perf.md "Expert parallelism" documents the
knobs and the sync-mode guard table.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


def expert_ffn(w1, w2, x):
    """The per-expert feed-forward: relu(x @ w1) @ w2."""
    return jnp.maximum(x @ w1, 0.0) @ w2


def moe_layer(tokens, gates_w, w1, w2, axis, capacity):
    """One expert-parallel MoE layer, per-device view under shard_map.

    tokens: [T, D] this device's tokens; w1/w2: THIS device's expert.
    Returns [T, D] with each token processed by its routed expert
    (dropped tokens — over capacity — pass through unchanged, the
    standard capacity-factor semantics).
    """
    n = lax.psum(1, axis)
    T, D = tokens.shape
    logits = tokens @ gates_w                      # [T, n]
    expert = jnp.argmax(logits, axis=-1)           # [T]
    gate = jax.nn.softmax(logits, axis=-1)
    gate = jnp.take_along_axis(gate, expert[:, None], axis=1)[:, 0]

    # Position of each token within its expert's send buffer; tokens past
    # `capacity` are dropped (pass through). Static shapes throughout.
    onehot = jax.nn.one_hot(expert, n, dtype=jnp.int32)        # [T, n]
    pos = jnp.cumsum(onehot, axis=0) * onehot                  # 1-based
    pos = jnp.sum(pos, axis=1) - 1                             # [T]
    keep = (pos >= 0) & (pos < capacity)

    # Scatter kept tokens into the [n, capacity, D+1] dispatch buffer —
    # the last channel carries the occupancy mask, so ONE exchange moves
    # payload and mask together.
    send = jnp.zeros((n, capacity, D + 1), tokens.dtype)
    payload = jnp.concatenate(
        [tokens, jnp.ones((T, 1), tokens.dtype)], axis=1)
    send = send.at[expert, jnp.clip(pos, 0, capacity - 1)].add(
        jnp.where(keep[:, None], payload, 0.0))

    # ONE all_to_all out: slot j of my buffer -> device j. Received:
    # [n, capacity, D+1] = every device's tokens routed to MY expert.
    recv = lax.all_to_all(send, axis, split_axis=0, concat_axis=0,
                          tiled=True).reshape(n, capacity, D + 1)
    recv_mask = recv[..., -1] > 0.5
    out = expert_ffn(w1, w2, recv[..., :D].reshape(n * capacity, D))
    out = jnp.where(recv_mask.reshape(-1)[:, None], out, 0.0)
    out = out.reshape(n, capacity, D)

    # all_to_all back: expert results return to their source devices.
    back = lax.all_to_all(out, axis, split_axis=0, concat_axis=0,
                          tiled=True).reshape(n, capacity, D)

    # Gather each token's result from (its expert's row, its position).
    result = back[expert, jnp.clip(pos, 0, capacity - 1)]
    return jnp.where(keep[:, None], gate[:, None] * result, tokens)


def make_moe_step(axis_name: str = "hvd", capacity: int = 4, mesh=None):
    """Build the jitted one-HLO-each-way MoE dispatch over the mesh.

    Takes global ``tokens [n*T, D]``, replicated ``gates_w [D, n]``, and
    expert weights stacked on the device axis (``w1 [n, D, H]``,
    ``w2 [n, H, D]``); returns the routed ``[n*T, D]`` output — the
    one-call user surface mirroring ``make_sp_attention_step``.
    """
    from .. import basics

    mesh = mesh or basics.global_mesh()
    step = jax.shard_map(
        lambda t, g, w1, w2: moe_layer(t, g, w1[0], w2[0], axis_name,
                                       capacity),
        mesh=mesh,
        in_specs=(P(axis_name), P(), P(axis_name), P(axis_name)),
        out_specs=P(axis_name),
        check_vma=False)
    return jax.jit(step)


# ---------------------------------------------------------------------------
# Expert parallelism as a first-class sync path
# ---------------------------------------------------------------------------


def route_to_capacity(tokens, logits, num_experts, capacity):
    """Capacity-factor top-1 routing into fixed per-expert slots — the
    jit-compatible answer to ragged dispatch (the helper the uneven-split
    ``alltoall`` rejection points at).

    ``tokens [T, D]`` + router ``logits [T, num_experts]`` →
    ``send [num_experts, capacity, D+1]`` (last channel = occupancy
    mask, so one exchange moves payload and mask together) plus the
    per-token routing state :func:`combine_from_capacity` needs to bring
    results home: ``expert [T]``, ``pos [T]`` (slot within the expert's
    buffer), ``keep [T]`` (tokens past ``capacity`` are dropped — they
    take the passthrough residual), ``gate [T]`` (softmax prob of the
    chosen expert), and ``counts [num_experts]`` (kept tokens per
    expert — the ``hvd_moe_expert_load`` signal). Static shapes
    throughout; identical math to :func:`moe_layer`'s inline routing.
    """
    T, D = tokens.shape
    expert = jnp.argmax(logits, axis=-1)                       # [T]
    gate = jax.nn.softmax(logits, axis=-1)
    gate = jnp.take_along_axis(gate, expert[:, None], axis=1)[:, 0]
    onehot = jax.nn.one_hot(expert, num_experts, dtype=jnp.int32)
    pos = jnp.cumsum(onehot, axis=0) * onehot                  # 1-based
    pos = jnp.sum(pos, axis=1) - 1                             # [T]
    keep = (pos >= 0) & (pos < capacity)
    send = jnp.zeros((num_experts, capacity, D + 1), tokens.dtype)
    payload = jnp.concatenate(
        [tokens, jnp.ones((T, 1), tokens.dtype)], axis=1)
    send = send.at[expert, jnp.clip(pos, 0, capacity - 1)].add(
        jnp.where(keep[:, None], payload, 0.0))
    counts = jnp.sum(onehot * keep[:, None].astype(jnp.int32), axis=0)
    return send, expert, pos, keep, gate, counts


def combine_from_capacity(back, tokens, expert, pos, keep, gate, capacity):
    """Inverse of :func:`route_to_capacity`: gather each token's expert
    result from ``back [num_experts, capacity, D]`` at (its expert, its
    slot), gate it, and give dropped tokens the passthrough residual."""
    result = back[expert, jnp.clip(pos, 0, capacity - 1)]
    return jnp.where(keep[:, None], gate[:, None] * result, tokens)


def moe_compression(override=None):
    """Resolve the MoE wire compression: ``HOROVOD_MOE_COMPRESSION``
    (or an explicit ``override``) → ``None`` (fp32, exact) | ``"int8"``
    (the EQuARX blockwise exchange). Unknown values raise — a silently
    ignored compression knob is a benchmarking lie."""
    raw = override if override is not None else os.environ.get(
        "HOROVOD_MOE_COMPRESSION", "")
    raw = str(raw).strip().lower()
    if raw in ("", "none", "0", "off"):
        return None
    if raw == "int8":
        return "int8"
    raise ValueError(
        f"HOROVOD_MOE_COMPRESSION={raw!r}: expected 'int8' or unset/"
        f"'none' (fp32)")


def replicate_expert_weights(w_experts, groups):
    """Lay ``w_experts [E, ...]`` out rank-major for the expert-sharded
    in_spec: rank ``groups[g][j]`` gets expert ``j``'s slice, so every
    dispatch group holds one full copy of the expert set. Returns
    ``[world, ...]`` ready for ``P(axis)`` sharding."""
    e = len(groups[0])
    world = sum(len(g) for g in groups)
    if w_experts.shape[0] != e:
        raise ValueError(
            f"w_experts has {w_experts.shape[0]} experts but each "
            f"dispatch group holds {e}")
    rows = [None] * world
    for grp in groups:
        for j, r in enumerate(grp):
            rows[r] = w_experts[j]
    return jnp.stack(rows, axis=0)


def _moe_exchange(axis, groups, plan):
    """The dispatch/combine wire: one callable serving both the f32 and
    the int8 exchanges (and both directions), so every payload rides the
    SAME schedule. Planner plan with a non-flat algorithm → the staged
    two_level form; otherwise the flat tiled alltoall scoped to the
    dispatch groups — which is also the planner-off emission, the
    bit-for-bit inertness contract (``_plan_bucket`` returns None for
    flat plans, so a flat *choice* never reaches here either)."""
    idx_groups = [list(g) for g in groups]

    def _exchange(buf):
        if plan is not None and plan.algorithm == "two_level":
            from ..ops import comms_planner

            return comms_planner.two_level_alltoall(buf, axis,
                                                    plan.islands)
        return lax.all_to_all(buf, axis, split_axis=0, concat_axis=0,
                              tiled=True, axis_index_groups=idx_groups)

    return _exchange


def _dispatch_exchange(send, axis, exchange, compression, salt):
    """One dispatch exchange of a ``[E, c, D+1]`` buffer slice →
    ``(payload [E, c, D], mask [E, c])`` as received. Under int8 the
    payload rides the EQuARX quantized wire and the occupancy mask rides
    the f32 side channel EXACTLY (routing never quantizes)."""
    e, c, dp1 = send.shape
    d = dp1 - 1
    if compression == "int8":
        from ..ops import quantization

        deq, mask = quantization.int8_alltoall_rows(
            send[..., :d].reshape(e, c * d), axis, salt=salt,
            extra=send[..., d], a2a=exchange)
        return deq.reshape(e, c, d), mask
    recv = exchange(send).reshape(e, c, dp1)
    return recv[..., :d], recv[..., d]


def _combine_exchange(out_seg, axis, exchange, compression, salt):
    """One combine exchange of ``[E, c, D]`` expert outputs back to
    their source ranks (no mask — combine addresses every slot)."""
    e, c, d = out_seg.shape
    if compression == "int8":
        from ..ops import quantization

        deq, _ = quantization.int8_alltoall_rows(
            out_seg.reshape(e, c * d), axis, salt=salt, a2a=exchange)
        return deq.reshape(e, c, d)
    return exchange(out_seg).reshape(e, c, d)


def expert_parallel_moe_layer(tokens, gates_w, w1, w2, axis, capacity,
                              groups, *, segments=1, compression=None,
                              plan=None, salt=None):
    """One expert-parallel MoE layer, per-device view under shard_map —
    the first-class sync-path flavor of :func:`moe_layer`.

    ``tokens [T, D]`` this device's tokens; ``w1 [D, H]`` / ``w2 [H,
    D]`` THIS device's expert; ``gates_w [D, E]`` where ``E =
    len(groups[0])`` is the expert-set size (``groups`` from
    :func:`process_sets.expert_partition` — experts shard one-per-rank
    within each dispatch group, data-parallel across groups).

    The dispatch is segmented along the capacity dim and
    software-pipelined (:func:`fusion.pipeline_interleave`): segment
    ``i+1``'s dispatch alltoall is emitted before segment ``i``'s expert
    FFN, so XLA overlaps wire and compute. ``compression="int8"`` rides
    the EQuARX exchange; a planner ``plan`` (from
    ``fusion._plan_bucket("alltoall", ...)``) stages the wire two_level.
    Returns ``(out [T, D], dropped [1] int32, load [1, E] int32)``.
    """
    from ..ops import fusion

    e = len(groups[0])
    send, expert, pos, keep, gate, counts = route_to_capacity(
        tokens, tokens @ gates_w, e, capacity)
    exchange = _moe_exchange(axis, groups, plan)
    segments = max(1, int(segments))
    if capacity % segments:
        raise ValueError(
            f"segments={segments} must divide capacity={capacity}")
    cs = capacity // segments
    d = tokens.shape[1]

    def _launch(i):
        return _dispatch_exchange(send[:, i * cs:(i + 1) * cs, :], axis,
                                  exchange, compression, salt)

    def _consume(i, launched):
        x, mask = launched
        h = expert_ffn(w1, w2, x.reshape(e * cs, d))
        h = jnp.where(mask.reshape(-1)[:, None] > 0.5, h, 0.0)
        return _combine_exchange(h.reshape(e, cs, d), axis, exchange,
                                 compression, salt)

    backs = fusion.pipeline_interleave(segments, _launch, _consume)
    back = backs[0] if segments == 1 else jnp.concatenate(backs, axis=1)
    out = combine_from_capacity(back, tokens, expert, pos, keep, gate,
                                capacity)
    dropped = jnp.sum((~keep).astype(jnp.int32)).reshape(1)
    return out, dropped, counts.reshape(1, e)


def data_parallel_moe_layer(tokens, gates_w, w1_all, w2_all, capacity,
                            *, segments=1):
    """The dense data-parallel baseline: every rank holds ALL experts
    (``w1_all [E, D, H]`` / ``w2_all [E, H, D]`` replicated) and routes
    locally — zero collectives, E× the resident expert bytes. Same
    routing math and segment walk as the expert-parallel layer, so the
    two trajectories are comparable token for token."""
    e = w1_all.shape[0]
    send, expert, pos, keep, gate, counts = route_to_capacity(
        tokens, tokens @ gates_w, e, capacity)
    segments = max(1, int(segments))
    if capacity % segments:
        raise ValueError(
            f"segments={segments} must divide capacity={capacity}")
    cs = capacity // segments
    d = tokens.shape[1]
    backs = []
    for i in range(segments):
        seg = send[:, i * cs:(i + 1) * cs, :]
        h = jax.vmap(expert_ffn)(w1_all, w2_all, seg[..., :d])
        backs.append(jnp.where(seg[..., d:] > 0.5, h, 0.0))
    back = backs[0] if segments == 1 else jnp.concatenate(backs, axis=1)
    out = combine_from_capacity(back, tokens, expert, pos, keep, gate,
                                capacity)
    dropped = jnp.sum((~keep).astype(jnp.int32)).reshape(1)
    return out, dropped, counts.reshape(1, e)


def _wire_bytes(e, capacity, d, compression):
    """Per-rank dispatch-exchange bytes as priced/observed (wire view:
    post-compression). int8 ≈ 1 B/elem payload + the f32 mask and
    per-block scale side channel, approximated at 8 B/slot — a
    documented approximation, not an accounting identity."""
    if compression == "int8":
        return e * capacity * d + 8 * e * capacity
    return e * capacity * (d + 1) * 4


def make_expert_parallel_moe_step(axis_name: str = "hvd",
                                  capacity: int = 4, mesh=None,
                                  expert_set=None, segments=None,
                                  compression=None, salt=None):
    """Build the jitted expert-parallel MoE step — experts sharded
    one-per-rank across ``expert_set`` (a ProcessSet, a rank list, or
    None for the whole world; :func:`process_sets.expert_partition`
    derives the dispatch groups and the data-parallel replica sets),
    capacity-factor dispatch/combine alltoalls over the expert set.

    Takes global ``tokens [n·T, D]``, replicated ``gates_w [D, E]``,
    and expert weights stacked rank-major on the device axis (``w1 [n,
    D, H]``, ``w2 [n, H, D]`` — :func:`replicate_expert_weights` builds
    the ``E < n`` layout); returns the routed ``[n·T, D]`` output, the
    :func:`make_moe_step` surface. Per-rank resident expert bytes are
    1/E of the dense replicated baseline.

    Knobs (all inert-by-default): ``compression`` /
    ``HOROVOD_MOE_COMPRESSION`` (int8 wire), ``segments`` /
    ``HOROVOD_OVERLAP_SEGMENTS`` (dispatch↔compute pipelining, clamped
    to a divisor of ``capacity``), and the comms planner
    (``HOROVOD_COMMS_PLANNER``) which may stage the full-world dispatch
    two_level. With every knob unset the emitted program is bit-for-bit
    the flat fp32 exchange.

    The returned callable carries introspection hooks: ``.jitted`` (the
    underlying jit for ``.lower()``/jaxpr assertions), ``.meta``
    (plan/bytes/algorithm, populated at first trace),
    ``.expert_groups``/``.replica_groups``/``.num_experts``, and
    ``.dispatch_probe(tokens, gates_w)`` — a route+dispatch-only
    program timed under a ``moe.dispatch.<bytes>B.<algo>`` span that
    feeds ``hvd_alltoall_latency_seconds`` and the α-β comms model.
    ``faults.MOE_DISPATCH`` fires here (the canonical MoE chaos
    injector): drop returns the passthrough residual for the whole
    batch, corrupt flips seeded bits in the token payload pre-dispatch.
    """
    import numpy as np

    from .. import basics, comms_model, faults
    from .. import metrics as _metrics
    from .. import process_sets, tracing
    from ..ops import comms_planner, fusion

    mesh = mesh or basics.global_mesh()
    n = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
    groups, replicas = process_sets.expert_partition(expert_set, n)
    e = len(groups[0])
    comp = moe_compression(compression)
    req = int(segments) if segments else fusion.overlap_segments()
    segs = max(dv for dv in range(1, min(req, capacity) + 1)
               if capacity % dv == 0)
    meta = {"plan": None, "nbytes": None, "algorithm": "flat",
            "link_class": "ici", "compression": comp, "segments": segs}

    def _plan_for(d):
        wire = _wire_bytes(e, capacity, d, comp)
        plan = fusion._plan_bucket("alltoall", wire, axis_name, e,
                                   candidates=("flat", "two_level"))
        meta.update(
            plan=plan, nbytes=int(wire),
            algorithm=(plan.algorithm if plan is not None else "flat"),
            link_class=comms_planner._worst_link_class(
                comms_planner._islands_for(e)))
        return plan, wire

    def _traced(tokens, gates_w, w1, w2):
        plan, wire = _plan_for(tokens.shape[1])
        # Trace-time observation: one sample per PROGRAM, the
        # hvd_grad_sync_* idiom — steady-state steps replay the cached
        # executable without re-observing.
        _metrics.MOE_DISPATCH_BYTES.observe(float(wire))
        fn = lambda t, g, a, b: expert_parallel_moe_layer(  # noqa: E731
            t, g, a[0], b[0], axis_name, capacity, groups,
            segments=segs, compression=comp, plan=plan, salt=salt)
        return jax.shard_map(
            fn, mesh=mesh,
            in_specs=(P(axis_name), P(), P(axis_name), P(axis_name)),
            out_specs=(P(axis_name), P(axis_name), P(axis_name)),
            check_vma=False)(tokens, gates_w, w1, w2)

    jitted = jax.jit(_traced)

    def _probe_traced(tokens, gates_w):
        plan, _ = _plan_for(tokens.shape[1])

        def fn(t, g):
            send, *_rest = route_to_capacity(t, t @ g, e, capacity)
            payload, mask = _dispatch_exchange(
                send, axis_name, _moe_exchange(axis_name, groups, plan),
                comp, salt)
            return payload * mask[..., None]

        return jax.shard_map(
            fn, mesh=mesh, in_specs=(P(axis_name), P()),
            out_specs=P(axis_name), check_vma=False)(tokens, gates_w)

    probe_jitted = jax.jit(_probe_traced)

    def dispatch_probe(tokens, gates_w):
        """Route + dispatch only (no FFN, no combine), timed — the
        quantized-vs-fp32 wire A/B and the latency-histogram feed."""
        import time

        name = (f"moe.dispatch.{meta['nbytes'] or 0}B"
                f".{meta['algorithm']}")
        t0 = time.perf_counter()
        with tracing.span(name, "collective",
                          args={"bytes": meta["nbytes"],
                                "op": "alltoall",
                                "algorithm": meta["algorithm"],
                                "link_class": meta["link_class"]}):
            out = probe_jitted(tokens, gates_w)
            jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        _metrics.ALLTOALL_LATENCY.observe(dt,
                                          algorithm=meta["algorithm"])
        if meta["nbytes"]:
            comms_model.observe("alltoall", meta["algorithm"],
                                meta["link_class"], meta["nbytes"], dt)
        return out

    def step(tokens, gates_w, w1, w2):
        spec = (faults.active().get(faults.MOE_DISPATCH)
                if faults.armed(faults.MOE_DISPATCH) else None)
        if spec is not None and spec.mode == "corrupt":
            blob = np.ascontiguousarray(np.asarray(tokens,
                                                   dtype=np.float32))
            flipped = faults.corrupt_payload(faults.MOE_DISPATCH,
                                             blob.tobytes())
            tokens = jnp.asarray(
                np.frombuffer(flipped, np.float32).reshape(blob.shape))
        elif spec is not None and faults.fire(faults.MOE_DISPATCH):
            # Dropped dispatch: the exchange never happens, every token
            # takes the capacity-overflow passthrough residual.
            return jnp.asarray(tokens)
        out, dropped, load = jitted(tokens, gates_w, w1, w2)
        # Zero-duration start markers on both wire directions — the
        # compute_skew attribution's cross-rank lateness food.
        name = f"{meta['nbytes'] or 0}B.{meta['algorithm']}"
        tracer = tracing.get_tracer()
        tracer.record_dispatch(f"moe.dispatch.{name}", cat="collective")
        tracer.record_dispatch(f"moe.combine.{name}", cat="collective")
        dropped = np.asarray(dropped)
        if dropped.sum():
            _metrics.MOE_TOKENS_DROPPED.inc(float(dropped.sum()))
        loads = np.asarray(load).sum(axis=0)
        for j in range(e):
            _metrics.MOE_EXPERT_LOAD.set(float(loads[j]),
                                         expert=str(j))
        return out

    step.jitted = jitted
    step.dispatch_probe = dispatch_probe
    step.expert_groups = groups
    step.replica_groups = replicas
    step.num_experts = e
    step.meta = meta
    return step


def make_data_parallel_moe_step(axis_name: str = "hvd",
                                capacity: int = 4, mesh=None,
                                segments=None):
    """Build the dense data-parallel MoE baseline step: all experts
    replicated on every rank (``w1_all [E, D, H]`` / ``w2_all [E, H,
    D]`` unsharded in_specs), local routing, zero collectives — the
    loss-trajectory oracle and the resident-bytes/throughput comparator
    for :func:`make_expert_parallel_moe_step`. Same wrapper-side
    metrics (dropped tokens, expert load) so the host-cost profile is
    symmetric in the bench A/B."""
    import numpy as np

    from .. import basics
    from .. import metrics as _metrics
    from ..ops import fusion

    mesh = mesh or basics.global_mesh()
    req = int(segments) if segments else fusion.overlap_segments()
    segs = max(dv for dv in range(1, min(req, capacity) + 1)
               if capacity % dv == 0)

    jitted = jax.jit(jax.shard_map(
        lambda t, g, a, b: data_parallel_moe_layer(t, g, a, b, capacity,
                                                   segments=segs),
        mesh=mesh,
        in_specs=(P(axis_name), P(), P(), P()),
        out_specs=(P(axis_name), P(axis_name), P(axis_name)),
        check_vma=False))

    def step(tokens, gates_w, w1_all, w2_all):
        out, dropped, load = jitted(tokens, gates_w, w1_all, w2_all)
        dropped = np.asarray(dropped)
        if dropped.sum():
            _metrics.MOE_TOKENS_DROPPED.inc(float(dropped.sum()))
        loads = np.asarray(load).sum(axis=0)
        for j in range(loads.shape[0]):
            _metrics.MOE_EXPERT_LOAD.set(float(loads[j]),
                                         expert=str(j))
        return out

    step.jitted = jitted
    step.num_experts = None  # derived from gates_w at call time
    return step
