"""Expert-parallel MoE dispatch on the device mesh.

The reference added the alltoall collective for MoE-style workloads but
ships no MoE layer (SURVEY.md §3.6: "only the collective primitive
exists"); this module goes one step beyond parity with the TPU-idiomatic
expert-parallel layer built on this framework's collectives: one expert
per device, top-1 routing, capacity-factor dispatch buffers (static
shapes — the GShard/Switch recipe, because XLA cannot do ragged
exchange), and ONE ``lax.all_to_all`` HLO out plus one back, riding ICI.

``examples/jax_moe_expert_parallel.py`` drives this layer end-to-end and
verifies it against a dense oracle; ``__graft_entry__.dryrun_multichip``
exercises the one-HLO dispatch on the virtual multi-chip mesh.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


def expert_ffn(w1, w2, x):
    """The per-expert feed-forward: relu(x @ w1) @ w2."""
    return jnp.maximum(x @ w1, 0.0) @ w2


def moe_layer(tokens, gates_w, w1, w2, axis, capacity):
    """One expert-parallel MoE layer, per-device view under shard_map.

    tokens: [T, D] this device's tokens; w1/w2: THIS device's expert.
    Returns [T, D] with each token processed by its routed expert
    (dropped tokens — over capacity — pass through unchanged, the
    standard capacity-factor semantics).
    """
    n = lax.psum(1, axis)
    T, D = tokens.shape
    logits = tokens @ gates_w                      # [T, n]
    expert = jnp.argmax(logits, axis=-1)           # [T]
    gate = jax.nn.softmax(logits, axis=-1)
    gate = jnp.take_along_axis(gate, expert[:, None], axis=1)[:, 0]

    # Position of each token within its expert's send buffer; tokens past
    # `capacity` are dropped (pass through). Static shapes throughout.
    onehot = jax.nn.one_hot(expert, n, dtype=jnp.int32)        # [T, n]
    pos = jnp.cumsum(onehot, axis=0) * onehot                  # 1-based
    pos = jnp.sum(pos, axis=1) - 1                             # [T]
    keep = (pos >= 0) & (pos < capacity)

    # Scatter kept tokens into the [n, capacity, D+1] dispatch buffer —
    # the last channel carries the occupancy mask, so ONE exchange moves
    # payload and mask together.
    send = jnp.zeros((n, capacity, D + 1), tokens.dtype)
    payload = jnp.concatenate(
        [tokens, jnp.ones((T, 1), tokens.dtype)], axis=1)
    send = send.at[expert, jnp.clip(pos, 0, capacity - 1)].add(
        jnp.where(keep[:, None], payload, 0.0))

    # ONE all_to_all out: slot j of my buffer -> device j. Received:
    # [n, capacity, D+1] = every device's tokens routed to MY expert.
    recv = lax.all_to_all(send, axis, split_axis=0, concat_axis=0,
                          tiled=True).reshape(n, capacity, D + 1)
    recv_mask = recv[..., -1] > 0.5
    out = expert_ffn(w1, w2, recv[..., :D].reshape(n * capacity, D))
    out = jnp.where(recv_mask.reshape(-1)[:, None], out, 0.0)
    out = out.reshape(n, capacity, D)

    # all_to_all back: expert results return to their source devices.
    back = lax.all_to_all(out, axis, split_axis=0, concat_axis=0,
                          tiled=True).reshape(n, capacity, D)

    # Gather each token's result from (its expert's row, its position).
    result = back[expert, jnp.clip(pos, 0, capacity - 1)]
    return jnp.where(keep[:, None], gate[:, None] * result, tokens)


def make_moe_step(axis_name: str = "hvd", capacity: int = 4, mesh=None):
    """Build the jitted one-HLO-each-way MoE dispatch over the mesh.

    Takes global ``tokens [n*T, D]``, replicated ``gates_w [D, n]``, and
    expert weights stacked on the device axis (``w1 [n, D, H]``,
    ``w2 [n, H, D]``); returns the routed ``[n*T, D]`` output — the
    one-call user surface mirroring ``make_sp_attention_step``.
    """
    from .. import basics

    mesh = mesh or basics.global_mesh()
    step = jax.shard_map(
        lambda t, g, w1, w2: moe_layer(t, g, w1[0], w2[0], axis_name,
                                       capacity),
        mesh=mesh,
        in_specs=(P(axis_name), P(), P(axis_name), P(axis_name)),
        out_specs=P(axis_name),
        check_vma=False)
    return jax.jit(step)
