"""Hierarchical (two-level) allreduce: the ICI+DCN composition.

Reference role: ``NCCLHierarchicalAllreduce``
(``horovod/common/ops/nccl_operations.cc``) — NCCL reduce-scatter within a
node, MPI allreduce across nodes on host, NCCL allgather within the node,
enabled by ``HOROVOD_HIERARCHICAL_ALLREDUCE``. The TPU mapping (SURVEY.md
§6): the fast "intra" leg is the ICI mesh inside a slice, the slow "cross"
leg is DCN between hosts/slices.

Two forms, mirroring the framework's two regimes:

- **Traced**: over a 2-D ``(cross, local)`` mesh —
  ``psum_scatter`` over the local axis → ``psum`` over the cross axis →
  ``all_gather`` over the local axis. Each device moves 1/local_size of
  the payload across the slow axis instead of the whole tensor, which is
  exactly the reference's bandwidth argument for the NCCL+MPI composition.
  Build the mesh with :func:`hierarchical_mesh`; inside a
  ``shard_map`` over both axes every collective op accepts the
  ``(cross, local)`` axis tuple transparently. Rank-order caveat: the
  hierarchical mesh's rank order is host-grouped (cross-major), which on
  interleaved ICI topologies differs from the canonical flat rank order —
  reductions are unaffected, but rank-sensitive ops (allgather
  concatenation, broadcast root, alltoall blocks, ``hvd.rank()``) follow
  the host-grouped order inside a hierarchical step.

- **Host/eager**: each controller process reduces its local shards with
  XLA, then the **cross-process leg runs through the native C++ runtime**
  (``horovod_tpu.runtime.NativeWorld`` — negotiation, fusion, response
  cache, ring TCP), making libhvdrt the DCN leg the way MPI was for the
  reference. See :func:`host_hierarchical_allreduce`.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh

CROSS_AXIS = "hvd_cross"
LOCAL_AXIS = "hvd_local"
HIERARCHICAL_AXES = (CROSS_AXIS, LOCAL_AXIS)


def hierarchical_mesh(cross_size: int | None = None,
                      local_size: int | None = None) -> Mesh:
    """A 2-D ``(cross, local)`` mesh over the world's devices in ICI order.

    Defaults to the topology's host structure (``cross_size`` hosts ×
    ``local_size`` chips per host) so the local axis rides ICI and the
    cross axis spans DCN. The canonical ICI rank order does NOT group a
    host's chips contiguously (``topology.py``), so rows are built by
    grouping devices by host, never by reshaping the flat order — a row
    that mixed hosts would put the full-payload reduce-scatter/allgather
    legs on DCN and invert the optimization. Explicit factors exist for
    tests and for splits that intentionally differ from host boundaries
    (those reshape the canonical order and must multiply to the world
    size).
    """
    from .. import basics

    topo = basics._state.require_init().topology
    if cross_size is None and local_size is None:
        if topo.size == topo.cross_size * topo.local_size:
            # Host-grouped rows: row i = host i's chips in canonical order.
            by_host: dict[int, list] = {}
            for d in topo.devices:
                by_host.setdefault(d.process_index, []).append(d)
            rows = [by_host[p] for p in sorted(by_host)]
            if len({len(r) for r in rows}) != 1:
                rows = [[d] for d in topo.devices]  # ragged: flat cross
            return Mesh(np.array(rows), HIERARCHICAL_AXES)
        # Heterogeneous hosts: fall back to a flat cross axis.
        cross_size, local_size = topo.size, 1
    elif cross_size is None:
        cross_size = topo.size // local_size
    elif local_size is None:
        local_size = topo.size // cross_size
    if cross_size * local_size != topo.size:
        raise ValueError(
            f"hierarchical mesh {cross_size}x{local_size} does not cover "
            f"the {topo.size}-device world"
        )
    devices = np.array(topo.devices).reshape(cross_size, local_size)
    return Mesh(devices, HIERARCHICAL_AXES)


def hierarchical_allreduce(
    x,
    op: str = "average",
    cross_axis: str = CROSS_AXIS,
    local_axis: str = LOCAL_AXIS,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
):
    """Traced two-level allreduce (call under shard_map over both axes).

    Sum/Average take the bandwidth-optimal reduce-scatter → cross-allreduce
    → allgather composition; Min/Max/Product reduce over both axes directly
    (already latency-optimal as one HLO); Adasum mirrors the reference's
    GPU hierarchy — average within the fast domain, Adasum across the slow
    one (``adasum_gpu_operations.cc`` semantics).
    """
    from ..ops.collective_ops import (
        Adasum, Average, Max, Min, Product, Sum, _VALID_OPS,
    )

    if op in (Min, Max, Product):
        from ..ops.collective_ops import _allreduce_traced

        return _allreduce_traced(
            x, op, (cross_axis, local_axis), prescale_factor, postscale_factor
        )
    if op == Adasum:
        from ..ops.adasum import adasum_reduce

        if prescale_factor != 1.0:
            x = x * jnp.asarray(prescale_factor, x.dtype)
        out = lax.pmean(x, local_axis)
        out = adasum_reduce(out, cross_axis)
        if postscale_factor != 1.0:
            out = out * jnp.asarray(postscale_factor, out.dtype)
        return out
    if op not in (Sum, Average):
        raise ValueError(f"unknown reduce op {op!r}; expected {_VALID_OPS}")

    if prescale_factor != 1.0:
        x = x * jnp.asarray(prescale_factor, x.dtype)
    local_n = lax.psum(1, local_axis)

    shape = x.shape
    flat = x.reshape(-1)
    pad = (-flat.size) % local_n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    # Each device keeps 1/local_n of the payload for the slow-axis hop.
    # The three legs are named so a profile shows which leg of which
    # segment/bucket overlaps which slice of backward compute — the
    # overlap scheduler issues this composition once PER SEGMENT, and
    # the legs keep their relative order within each segment while
    # different segments' legs interleave freely by dataflow.
    from ..profiler import annotate_collective

    with annotate_collective("hier.reduce_scatter_local"):
        shard = lax.psum_scatter(
            flat, local_axis, scatter_dimension=0, tiled=True)
    with annotate_collective("hier.allreduce_cross"):
        shard = lax.psum(shard, cross_axis)
    with annotate_collective("hier.allgather_local"):
        full = lax.all_gather(shard, local_axis, axis=0, tiled=True)
    if pad:
        full = full[: flat.size - pad]
    out = full.reshape(shape)

    scale = postscale_factor
    if op == Average:
        scale = scale / (local_n * lax.psum(1, cross_axis))
    if scale != 1.0:
        out = out * jnp.asarray(scale, out.dtype)
    return out


# ---------------------------------------------------------------------------
# Host/eager form: XLA local leg + native-runtime (libhvdrt) cross leg.
# ---------------------------------------------------------------------------

_host_world = None
_host_world_gen = None  # HOROVOD_WORLD_VERSION the cached world was built in


def _default_native_world():
    """Process-wide NativeWorld from the launcher's env contract.

    The cache is liveness-checked, not just memoized: the native runtime
    state is process-global, so ANY shutdown path (elastic re-init, test
    teardown, another NativeWorld instance) can kill it — in which case the
    next call re-establishes a live world instead of handing back a dead
    one forever.

    In an elastic world, a cached world found dead within the SAME
    generation it was built for is a peer-departure signal (a drained or
    crashed rank's negotiated shutdown), not a rebuild opportunity:
    re-forming from the still-stale env would re-join the dying epoch's
    endpoints (connect-timeout against a drained peer's dead coordinator).
    That case raises ``HorovodInternalError`` so the elastic recovery
    ladder re-rendezvouses with fresh env; once re-init has advanced
    ``HOROVOD_WORLD_VERSION``, rebuilding is legitimate again.
    """
    global _host_world, _host_world_gen
    if _host_world is not None and not _host_world.alive:
        # Initialized-but-dead (fatal control-plane error) or shut down:
        # tear down so re-init can form a fresh world (elastic recovery).
        try:
            _host_world.shutdown()
        except Exception:
            pass
        _host_world = None
        import os

        from ..runner.elastic.worker import elastic_enabled

        env_gen = os.environ.get("HOROVOD_WORLD_VERSION")
        if (elastic_enabled() and env_gen is not None
                and env_gen == _host_world_gen):
            from ..exceptions import HorovodInternalError

            raise HorovodInternalError(
                f"native host world died within generation {env_gen} "
                "(peer drained or crashed); entering elastic recovery"
            )
    if _host_world is None:
        import os

        from ..runner.elastic.worker import elastic_enabled
        from ..runtime import NativeRuntimeError, NativeWorld
        from ..utils.env import get_float

        nprocs = int(os.environ.get("HOROVOD_NUM_PROCESSES", "1") or 1)
        proc_id = int(os.environ.get("HOROVOD_PROCESS_ID", "0") or 0)
        addr = os.environ.get("HOROVOD_COORDINATOR_ADDR", "127.0.0.1")
        addr = addr.rsplit(":", 1)[0]
        port = int(os.environ.get("HOROVOD_NATIVE_PORT", "0") or 0)
        try:
            if nprocs > 1:
                addr, port = _exchange_native_endpoint(proc_id, port)
            if nprocs > 1 and not port:
                raise RuntimeError(
                    "host_hierarchical_allreduce needs HOROVOD_NATIVE_PORT "
                    "(the native runtime's coordinator port) in a "
                    "multi-process world"
                )
            _host_world = NativeWorld(
                proc_id, nprocs, addr, port or 29500,
                timeout_s=get_float("HOROVOD_NATIVE_INIT_TIMEOUT", 30.0))
        except (NativeRuntimeError, TimeoutError) as e:
            if not elastic_enabled():
                raise
            # An elastic epoch can die between this worker's assignment
            # fetch and its native join (a drained peer's coordinator is
            # gone, the endpoint never gets published, ...). That is
            # world churn, not a fatal runtime fault: surface it as the
            # recovery exception so the elastic ladder re-rendezvouses
            # with fresh state instead of the process dying rc=1.
            from ..exceptions import HorovodInternalError

            raise HorovodInternalError(
                f"native host world join failed ({e}); entering elastic "
                "recovery") from e
        _host_world_gen = os.environ.get("HOROVOD_WORLD_VERSION")
        _register_atexit_shutdown()
    return _host_world


_atexit_registered = False


def _register_atexit_shutdown() -> None:
    """Shut the native world down gracefully at interpreter exit: the C
    runtime's shutdown is NEGOTIATED (all ranks agree before the loop
    exits), so an early-exiting process drains cleanly instead of peers
    logging 'Connection reset by peer' at teardown."""
    global _atexit_registered
    if _atexit_registered:
        return
    _atexit_registered = True
    import atexit

    def _shutdown():
        w = _host_world
        if w is not None and w.alive:
            try:
                w.shutdown()
            except Exception:
                pass

    atexit.register(_shutdown)


def _exchange_native_endpoint(proc_id: int, fallback_port: int):
    """Rank 0 picks the native coordinator endpoint ON ITS OWN HOST and
    publishes it via the rendezvous KV; peers poll it.

    The launcher's HOROVOD_NATIVE_PORT is probed free on the LAUNCHER
    host — rank 0 may live elsewhere (Ray/Spark placement, remote -H
    hosts), the same cross-machine TOCTOU the coordinator port solves in
    ``basics._exchange_coordinator_port``. No KV (manual launch) → trust
    the env as given.
    """
    import os
    import time

    kv_addr = os.environ.get("HOROVOD_RENDEZVOUS_ADDR", "")
    kv_port = int(os.environ.get("HOROVOD_RENDEZVOUS_PORT", "-1") or -1)
    coord_host = os.environ.get(
        "HOROVOD_COORDINATOR_ADDR", "127.0.0.1").rsplit(":", 1)[0]
    if not kv_addr or kv_port < 0:
        return coord_host, fallback_port
    from ..runner.http.kv_server import KVClient, env_generation
    from ..runner.network import free_port, routable_addr

    version = os.environ.get("HOROVOD_WORLD_VERSION", "static")
    scope = f"native/{version}"
    # Generation-fenced: a zombie rank 0 must not republish a stale
    # native-coordinator endpoint into the re-formed world's rendezvous.
    kv = KVClient(kv_addr, kv_port, generation_fn=env_generation)
    if proc_id == 0:
        host = routable_addr()
        port = free_port()  # free on rank 0's host, where the bind happens
        kv.put(scope, "addr", f"{host}:{port}".encode())
        return host, port
    deadline = time.time() + 60.0
    while time.time() < deadline:
        val = kv.get(scope, "addr")
        if val is not None:
            host, port = val.decode().rsplit(":", 1)
            return host, int(port)
        time.sleep(0.05)
    raise TimeoutError(
        f"native endpoint not published to rendezvous KV scope {scope!r}"
    )


def host_hierarchical_allreduce(
    stacked,
    name: str,
    op: str = "average",
    world=None,
):
    """Eager hierarchical allreduce across controller processes.

    ``stacked`` follows the eager stacked-rank convention for THIS
    process's local shards: shape ``(local_n, *t)``. The local leg reduces
    those shards with XLA; the cross leg allreduces the partial through the
    native C++ runtime (negotiation + response cache + ring TCP over
    DCN — the reference's MPI role); the result is the full reduction over
    all ``local_n × n_processes`` logical ranks, returned stacked.
    """
    from ..ops.collective_ops import Average, Sum

    if op not in (Sum, Average):
        raise ValueError(f"host hierarchical allreduce supports sum/average, got {op!r}")
    w = world if world is not None else _default_native_world()
    x = jnp.asarray(stacked)
    if x.ndim < 1:
        raise ValueError("expected stacked-rank input (local_n, *shape)")
    local_n = x.shape[0]
    local_sum = jnp.sum(x, axis=0)  # ICI leg (XLA)
    cross = np.asarray(
        w.allreduce(np.asarray(local_sum), name, op="sum")
    )  # DCN leg (libhvdrt)
    if op == Average:
        # Processes may carry different shard counts; the divisor is the
        # true logical rank count, agreed through the same runtime.
        total = float(
            np.asarray(
                w.allreduce(
                    np.asarray([local_n], np.float32), name + "/count",
                    op="sum",
                )
            )[0]
        )
        cross = cross / total
    return jnp.broadcast_to(cross, x.shape)
