"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

No reference counterpart — the reference is data-parallel only (SURVEY.md
§3.6: SP/CP "absent"; the `alltoall`/`allgather` primitives it ships are
exactly what a sequence-parallel scheme needs). This module is the
TPU-native long-context subsystem the north star makes first-class:

- **Ring attention** (``ring_attention``): sequence sharded over a mesh
  axis; K/V blocks rotate around the ring via ``lax.ppermute`` — on TPU
  these are neighbor transfers over ICI torus links, overlapping with each
  step's blockwise-attention compute. Memory per chip stays O(S/N); total
  sequence length scales linearly with the ring size.
- **Ulysses** (``ulysses_attention``): ``lax.all_to_all`` re-shards
  sequence↔heads so each chip runs *full-sequence* attention on H/N heads;
  cheaper collectives for moderate S, requires H divisible by the axis.

Both run inside ``shard_map`` over a 1-D sub-axis (by default the global
``'hvd'`` axis, composable with DP via process sets / mesh reshapes) and use
the same online-softmax math as ``horovod_tpu.ops.attention`` with fp32
accumulators, so either scheme matches the dense oracle to bf16 tolerance.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.attention import (
    LSE_MASKED,
    NEG_INF,
    _attend_block,
    _finalize,
    blockwise_attention_reference,
    flash_attention,
    flash_attention_lse,
)


def _local_attend(q, k, v, m, l, o, scale, causal, q_offset, k_offset):
    """Fold one K/V shard into the running (m, l, o) for all [B, H] rows.

    q: [B, H, Sq, D]; k, v: [B, H, Sk, D]; m, l: [B, H, Sq]; o fp32 like q.
    """
    mask = None
    if causal:
        Sq, Sk = q.shape[2], k.shape[2]
        qpos = q_offset + jnp.arange(Sq)
        kpos = k_offset + jnp.arange(Sk)
        mask = qpos[:, None] >= kpos[None, :]

    def per_head(qh, kh, vh, mh, lh, oh):
        return _attend_block(qh, kh, vh, mh, lh, oh, mask, scale)

    return jax.vmap(jax.vmap(per_head))(q, k, v, m, l, o)


def ring_attention(q, k, v, axis_name: str = "hvd", causal: bool = False,
                   use_flash: bool = False, interpret: bool = False):
    """Ring (context-parallel) attention inside shard_map.

    Args: q, k, v ``[B, H, S_local, D]`` — the sequence dimension is the
    shard of a global sequence ``S_local * axis_size``, shard r holding
    positions ``[r*S_local, (r+1)*S_local)``. Returns the local output
    shard ``[B, H, S_local, D]``.

    Step t computes attention of the local Q block against the K/V block
    that originated on rank ``(idx - t) % n``, while ppermute-ing K/V one
    hop forward for step t+1 — compute and ICI transfer overlap (XLA
    schedules the independent ops concurrently).

    ``use_flash=True`` runs each step through the Pallas flash kernel and
    merges the per-shard partials by logsumexp — the MXU-tiled hot path
    for long sequences (trainable: the kernel has a custom_vjp backward).
    """
    n = lax.psum(1, axis_name)  # mesh axis size: a static Python int
    idx = lax.axis_index(axis_name)
    B, H, S, D = q.shape
    scale = 1.0 / (D ** 0.5)
    perm = [(i, (i + 1) % n) for i in range(n)]

    if use_flash:
        return _ring_attention_flash(q, k, v, n, idx, perm, axis_name,
                                     causal, interpret)

    q32 = q.astype(jnp.float32)
    m = jnp.full((B, H, S), NEG_INF, jnp.float32)
    l = jnp.zeros((B, H, S), jnp.float32)
    o = jnp.zeros((B, H, S, D), jnp.float32)

    # Static unroll over the (static) ring size: rotate for the NEXT step
    # before computing, so the ICI transfer overlaps the compute — and skip
    # the rotation on the last step (its result would be discarded, but XLA
    # cannot DCE a collective).
    kt, vt = k, v
    for t in range(n):
        src = (idx - t) % n  # which rank's K/V block we currently hold
        if t < n - 1:
            k_next = lax.ppermute(kt, axis_name, perm)
            v_next = lax.ppermute(vt, axis_name, perm)
        m, l, o = _local_attend(
            q32, kt, vt, m, l, o, scale, causal,
            q_offset=idx * S, k_offset=src * S,
        )
        if t < n - 1:
            kt, vt = k_next, v_next

    out = jax.vmap(jax.vmap(_finalize))(l, o)
    return out.astype(q.dtype)


def _ring_attention_flash(q, k, v, n, idx, perm, axis_name, causal,
                          interpret):
    """Flash-kernel ring: per-step (out_t, lse_t) from the Pallas kernel,
    merged online by logsumexp.

    Causality without traced kernel offsets (Pallas mask offsets are
    static): step t==0 is the diagonal block (causal kernel, Sq==Sk);
    later steps are block-wise all-or-nothing — the K/V shard originated
    on ``src = (idx - t) % n``, entirely in the past (visible, non-causal
    kernel) or entirely in the future (contribution erased by setting its
    lse to -inf, a traced select on the merge weights).
    """
    B, H, S, D = q.shape
    m_run = jnp.full((B, H, S), NEG_INF, jnp.float32)
    l_run = jnp.zeros((B, H, S), jnp.float32)
    acc = jnp.zeros((B, H, S, D), jnp.float32)

    kt, vt = k, v
    for t in range(n):
        src = (idx - t) % n
        if t < n - 1:
            k_next = lax.ppermute(kt, axis_name, perm)
            v_next = lax.ppermute(vt, axis_name, perm)
        o_t, lse_t = flash_attention_lse(
            q, kt, vt, causal=(causal and t == 0), interpret=interpret)
        # Fully-masked-row sentinel (+BIG) means "no keys": merge as -inf.
        lse_t = jnp.where(lse_t >= LSE_MASKED * 0.5, NEG_INF, lse_t)
        if causal and t > 0:
            visible = (src < idx)  # whole-block causality, traced scalar
            lse_t = jnp.where(visible, lse_t, NEG_INF)
        # Online logsumexp merge of the partial attention.
        m_new = jnp.maximum(m_run, lse_t)
        # Clamp so untouched rows (both -inf) stay a no-op.
        corr = jnp.exp(jnp.minimum(m_run - m_new, 0.0))
        w = jnp.exp(jnp.minimum(lse_t - m_new, 0.0))
        w = jnp.where(lse_t <= NEG_INF * 0.5, 0.0, w)
        corr = jnp.where(m_run <= NEG_INF * 0.5, 0.0, corr)
        acc = acc * corr[..., None] + w[..., None] * o_t.astype(jnp.float32)
        l_run = l_run * corr + w
        m_run = m_new
        if t < n - 1:
            kt, vt = k_next, v_next

    safe = jnp.where(l_run == 0.0, 1.0, l_run)
    return (acc / safe[..., None]).astype(q.dtype)


def ulysses_attention(q, k, v, axis_name: str = "hvd", causal: bool = False,
                      use_flash: bool = False, interpret: bool = False):
    """Ulysses-style sequence parallelism inside shard_map.

    Args: q, k, v ``[B, H, S_local, D]`` with ``H`` divisible by the axis
    size. all_to_all re-shards to ``[B, H/n, S_global, D]``, runs full
    attention per head group (optionally the Pallas flash kernel), and
    re-shards back. Returns ``[B, H, S_local, D]``.
    """
    n = lax.psum(1, axis_name)
    B, H, S, D = q.shape

    def to_seq(x):  # [B, H, S/n, D] -> [B, H/n, S, D]
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    def to_heads(x):  # [B, H/n, S, D] -> [B, H, S/n, D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    qs, ks, vs = to_seq(q), to_seq(k), to_seq(v)
    if use_flash:
        out = flash_attention(qs, ks, vs, causal=causal, interpret=interpret)
    else:
        out = blockwise_attention_reference(qs, ks, vs, causal=causal)
    return to_heads(out)


def shard_sequence(tree, axis: int = 2, process_set=None):
    """Split arrays along the sequence axis into the stacked-rank layout
    expected by shard_map over the set's mesh (helper for input pipelines)."""
    from ..process_sets import global_process_set

    ps = process_set if process_set is not None else global_process_set
    n = ps.size()

    def split(x):
        if x.shape[axis] % n:
            raise ValueError(
                f"sequence length {x.shape[axis]} not divisible by "
                f"sequence-parallel size {n}"
            )
        return jnp.stack(jnp.split(x, n, axis=axis))

    return jax.tree.map(split, tree)


def make_sp_attention_step(axis_name: str = "hvd", scheme: str = "ring",
                           causal: bool = False, mesh=None):
    """Build a jitted global-sequence attention fn over the mesh.

    Takes global [B, H, S, D] arrays, shards S over the axis, runs the
    chosen scheme, returns the global output — the one-call user surface.
    """
    from jax.sharding import PartitionSpec as P

    from .. import basics

    mesh = mesh or basics.global_mesh()
    if scheme == "ring":
        inner = functools.partial(ring_attention, axis_name=axis_name,
                                  causal=causal)
    elif scheme == "ring-flash":
        inner = functools.partial(
            ring_attention, axis_name=axis_name, causal=causal,
            use_flash=True,
            interpret=jax.default_backend() != "tpu",
        )
    elif scheme == "ulysses":
        inner = functools.partial(ulysses_attention, axis_name=axis_name,
                                  causal=causal)
    else:
        raise ValueError(
            f"unknown scheme {scheme!r}; use 'ring', 'ring-flash' or "
            "'ulysses'")

    spec = P(None, None, axis_name, None)
    sharded = jax.shard_map(
        inner, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    return jax.jit(sharded)
