"""Multi-axis device meshes: dp / tp / pp / sp / ep.

The reference is data-parallel-only (SURVEY.md §3.6); its nearest concept is
process sets (rank subgroups). In the TPU-native design, parallelism
strategies are **axes of one device mesh** — the factorization XLA's
collectives are compiled against, laid out so that the fastest-varying axes
sit on adjacent ICI links:

- ``dp``: data parallel — gradient allreduce (the Horovod core capability)
- ``tp``: tensor parallel — layer-internal psum/all_gather
- ``pp``: pipeline parallel — stage-to-stage ppermute
- ``sp``: sequence/context parallel — ring attention over ICI neighbors
- ``ep``: expert parallel — alltoall dispatch (the reference's ``alltoall``
  primitive, given a consumer)

Axis order in the mesh tuple = topology-major order: tp innermost (most
bandwidth-hungry, shortest ICI hops), then sp, ep, pp, dp outermost
(allreduce tolerates the longest hops / DCN). ``build_mesh`` reshapes the
canonical ICI-ordered device list row-major into that axis order and
asserts the constructed :class:`jax.sharding.Mesh` preserves it — flat
rank ``r`` of the topology occupies mesh position
``np.unravel_index(r, shape)``, so contiguous innermost-axis groups are
ICI-contiguous by construction.

The 2-D training mesh (:func:`mesh_2d`) is the ``(batch, model)``
factorization the step factories compile sync modes against:
``batch`` = dp (outermost, long hops / DCN), ``model`` = tp (innermost,
short ICI hops). ``HOROVOD_MESH_SHAPE="BxM"`` selects it without code
changes; unset leaves every factory on the flat 1-D axis bit for bit.
"""

from __future__ import annotations

import dataclasses
import math
import os
from typing import Any, Sequence

import numpy as np

AXIS_ORDER = ("dp", "pp", "ep", "sp", "tp")  # outermost -> innermost

#: Axis names of the 2-D training mesh, outermost first: ``batch`` is the
#: data axis (gradient sync, long hops), ``model`` the intra-layer axis
#: (parameter gathers, short ICI hops). The tuple is also the axis
#: argument collectives take to reduce over the WHOLE 2-D world in flat
#: rank order ("batch" major, matching the canonical device list).
MESH2D_AXES = ("batch", "model")

#: Leading-axis placement of resident fsdp stacked rows on the 2-D mesh:
#: row ``k = m*batch + b`` lands on device ``(b, m)`` ("model" major), so
#: the batch-axis gather at fixed m reassembles a CONTIGUOUS model block
#: and the model-axis gather concatenates blocks in flat order — see
#: ``ops.fusion.shard_ownership_2d``.
MESH2D_ROW_AXES = ("model", "batch")


def _nearest_factorizations(n_devices: int, axis: str, requested: int,
                            ) -> str:
    """Render the valid sizes for ``axis`` nearest to ``requested`` —
    the actionable half of a does-not-divide rejection."""
    divisors = [d for d in range(1, n_devices + 1) if n_devices % d == 0]
    divisors.sort(key=lambda d: (abs(d - requested), d))
    parts = []
    for d in divisors[:2]:
        parts.append(f"{axis}={d} (mesh {n_devices // d}x{d})")
    return " or ".join(parts)


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    dp: int = -1  # -1: infer from device count
    pp: int = 1
    ep: int = 1
    sp: int = 1
    tp: int = 1

    def resolve(self, n_devices: int) -> dict[str, int]:
        sizes = {a: getattr(self, a) for a in AXIS_ORDER}
        # Reject a fixed axis that cannot divide the device count up
        # front, with the nearest valid factorization spelled out —
        # "tp=3 does not divide 8" is actionable; "mesh does not cover"
        # after inference is not.
        for a, v in sizes.items():
            if v > 0 and n_devices % v != 0:
                raise ValueError(
                    f"mesh axis {a}={v} does not divide {n_devices} "
                    f"device(s); nearest valid: "
                    f"{_nearest_factorizations(n_devices, a, v)}")
        fixed = math.prod(v for v in sizes.values() if v > 0)
        inferred = [a for a, v in sizes.items() if v <= 0]
        if len(inferred) > 1:
            raise ValueError(f"at most one axis may be inferred, got {inferred}")
        if inferred:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"cannot infer {inferred[0]}: {n_devices} devices not "
                    f"divisible by {fixed}"
                )
            sizes[inferred[0]] = n_devices // fixed
        if math.prod(sizes.values()) != n_devices:
            raise ValueError(
                f"mesh {sizes} does not cover {n_devices} devices"
            )
        return sizes


def _default_devices():
    from ..topology import sorted_devices

    from .. import basics

    if basics.is_initialized():
        return basics._state.topology.devices
    return sorted_devices()


def _assert_topology_major(mesh, devices) -> None:
    """The constructed Mesh must enumerate devices in topology-major
    order: flat rank r at mesh position unravel_index(r, shape). A
    row-major reshape guarantees it; this assertion keeps the guarantee
    load-bearing (the docstring said it for four PRs while nothing
    checked)."""
    got = list(np.asarray(mesh.devices).reshape(-1))
    want = list(devices)
    if got != want:
        raise AssertionError(
            "mesh device order does not match topology-major placement: "
            f"mesh enumerates {[getattr(d, 'id', d) for d in got]} but the "
            f"canonical ICI order is {[getattr(d, 'id', d) for d in want]}")


def build_mesh(
    spec: MeshSpec | None = None,
    devices: Sequence[Any] | None = None,
    **axis_sizes: int,
):
    """Build a named mesh over the canonical ICI-ordered device list.

    ``build_mesh(dp=4, tp=2)`` or ``build_mesh(MeshSpec(dp=-1, tp=2))``.
    Devices default to the initialized world's topology order; the
    row-major reshape places flat rank r at mesh position
    ``unravel_index(r, shape)``, so contiguous tp (innermost) groups are
    ICI-contiguous — asserted, not assumed.
    """
    from jax.sharding import Mesh

    if spec is None:
        spec = MeshSpec(**{a: axis_sizes.get(a, -1 if a == "dp" else 1) for a in AXIS_ORDER})
    elif axis_sizes:
        raise ValueError("pass either a MeshSpec or axis sizes, not both")

    if devices is None:
        devices = _default_devices()
    sizes = spec.resolve(len(devices))
    shape = tuple(sizes[a] for a in AXIS_ORDER)
    array = np.array(devices).reshape(shape)
    mesh = Mesh(array, AXIS_ORDER)
    _assert_topology_major(mesh, list(devices))
    return mesh


# ---------------------------------------------------------------------------
# The 2-D (batch, model) training mesh
# ---------------------------------------------------------------------------


def mesh_2d(batch: int = -1, model: int = 1,
            devices: Sequence[Any] | None = None):
    """The ``(batch, model)`` training mesh over the canonical device
    list: ``model`` innermost (contiguous flat ranks — the shortest ICI
    hops carry the intra-layer parameter collectives), ``batch``
    outermost (gradient sync tolerates the long hops). ``batch=-1``
    infers from the device count. Flat rank ``r`` sits at mesh position
    ``(r // model, r % model)``."""
    from jax.sharding import Mesh

    if devices is None:
        devices = _default_devices()
    # dp/tp carry the divide-and-nearest-factorization checks; the 2-D
    # mesh is exactly the (dp, tp) plane of the canonical axis order.
    sizes = MeshSpec(dp=batch, tp=model).resolve(len(devices))
    b, m = sizes["dp"], sizes["tp"]
    mesh = Mesh(np.array(devices).reshape(b, m), MESH2D_AXES)
    _assert_topology_major(mesh, list(devices))
    return mesh


def is_mesh_2d(mesh) -> bool:
    """True when ``mesh`` is a named 2-D ``(batch, model)`` mesh."""
    return (mesh is not None
            and tuple(getattr(mesh, "axis_names", ())) == MESH2D_AXES)


def mesh_axis_sizes(mesh) -> dict[str, int]:
    """``{"batch": B, "model": M}`` of a 2-D training mesh."""
    if not is_mesh_2d(mesh):
        raise ValueError(f"not a (batch, model) mesh: {mesh!r}")
    return dict(zip(MESH2D_AXES, np.asarray(mesh.devices).shape))


def parse_mesh_shape(value: str) -> tuple[int, int]:
    """Parse a ``"BxM"`` mesh-shape string (e.g. ``"4x2"``) into
    ``(batch, model)``. ``-1`` for batch means infer."""
    parts = str(value).strip().lower().replace("×", "x").split("x")
    if len(parts) != 2:
        raise ValueError(
            f"HOROVOD_MESH_SHAPE must look like 'BxM' (e.g. '4x2'), "
            f"got {value!r}")
    try:
        b, m = int(parts[0]), int(parts[1])
    except ValueError:
        raise ValueError(
            f"HOROVOD_MESH_SHAPE must be two integers 'BxM', got "
            f"{value!r}") from None
    if m < 1 or (b < 1 and b != -1):
        raise ValueError(
            f"HOROVOD_MESH_SHAPE axes must be positive (batch may be -1 "
            f"to infer), got {value!r}")
    return b, m


def resolve_mesh_shape() -> tuple[int, int] | None:
    """The requested 2-D mesh shape: ``HOROVOD_MESH_SHAPE`` first, then
    an autotune pin (:func:`horovod_tpu.autotune.tuned_mesh_shape`).
    None — the default — leaves every factory on the flat 1-D axis,
    bit for bit."""
    raw = os.environ.get("HOROVOD_MESH_SHAPE", "").strip()
    if raw:
        return parse_mesh_shape(raw)
    from ..autotune import tuned_mesh_shape

    return tuned_mesh_shape()
