"""Multi-axis device meshes: dp / tp / pp / sp / ep.

The reference is data-parallel-only (SURVEY.md §3.6); its nearest concept is
process sets (rank subgroups). In the TPU-native design, parallelism
strategies are **axes of one device mesh** — the factorization XLA's
collectives are compiled against, laid out so that the fastest-varying axes
sit on adjacent ICI links:

- ``dp``: data parallel — gradient allreduce (the Horovod core capability)
- ``tp``: tensor parallel — layer-internal psum/all_gather
- ``pp``: pipeline parallel — stage-to-stage ppermute
- ``sp``: sequence/context parallel — ring attention over ICI neighbors
- ``ep``: expert parallel — alltoall dispatch (the reference's ``alltoall``
  primitive, given a consumer)

Axis order in the mesh tuple = topology-major order: tp innermost (most
bandwidth-hungry, shortest ICI hops), then sp, ep, pp, dp outermost
(allreduce tolerates the longest hops / DCN).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Sequence

import numpy as np

AXIS_ORDER = ("dp", "pp", "ep", "sp", "tp")  # outermost -> innermost


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    dp: int = -1  # -1: infer from device count
    pp: int = 1
    ep: int = 1
    sp: int = 1
    tp: int = 1

    def resolve(self, n_devices: int) -> dict[str, int]:
        sizes = {a: getattr(self, a) for a in AXIS_ORDER}
        fixed = math.prod(v for v in sizes.values() if v > 0)
        inferred = [a for a, v in sizes.items() if v <= 0]
        if len(inferred) > 1:
            raise ValueError(f"at most one axis may be inferred, got {inferred}")
        if inferred:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"cannot infer {inferred[0]}: {n_devices} devices not "
                    f"divisible by {fixed}"
                )
            sizes[inferred[0]] = n_devices // fixed
        if math.prod(sizes.values()) != n_devices:
            raise ValueError(
                f"mesh {sizes} does not cover {n_devices} devices"
            )
        return sizes


def build_mesh(
    spec: MeshSpec | None = None,
    devices: Sequence[Any] | None = None,
    **axis_sizes: int,
):
    """Build a named mesh over the canonical ICI-ordered device list.

    ``build_mesh(dp=4, tp=2)`` or ``build_mesh(MeshSpec(dp=-1, tp=2))``.
    Devices default to the initialized world's topology order, so contiguous
    tp groups are ICI-contiguous.
    """
    from jax.sharding import Mesh

    from ..topology import sorted_devices

    if spec is None:
        spec = MeshSpec(**{a: axis_sizes.get(a, -1 if a == "dp" else 1) for a in AXIS_ORDER})
    elif axis_sizes:
        raise ValueError("pass either a MeshSpec or axis sizes, not both")

    if devices is None:
        from .. import basics

        if basics.is_initialized():
            devices = basics._state.topology.devices
        else:
            devices = sorted_devices()
    sizes = spec.resolve(len(devices))
    shape = tuple(sizes[a] for a in AXIS_ORDER)
    array = np.array(devices).reshape(shape)
    return Mesh(array, AXIS_ORDER)
