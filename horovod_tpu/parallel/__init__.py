from .mesh import MeshSpec, build_mesh  # noqa: F401
from .data_parallel import make_train_step  # noqa: F401
from .hierarchical import (  # noqa: F401
    CROSS_AXIS,
    HIERARCHICAL_AXES,
    LOCAL_AXIS,
    hierarchical_allreduce,
    hierarchical_mesh,
    host_hierarchical_allreduce,
)
from .moe import expert_ffn, make_moe_step, moe_layer  # noqa: F401
from .sequence import (  # noqa: F401
    make_sp_attention_step,
    ring_attention,
    shard_sequence,
    ulysses_attention,
)
