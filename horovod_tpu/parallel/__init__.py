from .mesh import MeshSpec, build_mesh  # noqa: F401
from .data_parallel import make_train_step  # noqa: F401
from .sequence import (  # noqa: F401
    make_sp_attention_step,
    ring_attention,
    shard_sequence,
    ulysses_attention,
)
