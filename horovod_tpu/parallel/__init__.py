from .mesh import MeshSpec, build_mesh  # noqa: F401
from .data_parallel import make_train_step  # noqa: F401
