"""Full parameter sharding (ZeRO-3 / FSDP): params live sharded at rest.

PR 4's ``sync_mode="sharded"`` sharded the optimizer state (~1/n per
rank) but every rank still held a full parameter copy, capping the
largest trainable model at one device's HBM. This module removes that
cap: under ``sync_mode="fsdp"`` each rank persistently holds only its
byte-balanced parameter shard (the same rank-identical ownership map the
sharded optimizer state rides — :func:`ops.fusion.shard_ownership`), and
full parameters exist only *transiently, per segment*:

- the forward pass allgathers each segment's parameters just ahead of
  the layers that consume them (:func:`gather_params` — the per-segment
  gather HLOs have no cross-segment dependencies, so XLA's
  latency-hiding scheduler runs segment k+1's gather concurrently with
  segment k's compute: the prefetch);
- the backward pass emits each segment's gradient **reduce-scatter
  inside backprop** (the gather boundary is a custom-vjp whose backward
  reduces the full-shaped cotangents straight down to this rank's owned
  shards — the same boundary trick as ``make_overlapped_train_step``,
  with the cotangent landing in the *shard* domain instead of riding a
  zero background);
- the shard-local optimizer update writes back to the resident shard
  with **no trailing full-parameter allgather at all** — the next step's
  forward gather is the only re-materialization.

Wire per step: one parameter allgather (forward) + one gradient
reduce-scatter (backward) = the same bytes as one allreduce — but
resident param+optimizer memory is ~1/n of monolithic, which is the
unlock for models that do not fit one device's HBM. The int8/cast
compression halves ride the same EQuARX RS/AG machinery as the sharded
mode (``ops/quantization.py``).

Layout notes: the resident representation is :class:`ShardedParams` — a
registered pytree whose leaves are per-leaf ``(world, shard)`` stacked
rows (rank r's shard is row r, exactly the sharded optimizer-state
layout) plus static metadata (original tree structure, shapes, dtypes)
so the full tensors can be re-materialized from shards alone.
``shard_ownership`` being a pure function of shapes and world size keeps
every layer that already round-trips the optimizer state (checkpoints,
elastic resize, the peer replica pool) working on parameters with the
same host math.

``HOROVOD_FSDP_RESHARD_AFTER_FORWARD`` (default 1) keeps the
per-segment just-in-time gathers; ``0`` collapses the segmentation to
one up-front gather whose full tensors plausibly stay live across the
whole forward+backward (retain-after-forward: fewer, larger collectives,
higher in-step peak memory). In the compiled regime the in-step residual
lifetime is ultimately XLA's rematerialization decision — compose with
``jax.remat`` over the model for a hard in-step peak bound; the
*resident* (between-step) footprint is ~1/n either way.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Sequence

import numpy as np

import jax
import jax.numpy as jnp


class _Meta(NamedTuple):
    """Static (hashable) metadata of a :class:`ShardedParams`: the
    original tree structure and per-leaf full shapes/dtypes — everything
    needed to re-materialize full tensors from the shard rows."""

    treedef: Any
    shapes: tuple
    dtypes: tuple
    world_size: int


@jax.tree_util.register_pytree_node_class
class ShardedParams:
    """Resident fsdp-mode parameters: per-leaf stacked ``(world, shard)``
    rows + static full-shape metadata.

    Row ``r`` of every leaf is rank r's owned slice of the zero-padded
    flat view (ownership map: :func:`ops.fusion.shard_ownership`), so
    sharding the leading axis over the mesh
    (``data_parallel.shard_state``) leaves each rank holding ~1/n of the
    model at rest. Registered as a pytree: ``jax.tree.map`` /
    ``device_put`` / shard_map specs all treat the rows as ordinary
    leaves and rebuild the wrapper (metadata is aux data, static under
    tracing).
    """

    def __init__(self, rows: Sequence[Any], meta: _Meta):
        self.rows = list(rows)
        self.meta = meta

    def tree_flatten(self):
        return tuple(self.rows), self.meta

    @classmethod
    def tree_unflatten(cls, meta, rows):
        return cls(list(rows), meta)

    # -- static facts --------------------------------------------------------

    @property
    def world_size(self) -> int:
        return self.meta.world_size

    def templates(self) -> list[jax.ShapeDtypeStruct]:
        """Per-leaf full-shape templates, in row order."""
        return [jax.ShapeDtypeStruct(s, d)
                for s, d in zip(self.meta.shapes, self.meta.dtypes)]

    def template_tree(self):
        """The full-parameter pytree of ShapeDtypeStructs."""
        return jax.tree.unflatten(self.meta.treedef, self.templates())

    def shards_tree(self):
        """This object's row leaves re-hung on the ORIGINAL tree
        structure — the plain-pytree view the shard-local optimizer
        state and gradients are congruent to."""
        return jax.tree.unflatten(self.meta.treedef, self.rows)

    def with_rows(self, rows_tree) -> "ShardedParams":
        """A new ShardedParams carrying ``rows_tree``'s leaves (same
        structure as :meth:`shards_tree`) under this metadata."""
        return ShardedParams(jax.tree.leaves(rows_tree), self.meta)

    def row(self, r: int):
        """Rank ``r``'s shard as a pytree (original structure, one 1-D
        host slice per leaf) — what the peer replica record carries.
        Slices BEFORE the host transfer, so only the owned row (~1/n)
        moves device→host, never the full stacked leaf."""
        return jax.tree.unflatten(
            self.meta.treedef, [np.asarray(x[r]) for x in self.rows])


def _resident_bytes(leaves, world_size: int) -> int:
    # size/dtype are static facts — never np.asarray a leaf here (this
    # runs on resize/checkpoint paths; materializing device arrays on
    # the host for a metrics gauge would cost a full model transfer).
    total = sum(int(l.size) * jnp.dtype(l.dtype).itemsize for l in leaves)
    return total // max(1, int(world_size))


def _record_resident(kind: str, sync_mode: str, nbytes: int) -> None:
    try:
        from .. import metrics

        metrics.RESIDENT_BYTES.set(nbytes, kind=kind, sync_mode=sync_mode)
    except Exception:  # noqa: BLE001 — instrumentation is best-effort
        pass
    try:
        # The memory observatory's live accounting rides the same call
        # sites: every (re)materialization of sharded state updates the
        # hvd_hbm_bytes{kind} cell with its exact per-rank nbytes.
        from .. import memory

        memory.note_resident(kind, nbytes)
    except Exception:  # noqa: BLE001 — instrumentation is best-effort
        pass


def _note_param_leaves(params, sizes, world_size: int) -> None:
    """Feed the memory observatory's forensics table: the per-rank
    resident bytes of every named parameter leaf (ownership-map rows,
    not full leaves — the bytes that actually sit in HBM). Never
    raises."""
    try:
        from .. import memory

        flat, _ = jax.tree_util.tree_flatten_with_path(params)
        per_leaf = [
            (jax.tree_util.keystr(path) or "<root>",
             int(s) * np.dtype(leaf.dtype).itemsize)
            for (path, leaf), s in zip(flat, sizes)
        ]
        per_leaf.sort(key=lambda kv: kv[1], reverse=True)
        memory.note_resident(
            "params", sum(b for _, b in per_leaf),
            top_leaves=per_leaf[:memory.top_n()])
    except Exception:  # noqa: BLE001 — instrumentation is best-effort
        pass


def shard_params(params, world_size: int | None = None) -> ShardedParams:
    """Shard a full parameter pytree into the resident fsdp layout.

    Every leaf of ``size m`` becomes ``(n, ceil(m/n))`` rows of its
    zero-padded flat view (per :func:`ops.fusion.shard_ownership` —
    byte-balanced, rank-identical, a pure function of shapes and world
    size). Pure host/jnp math; place the result on the mesh with
    ``data_parallel.shard_state`` so each rank materializes only its
    row. An already-sharded input is re-sharded for ``world_size``.
    """
    from ..ops.fusion import shard_ownership

    if isinstance(params, ShardedParams):
        full = unshard_params(params)
        return shard_params(full, world_size)
    if world_size is None:
        from .. import basics

        world_size = basics.size()
    n = int(world_size)
    if n < 1:
        raise ValueError(
            f"shard_params needs a positive world size, got {world_size!r} "
            "(init() first, or pass world_size=)")
    leaves, treedef = jax.tree.flatten(params)
    # jnp.asarray only — size/shape/dtype are static facts; np.asarray
    # here would pull every full leaf device→host on each resize hop.
    leaves = [jnp.asarray(l) for l in leaves]
    sizes = shard_ownership(leaves, n)
    rows = [
        jnp.pad(l.ravel(), (0, n * s - int(l.size))).reshape(n, s)
        for l, s in zip(leaves, sizes)
    ]
    meta = _Meta(
        treedef=treedef,
        shapes=tuple(tuple(l.shape) for l in leaves),
        dtypes=tuple(np.dtype(l.dtype) for l in leaves),
        world_size=n,
    )
    sp = ShardedParams(rows, meta)
    _record_resident("params", "fsdp", _resident_bytes(rows, n))
    _note_param_leaves(params, sizes, n)
    return sp


def unshard_params(sp: ShardedParams):
    """Gather the resident rows back to the full parameter pytree — the
    exact inverse of :func:`shard_params` (padding trimmed, shapes and
    dtypes restored). Pure host/jnp math when the rows are addressable
    (single-controller worlds, host snapshots); non-addressable
    P(axis)-sharded rows are first replicated via the same compiled
    allgather the optimizer-state unshard uses."""
    from ..optimizer import _gather_if_nonaddressable

    if not isinstance(sp, ShardedParams):
        raise TypeError(
            f"unshard_params expects a ShardedParams, got {type(sp).__name__}"
            " (a full pytree is already unsharded)")
    rows = _gather_if_nonaddressable(sp.rows)
    out = []
    for row, shape, dtype in zip(rows, sp.meta.shapes, sp.meta.dtypes):
        row = jnp.asarray(row)
        size = int(np.prod(shape)) if shape else 1
        flat = row.reshape(-1)[:size]
        out.append(flat.reshape(shape).astype(dtype))
    return jax.tree.unflatten(sp.meta.treedef, out)


def reshard_params(params, world_size: int) -> ShardedParams:
    """Re-shard parameters (full pytree or ShardedParams) for a possibly
    new world size — the elastic-resize hop. Ownership re-derives from
    the new size alone, so no coordination is needed (the same contract
    as ``reshard_opt_state``)."""
    return shard_params(params, world_size)


def stack_param_rows(rows_by_rank: Sequence[Any], meta: _Meta,
                     ) -> ShardedParams:
    """Re-materialize a ShardedParams from per-rank row pytrees (the
    peer replica pool's reconstruction path): ``rows_by_rank[r]`` is the
    pytree :meth:`ShardedParams.row` returned for rank r. The stack must
    be complete — exactly ``meta.world_size`` rows, in rank order."""
    if len(rows_by_rank) != meta.world_size:
        raise ValueError(
            f"stack_param_rows needs {meta.world_size} rows (one per rank "
            f"of the recorded world), got {len(rows_by_rank)}")
    stacked = jax.tree.map(
        lambda *xs: np.stack([np.asarray(x) for x in xs]), *rows_by_rank)
    return ShardedParams(jax.tree.leaves(stacked), meta)


def resident_param_bytes(sp: ShardedParams) -> int:
    """Per-rank resident parameter bytes (one row of every leaf)."""
    return _resident_bytes(sp.rows, sp.world_size)


def reshard_after_forward() -> bool:
    """The ``HOROVOD_FSDP_RESHARD_AFTER_FORWARD`` knob (default on):
    per-segment just-in-time gathers. Off collapses the segmentation to
    one up-front gather (retain-after-forward)."""
    import os

    return os.environ.get(
        "HOROVOD_FSDP_RESHARD_AFTER_FORWARD", "1").strip() != "0"


def _wire_itemsize(compression, dtype) -> int:
    """Bytes per element the gather actually puts on the wire."""
    if getattr(compression, "marker", None) == "int8":
        return 1
    try:
        wire, _ = compression.compress(jnp.zeros((1,), dtype))
        return jnp.dtype(wire.dtype).itemsize
    except Exception:  # noqa: BLE001 — fall back to the storage dtype
        return jnp.dtype(dtype).itemsize


def _record_gather(templates, compression, axis: str = "batch") -> None:
    """Trace-time metrics record of one parameter-gather program segment
    (static wire bytes — the per-trace shape, not a per-step rate, same
    contract as the grad-sync flush counters), labeled by the mesh axis
    the collective runs over: the flat 1-D wire and the 2-D batch leg
    record under ``axis="batch"``, the 2-D intra-layer leg under
    ``axis="model"``. Never raises."""
    try:
        from .. import metrics

        nbytes = sum(
            int(np.prod(t.shape) if t.shape else 1)
            * _wire_itemsize(compression, t.dtype)
            for t in templates)
        metrics.PARAM_GATHER_BYTES.observe(nbytes, axis=axis)
    except Exception:  # noqa: BLE001 — instrumentation is best-effort
        pass


def _gather_boundary(shard_leaves, templates, seg_index, spec, axis_name,
                     world_size, salt):
    """Shards-in / full-tensors-out boundary for ONE segment, with the
    gradient reduce-scatter riding the custom-vjp backward.

    Forward: allgather this segment's shards to full tensors through the
    optimizer's wire (cast compression halves the gather bytes; int8
    rides the quantized EQuARX gather). Backward: the full-shaped
    cotangents reduce-scatter through the exact wire the
    DistributedOptimizer was built with (op/compression/scaling/
    bucketing — ``optimizer._reducescatter_grads``), landing directly in
    the shard domain: the cotangent of a ``(s,)`` shard input is the
    reduced ``(s,)`` owned slice. Because the boundary sits inside the
    differentiated function, each segment's reduce-scatter is emitted at
    the point its gradients finish accumulating — inside backprop, where
    it overlaps the remaining layers' backward compute (the overlap
    scheduler's contract, inherited).

    ``salt`` (the int8 stochastic-rounding step counter) rides the
    forward as a residual, exactly like ``_segment_sync``.
    """
    from ..optimizer import (
        _gather_param_shards,
        _record_flush,
        _reducescatter_grads,
    )
    from ..profiler import annotate_collective

    n = int(world_size)
    templates = list(templates)

    def gather(ls, s):
        _record_gather(templates, spec.compression)
        with annotate_collective(f"fsdp.param_gather.seg{seg_index}"):
            full = _gather_param_shards(
                list(ls), templates, spec.compression, axis_name, n,
                spec.fusion_threshold_bytes, 0, quant_salt=s)
        return list(full)

    def reduce_cts(cts, s):
        with annotate_collective(f"fsdp.grad_reducescatter.seg{seg_index}"):
            shards = _reducescatter_grads(
                list(cts),
                spec.op,
                axis_name,
                spec.compression,
                spec.prescale_factor,
                spec.postscale_factor,
                spec.fusion_threshold_bytes,
                0,
                world_size=n,
                quant_salt=s,
                issue_reversed=True,
                # One flush record per segment, labeled fsdp — the mode
                # rides down so the wire-view bytes land under the label
                # that actually ran (no phantom 'sharded' series).
                flush_label="fsdp",
            )
        return [jnp.asarray(sh).astype(jnp.asarray(orig).dtype)
                for sh, orig in zip(shards, shard_leaves)]

    if salt is None:

        @jax.custom_vjp
        def boundary(ls):
            return gather(ls, None)

        def fwd(ls):
            return gather(ls, None), None

        def bwd(_, cts):
            return (reduce_cts(cts, None),)

        boundary.defvjp(fwd, bwd)
        return boundary(list(shard_leaves))

    @jax.custom_vjp
    def boundary_salted(ls, s):
        return gather(ls, s)

    def fwd_salted(ls, s):
        return gather(ls, s), s

    def bwd_salted(s, cts):
        return (reduce_cts(cts, s),
                np.zeros(np.shape(s), jax.dtypes.float0))

    boundary_salted.defvjp(fwd_salted, bwd_salted)
    return boundary_salted(list(shard_leaves), salt)


def gather_params(shards_tree, meta: _Meta, spec, axis_name,
                  world_size: int, salt=None,
                  num_segments: int | None = None):
    """Re-materialize the FULL parameter pytree from this rank's shards,
    segment by segment, inside a shard_map trace — the heart of the fsdp
    forward pass.

    ``shards_tree`` holds this rank's per-leaf 1-D owned shards (the
    :meth:`ShardedParams.shards_tree` view with the leading world axis
    stripped). The template leaves are split into K contiguous
    byte-balanced segments (``ops.fusion.segment_leaves`` — layer order)
    and each segment gets a :func:`_gather_boundary`: the forward
    allgathers that segment's parameters (independent HLOs in segment
    order, so XLA overlaps segment k+1's gather with segment k's
    compute), and differentiating through the result yields gradients
    that are ALREADY reduce-scattered to the shard domain, each
    segment's collective emitted inside backprop.

    With ``HOROVOD_FSDP_RESHARD_AFTER_FORWARD=0`` the segmentation
    collapses to one up-front gather (retain-after-forward).
    """
    from ..ops.fusion import fsdp_segments, segment_leaves

    shard_leaves = jax.tree.leaves(shards_tree)
    templates = [jax.ShapeDtypeStruct(s, d)
                 for s, d in zip(meta.shapes, meta.dtypes)]
    if len(shard_leaves) != len(templates):
        raise ValueError(
            f"gather_params: {len(shard_leaves)} shard leaves vs "
            f"{len(templates)} templates — the shards tree must be the "
            "ShardedParams row view of the same parameter pytree")
    if not reshard_after_forward():
        k = 1
    elif num_segments is not None:
        k = max(1, int(num_segments))
    else:
        k = fsdp_segments()
    full: list[Any] = [None] * len(templates)
    for si, idx in enumerate(segment_leaves(templates, k)):
        gathered = _gather_boundary(
            [shard_leaves[i] for i in idx],
            [templates[i] for i in idx],
            si, spec, axis_name, world_size, salt)
        for i, g in zip(idx, gathered):
            full[i] = g
    return jax.tree.unflatten(meta.treedef, full)


# ---------------------------------------------------------------------------
# The 2-D (batch, model) wire: two-leg gathers / reduce-scatters
# ---------------------------------------------------------------------------


def _gather_boundary_2d(shard_leaves, templates, seg_index, spec,
                        batch: int, model: int, salt):
    """The :func:`_gather_boundary` of the 2-D ``(batch, model)`` mesh:
    same shards-in / full-tensors-out custom-vjp contract, with each
    collective split into two legs placed on the links that suit it.

    Forward — resident ``(shard,)`` rows to full tensors in two hops:

    1. **batch leg** (long hops / DCN): the existing bucketed
       ``_gather_param_shards`` machinery allgathers this rank's shard
       over the ``batch`` axis into its model coordinate's contiguous
       ``(batch*shard,)`` block — 1/model of the segment's bytes on the
       slow links, vs the full segment on the flat 1-D wire.
    2. **model leg** (short ICI hops): one plain ``lax.all_gather`` per
       leaf over the ``model`` axis concatenates the blocks into the
       full flat view — the intra-layer collective XLA schedules on the
       fastest links of the mesh.

    Backward reverses the legs: the full-shaped cotangents
    ``psum_scatter`` over ``model`` down to the block domain, then the
    block cotangents ride the SAME bucketed ``_reducescatter_grads``
    wire as the flat mode over the ``batch`` axis (compression, scaling,
    flush accounting — ``flush_label="fsdp"``), landing in the resident
    ``(shard,)`` domain. ``op=Average`` divides by ``batch`` inside the
    batch leg, so the model leg contributes its own ``1/model`` — the
    composition equals the flat wire's ``1/(batch*model)``.

    The two-hop split of :func:`ops.fusion.shard_ownership_2d` keeps the
    resident row layout byte-identical to the flat wire, so the gathered
    full tensors are bit-equal to the 1-D gather; only the gradient
    reduction association differs (two-leg vs flat), which is
    reduction-order noise.
    """
    from jax import lax

    from ..optimizer import _gather_param_shards, _reducescatter_grads
    from ..ops import collective_ops
    from ..ops.fusion import shard_ownership_2d
    from ..profiler import annotate_collective

    b, m = int(batch), int(model)
    templates = list(templates)
    ownership = shard_ownership_2d(templates, b, m)
    batch_axis, model_axis = "batch", "model"
    block_templates = [
        jax.ShapeDtypeStruct((share,), t.dtype)
        for (share, _s), t in zip(ownership, templates)
    ]

    def gather(ls, s):
        _record_gather(block_templates, spec.compression, axis="batch")
        with annotate_collective(
                f"fsdp.param_gather.batch.seg{seg_index}"):
            blocks = _gather_param_shards(
                list(ls), block_templates, spec.compression, batch_axis,
                b, spec.fusion_threshold_bytes, 0, quant_salt=s)
        _record_gather(templates, None, axis="model")
        full = []
        with annotate_collective(
                f"fsdp.param_gather.model.seg{seg_index}"):
            for blk, t in zip(blocks, templates):
                flat = lax.all_gather(jnp.ravel(blk), model_axis,
                                      tiled=True)
                size = int(np.prod(t.shape)) if t.shape else 1
                full.append(flat[:size].reshape(t.shape).astype(t.dtype))
        return full

    def reduce_cts(cts, s):
        blocks = []
        with annotate_collective(
                f"fsdp.grad_reducescatter.model.seg{seg_index}"):
            for ct, (share, shard) in zip(cts, ownership):
                flat = jnp.ravel(jnp.asarray(ct))
                flat = jnp.pad(flat, (0, m * share - int(flat.size)))
                blk = lax.psum_scatter(flat, model_axis, tiled=True)
                if spec.op is collective_ops.Average:
                    # The batch leg divides by `batch`; this leg owes
                    # the remaining 1/model of the flat wire's 1/world.
                    blk = blk / m
                blocks.append(blk)
        with annotate_collective(
                f"fsdp.grad_reducescatter.batch.seg{seg_index}"):
            shards = _reducescatter_grads(
                blocks,
                spec.op,
                batch_axis,
                spec.compression,
                spec.prescale_factor,
                spec.postscale_factor,
                spec.fusion_threshold_bytes,
                0,
                world_size=b,
                quant_salt=s,
                issue_reversed=True,
                flush_label="fsdp",
            )
        return [jnp.asarray(sh).astype(jnp.asarray(orig).dtype)
                for sh, orig in zip(shards, shard_leaves)]

    if salt is None:

        @jax.custom_vjp
        def boundary(ls):
            return gather(ls, None)

        def fwd(ls):
            return gather(ls, None), None

        def bwd(_, cts):
            return (reduce_cts(cts, None),)

        boundary.defvjp(fwd, bwd)
        return boundary(list(shard_leaves))

    @jax.custom_vjp
    def boundary_salted(ls, s):
        return gather(ls, s)

    def fwd_salted(ls, s):
        return gather(ls, s), s

    def bwd_salted(s, cts):
        return (reduce_cts(cts, s),
                np.zeros(np.shape(s), jax.dtypes.float0))

    boundary_salted.defvjp(fwd_salted, bwd_salted)
    return boundary_salted(list(shard_leaves), salt)


def gather_params_2d(shards_tree, meta: _Meta, spec, batch: int,
                     model: int, salt=None,
                     num_segments: int | None = None):
    """:func:`gather_params` on the 2-D ``(batch, model)`` mesh — the
    same per-segment just-in-time schedule, each segment's boundary
    split into the batch-leg (bucketed machinery) and model-leg (plain
    ICI all_gather) collectives of :func:`_gather_boundary_2d`. The
    resident row layout is identical to the flat wire
    (:func:`ops.fusion.shard_ownership_2d`), so a ShardedParams built by
    :func:`shard_params` for ``world = batch*model`` feeds either."""
    from ..ops.fusion import fsdp_segments, segment_leaves

    shard_leaves = jax.tree.leaves(shards_tree)
    templates = [jax.ShapeDtypeStruct(s, d)
                 for s, d in zip(meta.shapes, meta.dtypes)]
    if len(shard_leaves) != len(templates):
        raise ValueError(
            f"gather_params_2d: {len(shard_leaves)} shard leaves vs "
            f"{len(templates)} templates — the shards tree must be the "
            "ShardedParams row view of the same parameter pytree")
    if int(batch) * int(model) != int(meta.world_size):
        raise ValueError(
            f"gather_params_2d: mesh {batch}x{model} does not factor the "
            f"sharded world of {meta.world_size} rows")
    if not reshard_after_forward():
        k = 1
    elif num_segments is not None:
        k = max(1, int(num_segments))
    else:
        k = fsdp_segments()
    full: list[Any] = [None] * len(templates)
    for si, idx in enumerate(segment_leaves(templates, k)):
        gathered = _gather_boundary_2d(
            [shard_leaves[i] for i in idx],
            [templates[i] for i in idx],
            si, spec, batch, model, salt)
        for i, g in zip(idx, gathered):
            full[i] = g
    return jax.tree.unflatten(meta.treedef, full)
