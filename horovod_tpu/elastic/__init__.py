from .state import (  # noqa: F401
    ObjectState,
    PeerShardedState,
    State,
    TpuState,
)
from .runner import run  # noqa: F401
