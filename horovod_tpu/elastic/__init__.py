from .state import ObjectState, State, TpuState  # noqa: F401
from .runner import run  # noqa: F401
