from .state import (  # noqa: F401
    ObjectState,
    PeerShardedState,
    State,
    TpuState,
)
from .runner import run  # noqa: F401
from ..integrity import (  # noqa: F401
    consume_skip_ahead,
    observe_loss,
)
