"""The elastic retry loop: ``@hvd.elastic.run``.

Parity with the reference's ``horovod/common/elastic.py — run_fn()``
(SURVEY.md §4.4): the decorated training function survives peer
failure/addition by catching the two recovery exceptions:

- ``HorovodInternalError`` (a collective failed — e.g. a TPU VM in the
  slice was preempted mid-step): restore() to the last commit, tear down
  and re-initialize the world, then retry.
- ``HostsUpdatedInterrupt`` (driver says the host set changed, nothing
  failed): keep in-memory state, re-rendezvous, sync, continue.

TPU divergence (by design, SURVEY.md §4.4 "Elastic × ICI topology"): worlds
re-form on valid sub-topologies only — the new device set after re-init is
whatever the re-rendezvous yields; per-chip shrink inside a slice is not a
thing on ICI, so recovery granularity is the host (TPU VM). The re-init path
rebuilds meshes and recompiles steps against the new world size (an
executable-cache flush, handled in ``shutdown()``).
"""

from __future__ import annotations

import functools
import signal
import threading

from .. import basics
from .. import metrics as _metrics
from ..exceptions import (
    HorovodInternalError,
    HostsUpdatedInterrupt,
    LossSpikeError,
    RecoveryExhaustedError,
    RemovedFromWorldError,
)
from ..utils.env import get_float, get_int
from ..utils.logging import get_logger

# The escalation ladder's rung names, keyed by consecutive no-progress
# failures (these are the `rung` label values of hvd_recoveries_total and
# the journal's `recovery` events — see docs/observability.md).
_RUNGS = {1: "restore", 2: "rendezvous", 3: "peer", 4: "durable"}

# Preemption drain: SIGTERM (the cloud's preemption notice, and the elastic
# driver's first termination signal) flips this event; the NEXT
# ``state.commit()`` — i.e. right after a consistent snapshot — raises
# ``RemovedFromWorldError`` so the worker exits cleanly with EXIT_REMOVED
# instead of being SIGKILLed mid-step with an uncommitted epoch.
_drain = threading.Event()


def drain_requested() -> bool:
    return _drain.is_set()


def _install_drain_handler() -> None:
    """Arm the SIGTERM→drain contract (main thread only; signal module
    refuses handlers elsewhere, and workers embedded in a host process —
    Ray/Spark actors — must not steal its handlers)."""
    if threading.current_thread() is not threading.main_thread():
        return
    log = get_logger()

    def _on_sigterm(signum, frame):
        if not _drain.is_set():
            _drain.set()
            _metrics.event("drain_requested")
            # Preemption postmortem: what this rank was doing when the
            # notice landed (its last K steps' spans) rides the journal
            # alongside drain_requested.
            from .. import tracing

            tracing.dump_flight_record("drain_requested")
            log.info(
                "elastic: SIGTERM (preemption notice) — draining: final "
                "commit, then clean EXIT_REMOVED"
            )

    try:
        signal.signal(signal.SIGTERM, _on_sigterm)
    except (ValueError, OSError):  # exotic host environments: best-effort
        pass


def run(func):
    """Decorator: ``@hvd.elastic.run`` / ``hvd.elastic.run(train)(state, ...)``.

    The wrapped function receives a ``State`` first argument; it is retried
    until it returns, with restore/sync + world re-initialization between
    attempts, mirroring the reference's retry loop.

    Recovery follows an **escalation ladder** keyed on consecutive
    ``HorovodInternalError`` failures with no progress (no commit landed
    in between):

    1. ``restore`` — in-memory ``state.restore()`` to the last commit
       (the cheap, common case — a peer died mid-step);
    2. ``rendezvous`` — full re-rendezvous + ``state.sync()`` from rank
       0, *skipping* the local restore (the local snapshot itself may be
       part of the problem);
    3. ``peer`` — re-materialize from the in-memory peer replica pool
       via :meth:`State.restore_peer` (:mod:`horovod_tpu.peercheck`) when
       armed: the departed ranks' shards are rebuilt from the replicas K
       ring neighbors hold, with zero durable-storage reads. A state
       whose local snapshot provably cannot re-form the world
       (``peer_restore_pending`` — shard-local commits) jumps here
       straight from rung 1, skipping the rank-0 sync that cannot help.
       Any replica gap or checksum mismatch falls through to
    4. ``durable`` — restore via :meth:`State.register_durable_restore`
       (the orbax/pickle checkpoint layer) when registered, else rung 1
       again.

    A :class:`~horovod_tpu.exceptions.LossSpikeError` (the
    ``HOROVOD_LOSS_SPIKE_SIGMA`` detector, raised by
    ``integrity.observe_loss``) takes a dedicated path: a **storage-free
    rewind** to the last commit — the local snapshot, completed through
    the peer rung when the state's commits are shard-local — that never
    climbs the ladder, journals a ``rewind`` event, counts
    ``hvd_rewinds_total{reason="loss_spike"}``, and is bounded by its
    own ``HOROVOD_REWIND_MAX`` storm breaker (past the cap, spikes ride
    the normal ladder). The training loop should consume
    ``integrity.consume_skip_ahead()`` after a rewind so the poison
    batch does not replay.

    A **storm breaker** caps the ladder: after
    ``HOROVOD_RECOVERY_MAX_ATTEMPTS`` consecutive no-progress failures
    (default 10; 0 disables) the loop raises
    :class:`RecoveryExhaustedError` instead of livelocking through
    abort/recover cycles forever, with exponential backoff (capped at
    ``HOROVOD_RECOVERY_BACKOFF_MAX`` seconds) between attempts so a
    flapping host cannot saturate the control plane.

    **Observability** (docs/observability.md): the loop clocks every
    phase into the goodput tracker — world formation + ``sync()`` as
    ``rendezvous`` loss, ``restore()``/durable restore as ``restore``
    loss, the inter-attempt sleep as ``backoff`` loss, time inside
    ``func`` as productive — except a FAILED attempt
    (``HorovodInternalError``: its work rolls back and replays), whose
    doomed tail after the last landed commit books as
    ``failed_attempt`` loss — surfaced in ``hvd.profiler.summary()`` and
    the ``hvd_goodput_*`` scrape counters; and journals every lifecycle
    transition (world_synced, recovery rung, checkpoint fallback,
    hosts_updated, removed_from_world, recovery_exhausted) to
    ``HOROVOD_EVENT_LOG`` with the world generation stamped on each
    record.
    """

    @functools.wraps(func)
    def wrapper(state, *args, **kwargs):
        import sys
        import time  # noqa: F401  (used below)

        import os

        log = get_logger()
        notification_manager.init()
        _install_drain_handler()
        skip_sync = False
        needs_reset = False
        first_init_failure = None
        init_retry_limit_s = float(
            os.environ.get("HOROVOD_ELASTIC_TIMEOUT", "600") or 600
        )
        max_recovery = get_int("HOROVOD_RECOVERY_MAX_ATTEMPTS", 10)
        recovery_backoff_max = get_float("HOROVOD_RECOVERY_BACKOFF_MAX", 5.0)
        consecutive_failures = 0
        consecutive_rewinds = 0
        commits_before_attempt = 0
        goodput = _metrics.goodput()
        _metrics.event("elastic_run_start")

        def _generation() -> int:
            from .. import abort

            return abort.current_generation()

        while True:
            t_attempt = time.perf_counter()
            run_started = None
            # World (re-)formation runs INSIDE the retry scope: init() can
            # itself fail transiently during an elastic reconfiguration
            # (driver mid-publish, KV briefly unreachable) and must retry,
            # not kill the worker. Non-framework exceptions out of init()
            # (e.g. jax.distributed RuntimeError) are wrapped as internal
            # errors; persistent failure past the elastic timeout re-raises.
            try:
                if not basics.is_initialized():
                    try:
                        basics.init()
                    except (HorovodInternalError, HostsUpdatedInterrupt,
                            RemovedFromWorldError):
                        raise
                    except Exception as e:
                        now = time.time()
                        if first_init_failure is None:
                            first_init_failure = now
                        if now - first_init_failure > init_retry_limit_s:
                            log.error(
                                "elastic: re-initialization failing for "
                                ">%ss; giving up", init_retry_limit_s,
                            )
                            raise
                        raise HorovodInternalError(
                            f"world re-initialization failed: {e}"
                        ) from e
                    if needs_reset:
                        state.on_reset()
                        needs_reset = False
                first_init_failure = None
                # A skip-sync host update normally keeps in-memory state,
                # but a state whose layout is world-shaped (the sharded
                # optimizer's stacked shards) must re-shard for the new
                # world regardless — needs_world_sync() flags it.
                if not skip_sync or getattr(
                        state, "needs_world_sync", lambda: False)():
                    state.sync()
                _metrics.event("world_synced", generation=_generation(),
                               np=basics.size(), skip_sync=skip_sync)
                from ..runner.elastic.worker import _counters

                # Snapshot taken AFTER sync (which commits internally):
                # only commits the training function itself lands count as
                # progress for the storm breaker below.
                commits_before_attempt = _counters.commits
                # Formation + sync time is rendezvous loss; from here on
                # the attempt's time is attributed by how it ends: up to
                # the last landed commit is productive; a failed
                # attempt's doomed tail (after its last commit, or the
                # whole attempt when nothing committed) books as
                # lost{cause="failed_attempt"} so the SLO controller
                # optimizes an honest signal.
                goodput.add_lost(
                    "rendezvous", time.perf_counter() - t_attempt)
                run_started = time.perf_counter()
                try:
                    result = func(state, *args, **kwargs)
                except HorovodInternalError:
                    now = time.perf_counter()
                    last_commit = _counters.last_commit_pc
                    if (_counters.commits > commits_before_attempt
                            and last_commit is not None
                            and run_started <= last_commit <= now):
                        goodput.add_productive(last_commit - run_started)
                        goodput.add_lost(
                            "failed_attempt", now - last_commit)
                    else:
                        goodput.add_lost(
                            "failed_attempt", now - run_started)
                    raise
                except BaseException:
                    # Host updates, drain exits, and user exceptions all
                    # end at (or propagate out of) a consistent point:
                    # their in-func time stays productive.
                    goodput.add_productive(
                        time.perf_counter() - run_started)
                    raise
                goodput.add_productive(time.perf_counter() - run_started)
                try:
                    # Completion record: the rc=0 this process is about
                    # to exit with is unreadable to a driver that
                    # ADOPTED it across a crash-restart takeover — the
                    # done record is how success survives (best-effort;
                    # see runner/elastic/worker.announce_done).
                    from ..runner.elastic.worker import announce_done

                    announce_done()
                except Exception:  # noqa: BLE001 — advisory only
                    pass
                return result
            except HorovodInternalError as e:
                from .. import abort, stall
                from ..runner.elastic.worker import _counters

                if run_started is None:
                    # The attempt died during formation/sync: that time
                    # never reached the productive clock — it is
                    # rendezvous loss.
                    goodput.add_lost(
                        "rendezvous", time.perf_counter() - t_attempt)
                # Progress (a commit landed inside the attempt) resets the
                # storm breaker: distinct one-off failures across a long
                # job are routine churn, not a livelock. The rewind storm
                # breaker resets on the same evidence.
                if _counters.commits > commits_before_attempt:
                    consecutive_failures = 0
                    consecutive_rewinds = 0
                consecutive_failures += 1
                # Re-baseline NOW, not only at the next post-sync snapshot:
                # a failure raised before that snapshot (sync itself
                # failing) must compare against this failure's counter, or
                # an earlier attempt's commits would read as fresh progress
                # on every retry and the breaker would never trip.
                commits_before_attempt = _counters.commits
                # This failure consumed any armed coordinated abort, and
                # the inspector's verdict with it — the next attempt gets
                # a clean slate (a re-abort in the NEW world re-arms both).
                abort.consume()
                stall.get_inspector().failed = False
                # Storage-free rewind-on-spike: a LossSpikeError is a
                # VOLUNTARY rollback — the world did not fail, the DATA
                # did. Rewind to the last commit (completed through the
                # peer rung when the state's commits are shard-local)
                # without climbing the escalation ladder, bounded by the
                # HOROVOD_REWIND_MAX storm breaker (a commit landing
                # resets it; past the cap a spike rides the normal
                # ladder like any failure).
                handled_rewind = False
                rewind_cap = None
                if isinstance(e, LossSpikeError):
                    from .. import integrity

                    rewind_cap = integrity.rewind_max()
                if (rewind_cap is not None
                        and (rewind_cap <= 0
                             or consecutive_rewinds < rewind_cap)):
                    from .. import integrity, tracing

                    consecutive_rewinds += 1
                    # Voluntary: not a ladder step, not storm evidence.
                    consecutive_failures -= 1
                    log.warning(
                        "elastic: %s — storage-free rewind to the last "
                        "commit (%d consecutive; "
                        "HOROVOD_REWIND_MAX=%d)",
                        e, consecutive_rewinds, rewind_cap)
                    t_restore = time.perf_counter()
                    rewound = True
                    try:
                        if basics.is_initialized():
                            state.restore()
                        if getattr(state, "peer_restore_pending",
                                   lambda: False)():
                            # Shard-local snapshot: the peer rung is the
                            # storage-free completion of this rewind.
                            rewound = bool(state.restore_peer())
                    except Exception as pe:  # noqa: BLE001
                        log.error(
                            "elastic: spike rewind could not restore "
                            "(%s); falling through to the recovery "
                            "ladder", pe)
                        rewound = False
                    goodput.add_lost(
                        "restore", time.perf_counter() - t_restore)
                    if rewound:
                        handled_rewind = True
                        integrity.record_rewind(
                            "loss_spike", generation=_generation(),
                            consecutive=consecutive_rewinds,
                            detail=str(e))
                        tracing.dump_flight_record(
                            "rewind", generation=_generation())
                    else:
                        consecutive_failures += 1  # ladder after all
                elif rewind_cap is not None:
                    log.error(
                        "elastic: loss-spike rewind storm breaker "
                        "tripped (%d consecutive rewinds with no "
                        "commit; HOROVOD_REWIND_MAX=%d) — escalating "
                        "through the normal recovery ladder",
                        consecutive_rewinds, rewind_cap)
                    _metrics.event(
                        "rewind_storm", generation=_generation(),
                        consecutive=consecutive_rewinds)
                if not handled_rewind:
                    if (max_recovery > 0
                            and consecutive_failures >= max_recovery):
                        log.error(
                            "elastic: %d consecutive recovery attempts "
                            "with no progress "
                            "(HOROVOD_RECOVERY_MAX_ATTEMPTS=%d); "
                            "giving up", consecutive_failures,
                            max_recovery,
                        )
                        _metrics.event(
                            "recovery_exhausted",
                            generation=_generation(),
                            failures=consecutive_failures,
                            error=str(e)[:300])
                        raise RecoveryExhaustedError(
                            f"{consecutive_failures} consecutive recovery "
                            f"attempts failed with no progress (last: {e})"
                        ) from e
                    rung_n = min(consecutive_failures, 4)
                    if rung_n == 2 and getattr(
                            state, "peer_restore_pending", lambda: False)():
                        # The state reports its local snapshot cannot
                        # re-form the world (shard-local commit after a
                        # peer death): rung 2's rank-0 sync cannot help
                        # either — escalate straight to the peer rung.
                        rung_n = 3
                    if rung_n == 3 and not getattr(
                            state, "peer_restore_armed", lambda: False)():
                        rung_n = 4  # no replica plane: durable is next
                    rung = _RUNGS[rung_n]
                    _metrics.RECOVERIES.inc(rung=rung)
                    _metrics.event(
                        "recovery", generation=_generation(), rung=rung,
                        failures=consecutive_failures, error=str(e)[:300])
                    t_restore = time.perf_counter()
                    if rung == "restore":
                        log.warning(
                            "elastic: internal failure (%s); restoring "
                            "last commit (recovery rung 'restore')", e)
                        if basics.is_initialized():
                            state.restore()
                    elif rung == "rendezvous":
                        log.warning(
                            "elastic: internal failure (%s); escalating "
                            "to full re-rendezvous + sync from rank 0, "
                            "skipping local restore (recovery rung "
                            "'rendezvous')", e)
                    else:
                        restored = False
                        if rung == "peer":
                            log.warning(
                                "elastic: internal failure (%s); "
                                "escalating to peer-replica restore "
                                "(recovery rung 'peer')", e)
                            try:
                                restored = state.restore_peer()
                            except Exception as pe:  # noqa: BLE001
                                log.error(
                                    "elastic: peer-replica restore "
                                    "failed (%s); falling through to "
                                    "the durable rung", pe)
                            if restored:
                                # Every storage-free recovery leaves the
                                # same postmortem the durable path
                                # would: the flight record of this
                                # rank's last K steps, replica-pool
                                # state included.
                                from .. import tracing

                                tracing.dump_flight_record(
                                    "peer_restore",
                                    generation=_generation())
                            else:
                                _metrics.event(
                                    "peer_fallback",
                                    generation=_generation())
                                _metrics.RECOVERIES.inc(rung="durable")
                                rung = "durable"
                        if rung == "durable" and not restored:
                            log.warning(
                                "elastic: internal failure (%s); "
                                "escalating to durable checkpoint "
                                "restore (recovery rung 'durable')", e)
                            try:
                                restored = state.restore_durable()
                            except Exception as ce:  # noqa: BLE001
                                log.error(
                                    "elastic: durable restore failed "
                                    "(%s); falling back to the "
                                    "in-memory commit", ce)
                            if not restored:
                                _metrics.event(
                                    "checkpoint_fallback",
                                    generation=_generation(),
                                    durable_restored=False)
                                if basics.is_initialized():
                                    state.restore()
                            else:
                                _metrics.event(
                                    "checkpoint_fallback",
                                    generation=_generation(),
                                    durable_restored=True)
                    goodput.add_lost(
                        "restore", time.perf_counter() - t_restore)
                skip_sync = False
                t_backoff = time.perf_counter()
                time.sleep(min(
                    0.5 * (2 ** (consecutive_failures - 1)),
                    recovery_backoff_max,
                ))
                goodput.add_lost(
                    "backoff", time.perf_counter() - t_backoff)
            except HostsUpdatedInterrupt as e:
                log.info("elastic: hosts updated; re-syncing")
                if run_started is None:
                    # sync() commits internally, and a pending host-change
                    # notification surfaces there: formation time cut
                    # short by the interrupt is still rendezvous loss.
                    goodput.add_lost(
                        "rendezvous", time.perf_counter() - t_attempt)
                _metrics.event("hosts_updated", generation=_generation(),
                               skip_sync=e.skip_sync)
                skip_sync = e.skip_sync
            except RemovedFromWorldError:
                # This host left the world: exit with the driver's sentinel
                # code (not success, not a blacklisting failure).
                from ..runner.elastic.constants import EXIT_REMOVED

                _metrics.event("removed_from_world",
                               generation=_generation())
                log.info("elastic: removed from world; exiting")
                sys.exit(EXIT_REMOVED)
            # Tear down; the next iteration re-forms the world.
            try:
                basics.shutdown()
            except Exception as e:  # keep retrying even if teardown is dirty
                log.warning("elastic: shutdown during reset failed: %s", e)
            needs_reset = True

    return wrapper


class _NotificationManager:
    """Receives host-change notifications from the elastic driver.

    The reference runs a ``WorkerNotificationService`` TCP listener in each
    worker (``horovod/runner/elastic/worker.py``); here the driver pokes a
    file/socket and `handle_hosts_updated` arms an interrupt that surfaces
    as ``HostsUpdatedInterrupt`` at the next ``state.commit()`` /
    ``check_host_updates()`` call.
    """

    def __init__(self):
        self._pending = False
        self._initialized = False

    def init(self):
        self._initialized = True

    def handle_hosts_updated(self):
        self._pending = True

    def check_host_updates(self):
        if self._pending:
            self._pending = False
            raise HostsUpdatedInterrupt()

    def clear(self):
        """Drop a stale notification (the worker already joined the epoch
        the notification was about — e.g. via re-init after a failure)."""
        self._pending = False


notification_manager = _NotificationManager()
