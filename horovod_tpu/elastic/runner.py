"""The elastic retry loop: ``@hvd.elastic.run``.

Parity with the reference's ``horovod/common/elastic.py — run_fn()``
(SURVEY.md §4.4): the decorated training function survives peer
failure/addition by catching the two recovery exceptions:

- ``HorovodInternalError`` (a collective failed — e.g. a TPU VM in the
  slice was preempted mid-step): restore() to the last commit, tear down
  and re-initialize the world, then retry.
- ``HostsUpdatedInterrupt`` (driver says the host set changed, nothing
  failed): keep in-memory state, re-rendezvous, sync, continue.

TPU divergence (by design, SURVEY.md §4.4 "Elastic × ICI topology"): worlds
re-form on valid sub-topologies only — the new device set after re-init is
whatever the re-rendezvous yields; per-chip shrink inside a slice is not a
thing on ICI, so recovery granularity is the host (TPU VM). The re-init path
rebuilds meshes and recompiles steps against the new world size (an
executable-cache flush, handled in ``shutdown()``).
"""

from __future__ import annotations

import functools
import signal
import threading

from .. import basics
from ..exceptions import (
    HorovodInternalError,
    HostsUpdatedInterrupt,
    RemovedFromWorldError,
)
from ..utils.logging import get_logger

# Preemption drain: SIGTERM (the cloud's preemption notice, and the elastic
# driver's first termination signal) flips this event; the NEXT
# ``state.commit()`` — i.e. right after a consistent snapshot — raises
# ``RemovedFromWorldError`` so the worker exits cleanly with EXIT_REMOVED
# instead of being SIGKILLed mid-step with an uncommitted epoch.
_drain = threading.Event()


def drain_requested() -> bool:
    return _drain.is_set()


def _install_drain_handler() -> None:
    """Arm the SIGTERM→drain contract (main thread only; signal module
    refuses handlers elsewhere, and workers embedded in a host process —
    Ray/Spark actors — must not steal its handlers)."""
    if threading.current_thread() is not threading.main_thread():
        return
    log = get_logger()

    def _on_sigterm(signum, frame):
        if not _drain.is_set():
            _drain.set()
            log.info(
                "elastic: SIGTERM (preemption notice) — draining: final "
                "commit, then clean EXIT_REMOVED"
            )

    try:
        signal.signal(signal.SIGTERM, _on_sigterm)
    except (ValueError, OSError):  # exotic host environments: best-effort
        pass


def run(func):
    """Decorator: ``@hvd.elastic.run`` / ``hvd.elastic.run(train)(state, ...)``.

    The wrapped function receives a ``State`` first argument; it is retried
    until it returns, with restore/sync + world re-initialization between
    attempts, mirroring the reference's retry loop.
    """

    @functools.wraps(func)
    def wrapper(state, *args, **kwargs):
        import sys
        import time  # noqa: F401  (used below)

        import os

        log = get_logger()
        notification_manager.init()
        _install_drain_handler()
        skip_sync = False
        needs_reset = False
        backoff = 0.5
        first_init_failure = None
        init_retry_limit_s = float(
            os.environ.get("HOROVOD_ELASTIC_TIMEOUT", "600") or 600
        )
        while True:
            # World (re-)formation runs INSIDE the retry scope: init() can
            # itself fail transiently during an elastic reconfiguration
            # (driver mid-publish, KV briefly unreachable) and must retry,
            # not kill the worker. Non-framework exceptions out of init()
            # (e.g. jax.distributed RuntimeError) are wrapped as internal
            # errors; persistent failure past the elastic timeout re-raises.
            try:
                if not basics.is_initialized():
                    try:
                        basics.init()
                    except (HorovodInternalError, HostsUpdatedInterrupt,
                            RemovedFromWorldError):
                        raise
                    except Exception as e:
                        now = time.time()
                        if first_init_failure is None:
                            first_init_failure = now
                        if now - first_init_failure > init_retry_limit_s:
                            log.error(
                                "elastic: re-initialization failing for "
                                ">%ss; giving up", init_retry_limit_s,
                            )
                            raise
                        raise HorovodInternalError(
                            f"world re-initialization failed: {e}"
                        ) from e
                    if needs_reset:
                        state.on_reset()
                        needs_reset = False
                first_init_failure = None
                backoff = 0.5
                if not skip_sync:
                    state.sync()
                return func(state, *args, **kwargs)
            except HorovodInternalError as e:
                log.warning("elastic: internal failure (%s); restoring", e)
                if basics.is_initialized():
                    state.restore()
                skip_sync = False
                time.sleep(min(backoff, 5.0))
                backoff *= 2
            except HostsUpdatedInterrupt as e:
                log.info("elastic: hosts updated; re-syncing")
                skip_sync = e.skip_sync
            except RemovedFromWorldError:
                # This host left the world: exit with the driver's sentinel
                # code (not success, not a blacklisting failure).
                from ..runner.elastic.constants import EXIT_REMOVED

                log.info("elastic: removed from world; exiting")
                sys.exit(EXIT_REMOVED)
            # Tear down; the next iteration re-forms the world.
            try:
                basics.shutdown()
            except Exception as e:  # keep retrying even if teardown is dirty
                log.warning("elastic: shutdown during reset failed: %s", e)
            needs_reset = True

    return wrapper


class _NotificationManager:
    """Receives host-change notifications from the elastic driver.

    The reference runs a ``WorkerNotificationService`` TCP listener in each
    worker (``horovod/runner/elastic/worker.py``); here the driver pokes a
    file/socket and `handle_hosts_updated` arms an interrupt that surfaces
    as ``HostsUpdatedInterrupt`` at the next ``state.commit()`` /
    ``check_host_updates()`` call.
    """

    def __init__(self):
        self._pending = False
        self._initialized = False

    def init(self):
        self._initialized = True

    def handle_hosts_updated(self):
        self._pending = True

    def check_host_updates(self):
        if self._pending:
            self._pending = False
            raise HostsUpdatedInterrupt()

    def clear(self):
        """Drop a stale notification (the worker already joined the epoch
        the notification was about — e.g. via re-init after a failure)."""
        self._pending = False


notification_manager = _NotificationManager()
